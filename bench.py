"""Headline benchmark: ViT-Large images/sec on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: the reference's best in-repo single-device ViT-Large number —
0.22 img/s on RCC-VE-C2000 at batch=8 (BASELINE.md, README_Scheduler.md:213-239).

Method: microbatches are streamed through the model inside ONE jitted
`lax.scan` program (the single-stage degenerate of the SPMD pipeline), inputs
device-resident, and a scalar reduction of the logits is read back to fence
execution — `block_until_ready` alone does not fence on the tunneled axon
platform.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMG_PER_SEC = 0.22  # ViT-Large b=8 on RCC-VE-C2000 (BASELINE.md)


def main():
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.models.shard import make_shard_fn

    name = "google/vit-large-patch16-224"
    entry = registry.get_model_entry(name)
    cfg = entry.config
    shard_cfg = registry.make_shard_config(name, 1, registry.get_model_layers(name))
    params = entry.family.init_params(cfg, shard_cfg, dtype=jnp.bfloat16)
    fn = make_shard_fn(entry.family.FAMILY, cfg, shard_cfg)

    batch = 8   # reference profiles use batch=8 (README_Scheduler.md:148-151)
    n_ubatch = 32
    rng = np.random.default_rng(0)
    xs = jax.device_put(jnp.asarray(
        rng.normal(size=(n_ubatch, batch, 3, 224, 224)), dtype=jnp.bfloat16))
    params = jax.device_put(params)

    @jax.jit
    def run_all(p, xs):
        def step(carry, x):
            logits = fn(p, x)
            return carry + jnp.sum(logits.astype(jnp.float32)), None

        total, _ = jax.lax.scan(step, jnp.float32(0), xs)
        return total

    float(run_all(params, xs))  # compile + warmup (readback fences)
    best = float("inf")
    for _ in range(3):
        tik = time.monotonic()
        float(run_all(params, xs))
        best = min(best, time.monotonic() - tik)
    img_per_sec = n_ubatch * batch / best

    print(json.dumps({
        "metric": "vit_large_images_per_sec_b8",
        "value": round(img_per_sec, 3),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 1),
    }))


if __name__ == "__main__":
    main()
