"""Benchmark observatory CLI: run one scenario recipe, print ONE JSON line.

`bench.py` is now a thin dispatcher over the `pipeedge_tpu/benchkit/`
recipe registry (docs/PERF.md has the catalog and the trajectory-record
schema). The default recipe is `exact` — the historical ViT-Large
headline — so a bare `python bench.py` still produces the BENCH record
it always did (same keys, now inside the schema-versioned envelope every
recipe shares: scenario, config fingerprint, environment stamp,
noise-banded throughput block).

Usage:
    python bench.py                        # the exact headline (ViT-L b8)
    python bench.py --recipe serve         # goodput bench at 3x overload
    python bench.py --recipe quant_collectives --model ... --ubatches 8
    python bench.py --list-recipes
    python bench.py --recipe exact -- --help        # recipe flags
    python bench.py --recipe serve --append-record BENCH_r06.json

`--append-record FILE` additionally folds the record into a
multi-scenario artifact (one record per scenario, newest wins) — how a
BENCH_r0N.json re-arms per PR. `tools/bench_report.py` diffs two such
artifacts (or single records) with per-metric noise bands and gates CI.
"""
import argparse
import json
import sys


def main() -> int:
    from pipeedge_tpu import benchkit
    from pipeedge_tpu.benchkit import schema

    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0], add_help=False)
    p.add_argument("-h", "--help", action="store_true")
    p.add_argument("--recipe", default="exact",
                   help="scenario recipe to run (--list-recipes)")
    p.add_argument("--list-recipes", action="store_true",
                   help="print the recipe catalog and exit")
    p.add_argument("--append-record", metavar="FILE", default=None,
                   help="also fold the record into the multi-scenario "
                        "artifact at FILE (created when missing)")
    p.add_argument("--notes", default=None,
                   help="free-form provenance appended to the record's "
                        "notes field (e.g. the r05->r06 gap note)")
    p.add_argument("--scenario-suffix", metavar="TAG", default=None,
                   help="record the run as scenario RECIPE@TAG so A/B "
                        "arms of one recipe coexist in a multi-scenario "
                        "artifact (artifact_append keys on scenario — "
                        "without a suffix the second arm replaces the "
                        "first)")
    args, rest = p.parse_known_args()
    if rest and rest[0] == "--":
        rest = rest[1:]         # `bench.py --recipe X -- <recipe flags>`

    if args.list_recipes:
        for recipe in benchkit.list_recipes():
            print(f"{recipe.name:18s} [{recipe.tier:5s}] {recipe.help}")
        return 0
    if args.help:
        recipe_given = any(a == "--recipe" or a.startswith("--recipe=")
                           for a in sys.argv[1:])
        if recipe_given:
            rest = ["--help"]   # delegate to the recipe's own parser
        else:
            p.print_help()
            return 0

    record = benchkit.run_recipe(args.recipe, rest, notes=args.notes)
    if args.scenario_suffix:
        record["scenario"] = f"{args.recipe}@{args.scenario_suffix}"
    problems = schema.validate_record(record)
    if problems:
        # a recipe that emits an invalid record is a bug, not a bench
        # result — fail loudly instead of committing a corrupt line
        print(f"bench.py: invalid record: {problems}", file=sys.stderr)
        return 2
    if args.append_record:
        schema.artifact_append(args.append_record, record)
    print(json.dumps(record, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
