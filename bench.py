"""Headline benchmark: ViT-Large images/sec on the available TPU chip(s).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Baseline: the reference's best in-repo single-device ViT-Large number —
0.22 img/s on RCC-VE-C2000 at batch=8 (BASELINE.md, README_Scheduler.md:213-239).

Reported extras (BASELINE.md north-star metric definition):
- p50_microbatch_latency_ms: median per-microbatch latency, measured as
  t(result readback) - t(enqueue) for individually dispatched microbatches
  (the reference's latency method, runtime.py:493-505, per microbatch).
  Includes one host<->device round trip — on the tunneled axon platform
  that round trip is tens of ms; steady_state_ubatch_ms carries the
  throughput-derived per-microbatch time for comparison.
- mfu: achieved model FLOP/s over a peak calibrated at bench start by
  timing chained 8192^3 bf16 matmuls (2*M*N*K FLOPs convention throughout).

Method: microbatches are streamed through the model inside ONE jitted
`lax.scan` program (the single-stage degenerate of the SPMD pipeline), inputs
device-resident, and a scalar reduction of the logits is read back to fence
execution — `block_until_ready` alone does not fence on the tunneled axon
platform. Blocks run unrolled (registry.should_unroll_blocks): measured ~6%
over the scanned layout on this model (see models/shard.py).

Statistics: the throughput loop runs REPS timed repetitions; the headline
`value` is the MEDIAN img/s, with min/max spread and raw per-rep samples in
the JSON so session-to-session drift (measured 750–943 img/s across tunnel
sessions, docs/PERF.md) is visible inside one record. MFU is reported
against BOTH denominators: the session-calibrated peak (chained 8192³ bf16
matmuls) and the platform's nominal bf16 spec when the device kind is known.
"""
import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_IMG_PER_SEC = 0.22  # ViT-Large b=8 on RCC-VE-C2000 (BASELINE.md)

REPS = 5  # timed repetitions of the streaming loop (median reported)

# Nominal dense bf16 peak FLOP/s by device kind (public TPU spec sheets).
# Used as the second MFU denominator; absent kinds report null.
NOMINAL_BF16_PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


# The PINNED peak-TFLOP calibration recipe (round-5 verdict item 7).
# Version it; never change a field without bumping `version` — the MFU
# denominators of different BENCH records are only comparable within one
# recipe version. Per-session spread is recorded alongside every result
# so the ±% error bars on calibrated MFU are explicit in the record.
CALIBRATION_RECIPE = {
    "version": "cal-v1",
    "matmul_mnk": [8192, 8192, 8192],
    "chain_length": 32,
    "dtype": "bfloat16",
    "accumulate": "float32",
    "protocol": "one jitted lax.scan chain; 1 compile+warm call, then "
                "3 timed reps fenced by scalar readback; peak = best "
                "rep, spread = all reps",
}


def _calibrate_peak_samples() -> list:
    """Per-rep implied bf16 FLOP/s (2*M*N*K) under CALIBRATION_RECIPE;
    the chain amortizes dispatch/tunnel latency out of the measurement.
    max(samples) is the session peak; the spread IS the error bar on
    every calibrated-MFU number this session."""
    m = CALIBRATION_RECIPE["matmul_mnk"][0]
    k_iters = CALIBRATION_RECIPE["chain_length"]
    a = jnp.ones((m, m), jnp.bfloat16)
    b = jnp.ones((m, m), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        def step(c, _):
            y = jnp.dot(c, b, preferred_element_type=jnp.float32)
            return y.astype(jnp.bfloat16) * 1e-4, None

        out, _ = jax.lax.scan(step, a, None, length=k_iters)
        return jnp.sum(out.astype(jnp.float32))

    float(mm(a, b))  # compile + warm
    samples = []
    for _ in range(3):
        tik = time.monotonic()
        float(mm(a, b))
        samples.append(2 * k_iters * m**3 / (time.monotonic() - tik))
    return samples


def _calibrate_peak_flops() -> float:
    return max(_calibrate_peak_samples())


def _model_flops_per_image(cfg) -> float:
    """Analytic ViT forward FLOPs per image (2*MAC convention)."""
    s = cfg.num_patches + 1
    d, i, l = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
    per_block = 8 * s * d * d + 4 * s * s * d + 4 * s * d * i
    embed = 2 * s * (cfg.patch_size**2 * cfg.num_channels) * d
    head = 2 * d * max(cfg.num_labels, 1)
    return l * per_block + embed + head


def main():
    from pipeedge_tpu.models import registry
    from pipeedge_tpu.models.layers import set_fast_numerics
    from pipeedge_tpu.utils import require_live_backend

    # Pin exact numerics for the headline/calibration passes BEFORE any
    # trace: an inherited PIPEEDGE_FAST_NUMERICS=1 would otherwise compile
    # the "exact" side of the A/B in fast mode too, reporting a ~1.0
    # speedup while claiming exact-parity numerics (ADVICE.md r5).
    set_fast_numerics(False)

    # lease-neutral wedge diagnostic (shared with bench_decode.py)
    require_live_backend("vit_large_images_per_sec_b8", unit="images/sec")
    name = "google/vit-large-patch16-224"
    cfg = registry.get_model_entry(name).config
    fn, params, _ = registry.module_shard_factory(
        name, None, 1, registry.get_model_layers(name), dtype=jnp.bfloat16)

    batch = 8   # reference profiles use batch=8 (README_Scheduler.md:148-151)
    # 128 microbatches amortize the fixed per-dispatch overhead (~65 ms on
    # the tunneled axon platform) to <6% of the run; input set = 385 MB HBM
    n_ubatch = 128
    rng = np.random.default_rng(0)
    xs = jax.device_put(jnp.asarray(
        rng.normal(size=(n_ubatch, batch, 3, 224, 224)), dtype=jnp.bfloat16))
    params = jax.device_put(params)

    cal_samples = _calibrate_peak_samples()
    peak_flops = max(cal_samples)

    # the UN-jitted shard apply: the factory's fn is jitted, and jit
    # caches by function identity — a numerics-mode change (trace-time
    # flag) only binds through a fresh trace of the raw callable
    raw_fn = fn.__wrapped__

    def make_run_all():
        # a FRESH jit wrapper (and fresh inner trace via raw_fn) per
        # numerics mode
        @jax.jit
        def run_all(p, xs):
            def step(carry, x):
                logits = raw_fn(p, x)
                return carry + jnp.sum(logits.astype(jnp.float32)), None

            total, _ = jax.lax.scan(step, jnp.float32(0), xs)
            return total

        return run_all

    run_all = make_run_all()

    # Host-side energy (reference's energy-first monitoring demo,
    # monitoring/__init__.py:110-114 there): RAPL powercap when readable,
    # else an explicit unreadable record — never silent omission.
    from pipeedge_tpu.monitoring.energy import default_energy_source
    energy_src = default_energy_source()
    if energy_src is not None:
        energy_src.init()

    float(run_all(params, xs))  # compile + warmup (readback fences)
    e0 = energy_src.get_uj() if energy_src is not None else 0
    times = []
    for _ in range(REPS):
        tik = time.monotonic()
        float(run_all(params, xs))
        times.append(time.monotonic() - tik)
    e1 = energy_src.get_uj() if energy_src is not None else 0
    samples = sorted(n_ubatch * batch / t for t in times)
    img_per_sec = statistics.median(samples)
    if energy_src is not None:
        wall = sum(times)
        energy_fields = {
            "host_energy_j_per_image": round(
                (e1 - e0) / 1e6 / (REPS * n_ubatch * batch), 4),
            "host_power_w": round((e1 - e0) / 1e6 / wall, 1),
            "energy_source": "rapl-powercap (host CPU packages; TPU chip "
                             "power not exposed through JAX)",
        }
        energy_src.finish()
    else:
        energy_fields = {
            "energy_source": "unreadable on this host (no readable RAPL "
                             "powercap domains)"}

    # p50 microbatch latency: individual dispatch, fenced per microbatch.
    # Segmented (dispatch = host enqueue of the jitted call, transfer =
    # device execution + readiness fence, emit = host scalar readback)
    # through telemetry spans so the medians come out of the same span
    # machinery the DCN trace reports use — the per-segment view of
    # where the steady-vs-p50 gap lives (ROADMAP item 5).
    from pipeedge_tpu import telemetry
    from pipeedge_tpu.telemetry import report as span_report

    @jax.jit
    def run_one(p, x):
        return jnp.sum(fn(p, x).astype(jnp.float32))

    float(run_one(params, xs[0]))  # compile + warm
    rec = telemetry.configure(rank=0)
    lats = []
    for i in range(n_ubatch):
        tik = time.monotonic()
        with telemetry.span("stage", "dispatch", mb=i):
            fut = run_one(params, xs[i])
        with telemetry.span("stage", "transfer", mb=i):
            fut.block_until_ready()
        with telemetry.span("stage", "emit", mb=i):
            float(fut)
        lats.append(time.monotonic() - tik)
    segments = span_report.segment_medians(rec.snapshot(),
                                           cats=frozenset(("stage",)))
    telemetry.disable()
    p50_ms = statistics.median(lats) * 1e3
    steady_lats = sorted(lats[1:])
    latency_breakdown = {
        # first measured microbatch vs the warm rest: the fill/steady
        # split BENCH rounds track against steady_state_ubatch_ms
        "fill_ms": round(lats[0] * 1e3, 2),
        "steady_p50_ms": round(
            span_report._percentile(steady_lats, 50) * 1e3, 2),
        "steady_p99_ms": round(
            span_report._percentile(steady_lats, 99) * 1e3, 2),
        "segments_p50_ms": {
            key.split("/", 1)[1]: val["p50_ms"]
            for key, val in segments.items()},
    }

    flops_img = _model_flops_per_image(cfg)
    achieved = img_per_sec * flops_img

    device_kind = jax.devices()[0].device_kind
    nominal_peak = NOMINAL_BF16_PEAK.get(device_kind)

    # fast-numerics headline (round-5 verdict item 1): the SAME streamed
    # loop with model-dtype LayerNorm/softmax and tanh GeLU — the
    # measured buy-back of the f32-numerics parity bucket, plus the
    # measured accuracy delta vs the exact mode on this input set
    # fresh lambdas over raw_fn per mode: jit caches by function
    # identity, so the trace-time numerics flag needs a new function
    # object (and no stale inner jit) to rebind
    logits_exact = np.asarray(
        jax.jit(lambda p, x: raw_fn(p, x))(params,
                                           xs[0]).astype(jnp.float32))
    set_fast_numerics(True)
    try:
        run_all_fast = make_run_all()
        float(run_all_fast(params, xs))          # compile + warm
        # INTERLEAVED exact/fast rounds (the docs/PERF.md A/B timing
        # discipline): session drift hits both modes equally, so the
        # reported speedup is a same-moment quotient, not early-session
        # exact vs late-session fast
        fast_times, exact_times = [], []
        for _ in range(3):
            tik = time.monotonic()
            float(run_all(params, xs))
            exact_times.append(time.monotonic() - tik)
            tik = time.monotonic()
            float(run_all_fast(params, xs))
            fast_times.append(time.monotonic() - tik)
        fast_img_per_sec = statistics.median(
            n_ubatch * batch / t for t in fast_times)
        exact_adjacent = statistics.median(
            n_ubatch * batch / t for t in exact_times)
        logits_fast = np.asarray(
            jax.jit(lambda p, x: raw_fn(p, x))(params,
                                               xs[0]).astype(jnp.float32))
    finally:
        # None would re-defer to the env var — this bench's records must
        # stay exact-mode regardless of the inherited environment
        set_fast_numerics(False)
    fast_achieved = fast_img_per_sec * flops_img
    top1_agree = float(np.mean(np.argmax(logits_exact, -1)
                               == np.argmax(logits_fast, -1)))
    fast_fields = {
        "images_per_sec": round(fast_img_per_sec, 3),
        "exact_interleaved_images_per_sec": round(exact_adjacent, 3),
        "speedup_vs_exact": round(fast_img_per_sec / exact_adjacent, 3),
        "mfu_calibrated": round(fast_achieved / peak_flops, 3),
        "mfu_nominal": (round(fast_achieved / nominal_peak, 3)
                        if nominal_peak else None),
        "achieved_tflops": round(fast_achieved / 1e12, 1),
        "top1_agreement_vs_exact": round(top1_agree, 4),
        "max_abs_logit_delta": round(
            float(np.max(np.abs(logits_exact - logits_fast))), 4),
    }

    print(json.dumps({
        "metric": "vit_large_images_per_sec_b8",
        "value": round(img_per_sec, 3),
        "unit": "images/sec",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC, 1),
        "value_median": round(img_per_sec, 3),
        "value_spread": [round(samples[0], 3), round(samples[-1], 3)],
        "value_samples": [round(s, 3) for s in samples],
        "p50_microbatch_latency_ms": round(p50_ms, 2),
        "latency_breakdown": latency_breakdown,
        "steady_state_ubatch_ms": round(min(times) / n_ubatch * 1e3, 2),
        "mfu": round(achieved / peak_flops, 3),
        "mfu_calibrated": round(achieved / peak_flops, 3),
        "mfu_nominal": (round(achieved / nominal_peak, 3)
                        if nominal_peak else None),
        "achieved_tflops": round(achieved / 1e12, 1),
        # both names kept: calibrated_peak_tflops is the original record
        # key (BENCH_r01), peak_calibrated_tflops pairs with peak_nominal
        "calibrated_peak_tflops": round(peak_flops / 1e12, 1),
        "peak_calibrated_tflops": round(peak_flops / 1e12, 1),
        "peak_nominal_tflops": (round(nominal_peak / 1e12, 1)
                                if nominal_peak else None),
        # pinned calibration recipe + per-session spread (verdict item
        # 7): calibrated MFU carries explicit error bars
        "calibration": dict(
            CALIBRATION_RECIPE,
            session_samples_tflops=[round(s / 1e12, 1)
                                    for s in cal_samples],
            calibration_spread=[round(min(cal_samples) / 1e12, 1),
                                round(max(cal_samples) / 1e12, 1)]),
        "mfu_calibrated_range": [
            round(achieved / max(cal_samples), 3),
            round(achieved / min(cal_samples), 3)],
        "fast_numerics": fast_fields,
        "device_kind": device_kind,
        **energy_fields,
    }))


if __name__ == "__main__":
    main()
