"""CLI: project a profiler results file into a scheduler models.yml.

Thin shim over pipeedge_tpu.sched.profiles (role parity with the
reference's profiler_results_to_models.py; same flags, same output format).
"""
import argparse
import sys

from pipeedge_tpu.models import registry
from pipeedge_tpu.sched import profiles


def main():
    parser = argparse.ArgumentParser(
        description="Produce scheduler-compatible models YAML file from "
                    "profiling results",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-i", "--results-yml", default="profiler_results.yml",
                        help="profiler results input YAML file")
    parser.add_argument("-o", "--models-yml", default="models.yml",
                        help="models output YAML file")
    parser.add_argument("-f", "--overwrite", action="store_true",
                        help="overwrite existing YAML model entries")
    args = parser.parse_args()

    try:
        results = profiles.ProfilerResults.load(
            args.results_yml, known_layer_counts=registry.get_model_layers)
        profiles.upsert_model(args.models_yml, results,
                              overwrite=args.overwrite)
    except profiles.ProfileError as exc:
        print(exc)
        sys.exit(1)


if __name__ == "__main__":
    main()
