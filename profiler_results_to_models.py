"""Convert profiler results into a scheduler-compatible models YAML file.

Parity with /root/reference/profiler_results_to_models.py: parameters_in/out
derived from recorded payload shapes (sum over the tuple payload of per-item
element counts), mem_MB straight from the profile.
"""
import argparse
import sys

import numpy as np
import yaml

from pipeedge_tpu.models import registry
from pipeedge_tpu.sched import yaml_files, yaml_types


def save_models_yml(file, model_name, num_layers, parameters_in,
                    parameters_out, mem, overwrite_model=False) -> bool:
    """Save/extend a models YAML file; refuses to overwrite unless asked."""
    models = yaml_files.yaml_models_load(file)
    if model_name in models and not overwrite_model:
        print(f"Model already exists: {file}: {model_name}")
        return False
    models[model_name] = yaml_types.yaml_model(num_layers, parameters_in,
                                               parameters_out, mem)
    yaml_files.yaml_save(models, file)
    return True


def main():
    parser = argparse.ArgumentParser(
        description="Produce scheduler-compatible models YAML file from "
                    "profiling results",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-i", "--results-yml", type=str,
                        default="profiler_results.yml",
                        help="profiler results input YAML file")
    parser.add_argument("-o", "--models-yml", type=str, default="models.yml",
                        help="models output YAML file")
    parser.add_argument("-f", "--overwrite", action="store_true",
                        help="overwrite existing YAML model entries")
    args = parser.parse_args()

    with open(args.results_yml, "r", encoding="utf-8") as yfile:
        results = yaml.safe_load(yfile)

    layers = results["layers"]
    model_name = results["model_name"]
    profile_data = results["profile_data"]
    if model_name in registry.get_model_names():
        exp_layers = registry.get_model_layers(model_name)
        if layers != exp_layers:
            print(f"Warning: expected and actual layer counts differ: "
                  f"{exp_layers} != {layers}")
    else:
        print(f"Warning: cannot verify layer count for unknown model: "
              f"{model_name}: {layers}")
    if layers != len(profile_data):
        print(f"Declared layer count does not match profile data count: "
              f"{layers} != {len(profile_data)}")
        sys.exit(1)
    if not profile_data:
        print("Empty profile data!")
        sys.exit(1)

    parameters_in = int(sum(np.prod(s) for s in profile_data[0]["shape_in"]))
    parameters_out = [int(sum(np.prod(s) for s in r["shape_out"]))
                      for r in profile_data]
    mem = [r["memory"] for r in profile_data]
    if not save_models_yml(args.models_yml, model_name, layers, parameters_in,
                           parameters_out, mem, overwrite_model=args.overwrite):
        sys.exit(1)


if __name__ == "__main__":
    main()
