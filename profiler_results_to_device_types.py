"""Convert profiler results into a scheduler-compatible device types YAML file.

Parity with /root/reference/profiler_results_to_device_types.py: appends a
(dtype, batch_size)-keyed model profile to a named device type, creating the
type (with required memory/bandwidth args) if new.
"""
import argparse
import sys

import yaml

from pipeedge_tpu import sched
from pipeedge_tpu.models import registry
from pipeedge_tpu.sched import yaml_files, yaml_types


def is_dev_type_compatible(device_types, dev_type_name, mem, bwdth) -> bool:
    """Existing device type properties must not silently change."""
    if mem is not None and device_types[dev_type_name]["mem_MB"] != mem:
        print(f"Device type memory mismatch: "
              f"{device_types[dev_type_name]['mem_MB']} != {mem}")
        return False
    if bwdth is not None and device_types[dev_type_name]["bw_Mbps"] != bwdth:
        print(f"Device type bandwidth mismatch: "
              f"{device_types[dev_type_name]['bw_Mbps']} != {bwdth}")
        return False
    return True


def is_model_profile_match(model_profile, dtype, batch_size) -> bool:
    """dtype+batch_size is the unique profile key ('float32' and
    'torch.float32' are the same key — both schedulers normalize)."""
    return sched.normalize_dtype(model_profile["dtype"]) == \
        sched.normalize_dtype(dtype) and \
        model_profile["batch_size"] == batch_size


def save_device_types_yml(file, dev_type_name, mem, bwdth, model_name, dtype,
                          batch_size, time_s, overwrite_model=False) -> bool:
    """Save/extend a device types YAML file."""
    device_types = yaml_files.yaml_device_types_load(file)
    if dev_type_name in device_types:
        if not is_dev_type_compatible(device_types, dev_type_name, mem, bwdth):
            return False
    else:
        if mem is None:
            print("New device type: must specify memory argument")
            return False
        if bwdth is None:
            print("New device type: must specify bandwidth argument")
            return False
        device_types[dev_type_name] = yaml_types.yaml_device_type(mem, bwdth, {})

    if device_types[dev_type_name]["model_profiles"] is None:
        device_types[dev_type_name]["model_profiles"] = {}
    model_profiles = device_types[dev_type_name]["model_profiles"]

    ymp = yaml_types.yaml_model_profile(dtype, batch_size, time_s)
    if model_name not in model_profiles:
        model_profiles[model_name] = []
    updated_in_place = False
    for idx, model_profile in enumerate(model_profiles[model_name]):
        if is_model_profile_match(model_profile, dtype, batch_size):
            if overwrite_model:
                print(f"Overwriting existing model profile: {file}: "
                      f"{dev_type_name}: {model_name}: {model_profile}")
                model_profiles[model_name][idx] = ymp
                updated_in_place = True
            else:
                print(f"Model profile already exists: {file}: {dev_type_name}: "
                      f"{model_name}: {model_profile}")
                return False
    if not updated_in_place:
        model_profiles[model_name].append(ymp)

    yaml_files.yaml_save(device_types, file)
    return True


def main():
    parser = argparse.ArgumentParser(
        description="Produce scheduler-compatible device types YAML file from "
                    "profiling results",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("dev_type", type=str, help="device type name")
    parser.add_argument("-i", "--results-yml", type=str,
                        default="profiler_results.yml",
                        help="profiler results input YAML file")
    parser.add_argument("-o", "--dev-types-yml", type=str,
                        default="device_types.yml",
                        help="device types output YAML file")
    parser.add_argument("-dtm", "--dev-type-mem", type=int,
                        help="memory in MB (required if not already in "
                             "DEV_TYPES_YML)")
    parser.add_argument("-dtb", "--dev-type-bw", type=int,
                        help="bandwidth in Mbps (required if not already in "
                             "DEV_TYPES_YML)")
    parser.add_argument("-f", "--overwrite", action="store_true",
                        help="overwrite existing YAML device type model "
                             "profile entries")
    args = parser.parse_args()

    with open(args.results_yml, "r", encoding="utf-8") as yfile:
        results = yaml.safe_load(yfile)

    batch_size = results["batch_size"]
    dtype = results["dtype"]
    layers = results["layers"]
    model_name = results["model_name"]
    profile_data = results["profile_data"]
    if model_name in registry.get_model_names():
        exp_layers = registry.get_model_layers(model_name)
        if layers != exp_layers:
            print(f"Warning: expected and actual layer counts differ: "
                  f"{exp_layers} != {layers}")
    else:
        print(f"Warning: cannot verify layer count for unknown model: "
              f"{model_name}: {layers}")
    if layers != len(profile_data):
        print(f"Declared layer count does not match profile data count: "
              f"{layers} != {len(profile_data)}")
        sys.exit(1)
    time_s = [r["time"] for r in profile_data]
    if not save_device_types_yml(args.dev_types_yml, args.dev_type,
                                 args.dev_type_mem, args.dev_type_bw,
                                 model_name, dtype, batch_size, time_s,
                                 overwrite_model=args.overwrite):
        sys.exit(1)


if __name__ == "__main__":
    main()
