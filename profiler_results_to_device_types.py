"""CLI: merge a profiler results file into a scheduler device_types.yml.

Thin shim over pipeedge_tpu.sched.profiles (role parity with the
reference's profiler_results_to_device_types.py; same flags, same output
format — the (dtype, batch_size) pair keys a device type's model profiles).
"""
import argparse
import sys

from pipeedge_tpu.models import registry
from pipeedge_tpu.sched import profiles


def main():
    parser = argparse.ArgumentParser(
        description="Produce scheduler-compatible device types YAML file "
                    "from profiling results",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("dev_type", help="device type name")
    parser.add_argument("-i", "--results-yml", default="profiler_results.yml",
                        help="profiler results input YAML file")
    parser.add_argument("-o", "--dev-types-yml", default="device_types.yml",
                        help="device types output YAML file")
    parser.add_argument("-dtm", "--dev-type-mem", type=int,
                        help="memory in MB (required if not already in "
                             "DEV_TYPES_YML)")
    parser.add_argument("-dtb", "--dev-type-bw", type=int,
                        help="bandwidth in Mbps (required if not already in "
                             "DEV_TYPES_YML)")
    parser.add_argument("-f", "--overwrite", action="store_true",
                        help="overwrite existing YAML device type model "
                             "profile entries")
    args = parser.parse_args()

    try:
        results = profiles.ProfilerResults.load(
            args.results_yml, known_layer_counts=registry.get_model_layers)
        profiles.upsert_device_type(
            args.dev_types_yml, args.dev_type, results,
            mem_MB=args.dev_type_mem, bw_Mbps=args.dev_type_bw,
            overwrite=args.overwrite)
    except profiles.ProfileError as exc:
        print(exc)
        sys.exit(1)


if __name__ == "__main__":
    main()
