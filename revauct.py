"""Pipeline Reverse Auction Scheduler CLI.

Parity with /root/reference/revauct.py: every device bids its feasible shards
(from its profiles) and neighbor bandwidths; the auctioneer filters/orders
bids and runs a latency-, throughput-, or host-count-optimizing scheduler,
printing the 1-indexed schedule YAML.

Two fan-out modes:

- ``--comm local`` (single controller): all device configs
  (device_types.yml + devices.yml + device_neighbors_world.yml) are local,
  so bids are gathered with a thread pool — same fan-out/fan-in shape as the
  reference's RPC, no network bring-up. Chips/hosts in the YAML play the
  role of ranks.
- ``--comm dcn`` (distributed): one process per rank, exactly the
  reference's deployment (revauct.py:168-180) — the auctioneer (rank 0)
  broadcasts a CMD_BID over the DCN command plane; every rank computes its
  bid from its OWN local profile files (`--dev-type`/`--host` identify the
  bidder, reference _DEVICE_CFG at revauct.py:147-152) and replies on the
  transport's BIDS channel. The auctioneer never needs the other ranks'
  device_types files.
"""
import argparse
import json
import logging
import queue
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Tuple

import numpy as np
import yaml

from pipeedge_tpu import sched
from pipeedge_tpu.models import registry
from pipeedge_tpu.sched import revauct, yaml_files

logger = logging.getLogger(__name__)

# the auction's profile dtype key (reference revauct.py fixes this too)
DTYPE = 'torch.float32'


def _find_profiles(yml_models, yml_dev_types, dev_type, model: str,
                   ubatch_size: int, dtype: str) -> Tuple:
    """Locate (model, device type, matching model profile) in the YAML config
    (reference revauct.py:40-64)."""
    yml_model = yml_models.get(model)
    yml_dev_type = yml_dev_types.get(dev_type)
    yml_dtm_profile = None
    if yml_dev_type is not None:
        for prof in (yml_dev_type.get('model_profiles') or {}).get(model, []):
            if sched.normalize_dtype(prof['dtype']) == \
                    sched.normalize_dtype(dtype) and \
                    prof['batch_size'] == ubatch_size:
                yml_dtm_profile = prof
                break
    return yml_model, yml_dev_type, yml_dtm_profile


def bid_latency_for_host(host: str, dev_type: str, cfg: dict, model: str,
                         ubatch_size: int, dtype: str = 'torch.float32'):
    """One device's auction response: (host, (shards, costs, neighbors)) —
    the payload shape of the reference's RPC handler (revauct.py:68-87)."""
    t_start = time.time()
    yml_model, yml_dev_type, yml_dtm_profile = _find_profiles(
        cfg['yml_models'], cfg['yml_dev_types'], dev_type, model, ubatch_size,
        dtype)
    shards, costs = [], []
    if yml_model is not None and yml_dev_type is not None and \
            yml_dtm_profile is not None:
        for shard, cost in revauct.bid_latency(yml_model, yml_dev_type,
                                               yml_dtm_profile, ubatch_size,
                                               dtype=dtype):
            shards.append(shard)
            costs.append(cost)
    else:
        # an empty bid silently shrinks the auctioned fleet — make the
        # misconfiguration (unknown dev type / missing profile) visible
        logger.warning(
            "host %s bids NOTHING: model=%s dev_type=%s ubatch=%d dtype=%s "
            "has no matching profile in the local device files",
            host, model, dev_type, ubatch_size, dtype)
    neighbors = cfg['yml_dev_neighbors_world'].get(host, {})
    logger.debug("Reverse auction bid time (ms): %f",
                 1000 * (time.time() - t_start))
    return host, (shards, costs, neighbors)


def _load_cfg(args) -> dict:
    """This rank's local profile files (reference _DEVICE_CFG population,
    revauct.py:147-152)."""
    return {
        'yml_models': yaml_files.yaml_models_load(args.sched_models_file),
        'yml_dev_types': yaml_files.yaml_device_types_load(
            args.sched_dev_types_file),
        'yml_dev_neighbors_world': yaml_files.yaml_device_neighbors_world_load(
            args.sched_dev_neighbors_world),
    }


def _schedule_and_print(args, yml_model, bids_in_order) -> None:
    """Auctioneer tail: filter/order the collected bids, run the selected
    scheduler, print the 1-indexed schedule YAML (reference
    revauct.py:182-239)."""
    bid_data_by_host = {
        host: ({tuple(s): c for s, c in zip(payload[0], payload[1])},
               payload[2])
        for host, payload in bids_in_order}

    if args.filter_bids_chunk > 1:
        bid_data_by_host = {
            h: (revauct.filter_bids_chunk(yml_model, b[0],
                                          chunk=args.filter_bids_chunk), b[1])
            for h, b in bid_data_by_host.items()}
    if args.filter_bids_largest:
        bid_data_by_host = {h: (revauct.filter_bids_largest(b[0]), b[1])
                            for h, b in bid_data_by_host.items()}

    data_host = args.data_host if args.data_host else \
        next(iter(bid_data_by_host))
    dev_order = list(bid_data_by_host.keys())
    rng = random.Random(args.seed)
    rng.shuffle(dev_order)
    dev_order = dev_order[:args.dev_count]
    for idx, dev in enumerate(dev_order):
        if dev == data_host:
            dev_order[0], dev_order[idx] = dev_order[idx], dev_order[0]
    logger.info("Device order: %s", dev_order)

    strict_order = not args.no_strict_order
    schedule = []
    t_start = time.time()
    if args.scheduler == 'latency_ordered':
        schedule, pred = revauct.sched_optimal_latency_dev_order(
            yml_model, args.ubatch_size, DTYPE, bid_data_by_host, data_host,
            data_host, dev_order, strict_order=strict_order,
            strict_first=args.strict_first, strict_last=args.strict_last)
        logger.info("Latency prediction (sec): %s", pred)
    elif args.scheduler == 'throughput_ordered':
        schedule, pred = revauct.sched_optimal_throughput_dev_order(
            yml_model, args.ubatch_size, DTYPE, bid_data_by_host, data_host,
            data_host, dev_order, strict_order=strict_order,
            strict_first=args.strict_first, strict_last=args.strict_last)
        logger.info("Throughput prediction (items/sec): %s", pred)
    else:
        schedule = revauct.sched_greedy_host_count(
            yml_model, args.ubatch_size, DTYPE, bid_data_by_host, data_host,
            data_host)
    logger.info("Scheduler function runtime (sec): %s", time.time() - t_start)
    logger.info("Schedule stages: %d", len(schedule))

    # shift to the runtime's 1-based layer numbering (reference
    # revauct.py:233-235)
    sched_compat = [{host: [l + 1 for l in layers]
                     for host, layers in part.items()} for part in schedule]
    logger.info("Schedule:")
    print(yaml.safe_dump(sched_compat, default_flow_style=None,
                         sort_keys=False))


def main_local(args) -> None:
    """Single-controller auction: all device configs are local; bids fan out
    to a thread pool (the reference's RPC fan-out shape, revauct.py:168-180,
    without network bring-up)."""
    if args.rank != 0:
        logger.info("Single-controller auction: rank %d idle", args.rank)
        return
    cfg = _load_cfg(args)
    host_types = {}
    for dev_type, hosts in yaml_files.yaml_devices_load(
            args.sched_dev_file).items():
        for host in hosts:
            host_types[host] = dev_type

    hosts = list(cfg['yml_dev_neighbors_world'].keys())[:args.worldsize]
    yml_model = cfg['yml_models'][args.model_name]

    t_start = time.time()
    with ThreadPoolExecutor() as pool:
        futs = [pool.submit(bid_latency_for_host, host,
                            host_types.get(host, ''), cfg, args.model_name,
                            args.ubatch_size, DTYPE) for host in hosts]
        bids_in_order = [f.result() for f in futs]
    logger.debug("Reverse auction total time (ms): %f",
                 1000 * (time.time() - t_start))
    if args.data_host is None:
        args.data_host = hosts[0]
    _schedule_and_print(args, yml_model, bids_in_order)


def main_dcn(args) -> None:
    """Distributed auction over the DCN command plane: rank-local bids, the
    reference's deployment shape (one process per device,
    revauct.py:168-180). Rank 0 is the auctioneer AND a bidder."""
    from pipeedge_tpu.comm import CMD_BID, CMD_STOP, dcn

    cfg = _load_cfg(args)
    bid_req_q: "queue.Queue" = queue.Queue()
    stop_ev = threading.Event()

    def handler(cmd, tensors):
        if cmd == CMD_BID:
            bid_req_q.put(tensors)
        elif cmd == CMD_STOP:
            stop_ev.set()

    addrs = dcn.parse_rank_addrs(args.dcn_addrs, args.worldsize, args.port)
    with dcn.DistDcnContext(args.worldsize, args.rank, addrs,
                            cmd_handler=handler) as ctx:
        if args.rank == 0:
            # broadcast the auction request (reference rpc_async fan-out,
            # revauct.py:171-174); rank 0 bids locally
            try:
                ctx.cmd_broadcast(CMD_BID, [
                    np.frombuffer(args.model_name.encode(), np.uint8),
                    np.asarray(args.ubatch_size, np.int32),
                    np.frombuffer(DTYPE.encode(), np.uint8)])
            except ConnectionError as exc:
                # release the bidders that ARE up before failing
                ctx.cmd_broadcast(CMD_STOP, best_effort=True)
                raise RuntimeError(
                    f"auction request undeliverable: {exc}") from None
            try:
                bids_in_order = [bid_latency_for_host(
                    args.host, args.dev_type, cfg, args.model_name,
                    args.ubatch_size, DTYPE)]
                for rank in range(1, args.worldsize):
                    try:
                        blob = ctx.recv_tensors(rank,
                                                timeout=args.auction_timeout,
                                                channel=dcn.CHANNEL_BIDS)
                    except (queue.Empty, ConnectionError) as exc:
                        raise RuntimeError(
                            f"no bid from rank {rank} within "
                            f"{args.auction_timeout}s ({exc.__class__.__name__}"
                            f"); is it up and bidding?") from None
                    bid = json.loads(bytes(blob[0]).decode())
                    bids_in_order.append(
                        (bid['host'],
                         (bid['shards'], bid['costs'], bid['neighbors'])))
            finally:
                # even on a failed collection (a bidder died), release the
                # others — they would otherwise block the full timeout
                ctx.cmd_broadcast(CMD_STOP)
            if args.data_host is None:
                args.data_host = args.host
            yml_model = cfg['yml_models'][args.model_name]
            _schedule_and_print(args, yml_model, bids_in_order)
        else:
            # bidder: wait for the request, answer from the LOCAL profiles
            # only (this process never sees the other ranks' device files)
            try:
                tensors = bid_req_q.get(timeout=args.auction_timeout)
            except queue.Empty:
                raise RuntimeError(
                    f"rank {args.rank}: no CMD_BID within "
                    f"{args.auction_timeout}s; is the auctioneer up?") \
                    from None
            model = bytes(tensors[0]).decode()
            ubatch_size = int(tensors[1])
            dtype = bytes(tensors[2]).decode()
            host, payload = bid_latency_for_host(
                args.host, args.dev_type, cfg, model, ubatch_size, dtype)
            blob = json.dumps({'host': host, 'shards': payload[0],
                               'costs': payload[1],
                               'neighbors': payload[2]}).encode()
            try:
                ctx.send_tensors(0, [np.frombuffer(blob, np.uint8)],
                                 channel=dcn.CHANNEL_BIDS)
            except OSError as exc:
                raise RuntimeError(
                    f"rank {args.rank}: could not deliver bid to the "
                    f"auctioneer ({exc}); is rank 0 still up?") from None
            if stop_ev.wait(timeout=args.auction_timeout):
                logger.info("rank %d: released by auctioneer", args.rank)
            else:
                logger.warning("rank %d: no CMD_STOP within %ss; exiting",
                               args.rank, args.auction_timeout)


def main() -> None:
    parser = argparse.ArgumentParser(
        description="Pipeline Reverse Auction Scheduler",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("rank", type=int,
                        help="this node's rank (0 = auctioneer)")
    parser.add_argument("worldsize", type=int,
                        help="number of devices to auction over")
    netcfg = parser.add_argument_group('Network configuration (--comm dcn)')
    netcfg.add_argument("-c", "--comm", default="local",
                        choices=["local", "dcn"],
                        help="bid fan-out: local thread pool (single "
                             "controller) or distributed rank-local bids "
                             "over the DCN command plane")
    netcfg.add_argument("--dcn-addrs", type=str, default=None,
                        help="comma-separated host:port per rank")
    netcfg.add_argument("--port", type=int, default=29500,
                        help="base port when --dcn-addrs is unset "
                             "(rank i listens on port+i)")
    netcfg.add_argument("--auction-timeout", type=float, default=120.0)
    devcfg = parser.add_argument_group('Device configuration')
    devcfg.add_argument("-sm", "--sched-models-file", default='models.yml')
    devcfg.add_argument("-sdt", "--sched-dev-types-file",
                        default='device_types.yml')
    devcfg.add_argument("-sd", "--sched-dev-file", default='devices.yml',
                        help="device types to hosts mapping YAML file "
                             "(--comm local only)")
    devcfg.add_argument("-sdnw", "--sched-dev-neighbors-world",
                        default='device_neighbors_world.yml')
    devcfg.add_argument("--host", type=str, default=None,
                        help="this bidder's hostname (--comm dcn; reference "
                             "revauct.py --host); default rank<N>")
    devcfg.add_argument("--dev-type", type=str, default=None,
                        help="this bidder's device type name in its local "
                             "device_types file (--comm dcn)")
    devcfg.add_argument("-D", "--data-host", type=str, default=None,
                        help="host where inputs are loaded and outputs "
                             "processed; default: first host / auctioneer")
    modcfg = parser.add_argument_group('Model configuration')
    modcfg.add_argument("-m", "--model-name", type=str,
                        default="google/vit-base-patch16-224",
                        choices=registry.get_model_names())
    modcfg.add_argument("-u", "--ubatch-size", default=8, type=int)
    schcfg = parser.add_argument_group('Additional scheduler options')
    schcfg.add_argument("--filter-bids-chunk", type=int, default=1)
    schcfg.add_argument("--filter-bids-largest", action='store_true')
    schcfg.add_argument("-sch", "--scheduler", default="latency_ordered",
                        choices=["latency_ordered", "throughput_ordered",
                                 "greedy_host_count"])
    schcfg.add_argument("-d", "--dev-count", type=int, default=None)
    schcfg.add_argument("--no-strict-order", action='store_true')
    schcfg.add_argument("--strict-first", action='store_true')
    schcfg.add_argument("--strict-last", action='store_true')
    schcfg.add_argument("--seed", type=int, default=None,
                        help="seed the device-order shuffle")
    args = parser.parse_args()
    if args.host is None:
        args.host = f"rank{args.rank}"
    if args.comm == "dcn" and not args.dev_type:
        parser.error("--comm dcn requires --dev-type (this bidder's entry "
                     "in its local device_types file)")

    if args.comm == "dcn":
        main_dcn(args)
    else:
        main_local(args)


if __name__ == "__main__":
    logging.basicConfig(format='%(message)s', level=logging.INFO)
    main()
