"""Adaptive quantization bitwidth policies (QuantPipe).

Capability parity with /root/reference/utils/quant.py:
- `constrain_max_bitwidth`: largest bitwidth meeting a data-movement time
  constraint given *discrete* packing (only integer values per uint32 word
  pack, so e.g. bit=7 compresses no better than bit=8) — quant.py:9-37.
- `AdaptiveBitwidthPerformanceController`: maps a performance target to a
  (bitwidth1, bitwidth2, iterations-in-bitwidth1) window split, modeling
  speedup as max_bit/bit (quant.py:40-107, based on Hoffmann et al.'s POET-
  style rate splitting).

Host-side numpy/pure Python: these run between pipeline windows and select
among pre-compiled per-bitwidth stage programs (bitwidth is compile-static
under jit — SURVEY.md §7 hard parts).
"""
from __future__ import annotations

import math
from typing import List, Tuple

import numpy as np

from ..ops.quant import compression_factor
from .controller import AdaptiveIntegralXupController

# Largest bitwidths in [2, 32] with unique discrete compression factors
# (reference runtime.py:177-179): 32/b changes only at divisors.
BITWIDTHS = [b for b in range(32, 1, -1)
             if int(compression_factor(b)) > int(compression_factor(b + 1))]


def constrain_max_bitwidth(t_max: float, d_size: float, d_speed: float,
                           bw_max: int) -> int:
    """Largest bitwidth whose *discrete* compression meets the time constraint.

    Returns 0 if even full compression cannot satisfy it. Units of `d_size`
    and `d_speed` must agree (e.g. Mbit and Mbit/s).
    """
    bitwidths = np.arange(bw_max, -1, -1, dtype=int)
    # discrete packing: effective scale = 1 / floor(32/bit); bitwidth 0 -> 0
    scales = np.concatenate([
        1.0 / np.floor(32.0 / bitwidths[:-1].astype(float)).astype(int),
        [0.0]])
    scale = np.inf if d_size == 0 else d_speed * t_max / d_size
    return int(bitwidths[scale >= scales][0])


class AdaptiveBitwidthPerformanceController(AdaptiveIntegralXupController):
    """Compute bitwidths meeting a data-movement performance constraint.

    Speedup model: xup(b) = max_bitwidth / b (perfect packing, no metadata
    overhead). The controller picks the two adjacent achievable speedups
    bracketing the target and splits the window between them.
    """

    def __init__(self, perf_constraint: float, bitwidths: List[int],
                 bitwidth_start: int):
        self._bitwidths = sorted(bitwidths, reverse=True)
        self._speedups = [self._bitwidths[0] / b for b in self._bitwidths]
        u_0 = self._bitwidths[0] / bitwidth_start
        super().__init__(perf_constraint, u_0, u_max=self._speedups[-1])

    def __call__(self, perf_measured: float, window_len: int) -> Tuple[int, int, int]:
        """Returns (bitwidth1, bitwidth2, iterations to spend in bitwidth1
        during the next window)."""
        xup_targ = super().__call__(perf_measured)
        idx_slow = max(0, len([s for s in self._speedups if s <= xup_targ]) - 1)
        idx_fast = min(idx_slow + 1, len(self._speedups) - 1)
        xup_slow = self._speedups[idx_slow]
        xup_fast = self._speedups[idx_fast]
        # Window split x solving 1/target = x/slow + (1-x)/fast:
        if math.isclose(xup_slow, xup_fast):
            frac = 0.0
        else:
            frac = (xup_slow * (xup_fast - xup_targ)) / \
                   (xup_targ * (xup_fast - xup_slow))
        return (self._bitwidths[idx_slow], self._bitwidths[idx_fast],
                round(window_len * frac))
