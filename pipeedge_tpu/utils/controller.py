"""Control-theoretic utilities: scalar Kalman filter + adaptive integral
speedup controller.

Capability parity with /root/reference/utils/controller.py (KalmanFilter at
4-66, AdaptiveIntegralXupController at 69-144). Standard textbook algorithms
(Welch & Bishop Kalman notes; Hellerstein et al. "Feedback Control of
Computing Systems"), reimplemented; pure Python — these run host-side between
pipeline windows, never inside jit.
"""
from __future__ import annotations

from typing import Optional


class KalmanFilter:
    """Scalar Kalman filter estimating x from measurements z = h*x + noise.

    Constants Q (process noise) and R (measurement noise) match the
    reference's tuning (controller.py:34-35).
    """

    def __init__(self, x_hat_0: float = 0, p_0: float = 1):
        self._x_hat = x_hat_0
        self._p = p_0
        self.Q = 0.00001
        self.R = 0.01

    @property
    def x_hat(self) -> float:
        """Current a-posteriori estimate."""
        return self._x_hat

    def __call__(self, z: float, h: float = 1) -> float:
        """One discrete step with measurement z and prediction coefficient h."""
        # predict
        x_prior = self._x_hat
        p_prior = self._p + self.Q
        # update
        gain = (p_prior * h) / (h * p_prior * h + self.R)
        self._x_hat = x_prior + gain * (z - h * x_prior)
        self._p = (1.0 - gain * h) * p_prior
        return self._x_hat


class AdaptiveIntegralXupController:
    """Adaptive integral X-up (speedup) controller.

    An integral controller whose gain adapts via a Kalman estimate of the
    base workload: u(k+1) = u(k) + (1 - pole) * e(k) / base_workload, with
    anti-windup clamping to [1, u_max] (reference controller.py:69-144).
    """

    def __init__(self, reference: float, u_0: float,
                 u_max: float = float('inf'), pole: float = 0,
                 kf_kwargs: Optional[dict] = None):
        self.reference = reference
        self._u = u_0
        self._u_max = u_max
        self.pole = pole
        self._kalman = KalmanFilter(**(kf_kwargs or {}))

    @property
    def pole(self) -> float:
        """Pole in [0, 1): small = reactive/noisy, large = slow/robust."""
        return self._pole

    @pole.setter
    def pole(self, pole: float) -> None:
        if pole < 0 or pole >= 1:
            raise ValueError("pole must be in range [0, 1)")
        self._pole = pole

    def __call__(self, y: float) -> float:
        """Compute the next control signal from measurement y."""
        base_workload = self._kalman(y, h=self._u)
        error = self.reference - y
        u = self._u + (1 - self._pole) * (error / base_workload)
        self._u = max(min(u, self._u_max), 1)  # anti-windup clamp
        return self._u
