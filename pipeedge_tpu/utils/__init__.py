"""Utility modules: thread primitives, controllers, quantization policies, data."""


def apply_env_platform() -> None:
    """Honor an explicit JAX_PLATFORMS env var via jax.config.

    The TPU plugin overrides the env var during backend discovery, so
    `JAX_PLATFORMS=cpu some_cli.py` silently grabs the (single-tenant,
    tunneled) TPU chip unless the platform is forced through jax.config
    before the first device query. CLIs that tests run as subprocesses call
    this first thing.
    """
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def force_host_cpu_devices(n: int) -> None:
    """Point jax at >= n virtual CPU devices (for multi-"chip" testing
    without TPU hardware, SURVEY.md §4).

    Must run before the first backend initialization in the process:
    --xla_force_host_platform_device_count is parse-once. Setting the
    JAX_PLATFORMS env var is NOT enough — the TPU plugin overrides it —
    so the platform is forced via jax.config, which wins. Safe to call
    multiple times; a too-small inherited device count is rewritten.
    """
    import os
    import re

    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      flags)
    if match is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    elif int(match.group(1)) < n:
        os.environ["XLA_FLAGS"] = (
            flags[:match.start()]
            + f"--xla_force_host_platform_device_count={n}"
            + flags[match.end():])
    if jax.config.jax_platforms != "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # backend already initialized; use what we have
            pass
