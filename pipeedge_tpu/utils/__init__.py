"""Utility modules: thread primitives, controllers, quantization policies, data."""
