"""Utility modules: thread primitives, controllers, quantization policies, data."""


def apply_env_platform() -> None:
    """Honor an explicit JAX_PLATFORMS env var via jax.config.

    The TPU plugin overrides the env var during backend discovery, so
    `JAX_PLATFORMS=cpu some_cli.py` silently grabs the (single-tenant,
    tunneled) TPU chip unless the platform is forced through jax.config
    before the first device query. CLIs that tests run as subprocesses call
    this first thing.
    """
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def force_host_cpu_devices(n: int) -> None:
    """Point jax at >= n virtual CPU devices (for multi-"chip" testing
    without TPU hardware, SURVEY.md §4).

    Must run before the first backend initialization in the process:
    --xla_force_host_platform_device_count is parse-once. Setting the
    JAX_PLATFORMS env var is NOT enough — the TPU plugin overrides it —
    so the platform is forced via jax.config, which wins. Safe to call
    multiple times; a too-small inherited device count is rewritten.
    """
    import os
    import re

    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    match = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      flags)
    if match is None:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    elif int(match.group(1)) < n:
        os.environ["XLA_FLAGS"] = (
            flags[:match.start()]
            + f"--xla_force_host_platform_device_count={n}"
            + flags[match.end():])
    if jax.config.jax_platforms != "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # backend already initialized; use what we have
            pass


def require_live_backend(metric: str, unit: str = None,
                         timeout_s: float = 180.0) -> None:
    """Fail fast (with a diagnosable JSON line) if the default backend
    cannot run a trivial computation within `timeout_s` — a wedged/held
    TPU tunnel lease otherwise hangs the caller with no output.

    The probe runs in a SUBPROCESS, not a thread: on timeout the parent
    prints an error record `{"metric": ..., "value": 0, ...}` and exits 1
    WITHOUT having initialized its own backend, and the child is left
    alone (never signaled) so it remains a well-behaved client that
    completes or fails cleanly whenever the backend answers. Killing or
    abandoning a mid-RPC client is exactly what wedges the single-tenant
    tunnel lease (docs/PERF.md), so the diagnostic must never do either.
    """
    import json
    import subprocess
    import sys

    # Honor an explicit JAX_PLATFORMS in the child: the TPU plugin
    # overrides the env var, so it must be forced via jax.config
    # (apply_env_platform semantics, inlined so the probe is cwd-free).
    probe_src = (
        "import os, jax\n"
        "p = os.environ.get('JAX_PLATFORMS')\n"
        "if p: jax.config.update('jax_platforms', p)\n"
        "import jax.numpy as jnp\n"
        "float(jnp.ones((2, 2)).sum())\n")
    probe = subprocess.Popen(
        [sys.executable, "-c", probe_src],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        _, err = probe.communicate(timeout=timeout_s)
        if probe.returncode == 0:
            return
        tail = err.decode(errors="replace").strip().splitlines()
        reason = tail[-1] if tail else f"probe exited {probe.returncode}"
    except subprocess.TimeoutExpired:
        # Deliberately do NOT kill the probe: it finishes on its own when
        # the backend unwedges, keeping this diagnostic lease-neutral.
        reason = (f"backend unresponsive after {timeout_s}s (TPU tunnel "
                  "lease held/wedged?); probe left running, not signaled")
    print(json.dumps({
        "metric": metric, "value": 0, "unit": unit, "vs_baseline": 0,
        "error": reason}), flush=True)
    raise SystemExit(1)
