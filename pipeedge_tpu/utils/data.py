"""Dataset utilities (parity with /root/reference/utils/data.py).

Numpy-first (the pipeline consumes jnp arrays); torch / HF-datasets /
torchvision are optional and gracefully gated — with zero egress the default
path is synthetic data, matching the reference's rollover-single-image mode
(runtime.py:394-401).
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class RolloverTensorDataset:
    """Repeat small tensors to a requested length (reference data.py:7-20)."""

    def __init__(self, max_size: int, *tensors):
        assert len(tensors) > 0
        self._tensors = tensors
        self._max_size = max_size

    def __len__(self) -> int:
        return self._max_size

    def __getitem__(self, idx) -> Tuple:
        if not 0 <= idx < self._max_size:
            raise IndexError(idx)
        return tuple(t[idx % len(t)] for t in self._tensors)


class SubsetDataset:
    """Index-selected view of a dataset (reference's load_dataset_subset)."""

    def __init__(self, dataset, indices: Sequence[int]):
        self._dataset = dataset
        self._indices = list(indices)

    def __len__(self) -> int:
        return len(self._indices)

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]


def load_dataset_subset(dataset, indices: Optional[Sequence[int]] = None,
                        max_size: Optional[int] = None,
                        shuffle: bool = False):
    """Select a subset by indices or size, optionally shuffled."""
    if indices is None:
        indices = list(range(len(dataset)))
    if shuffle:
        indices = list(indices)
        np.random.default_rng(0).shuffle(indices)
    if max_size is not None:
        indices = list(indices)[:max_size]
    return SubsetDataset(dataset, indices)


def synthetic_image_dataset(size: int, shape=(3, 224, 224),
                            n_labels: int = 1000) -> RolloverTensorDataset:
    """Random-image dataset; the zero-egress stand-in for the reference's
    downloaded sample image (runtime.py:397-401)."""
    rng = np.random.default_rng(0)
    images = rng.normal(size=(min(size, 64),) + shape).astype(np.float32)
    labels = rng.integers(0, n_labels, size=(min(size, 64),))
    return RolloverTensorDataset(size, images, labels)


def synthetic_token_dataset(size: int, seq_len: int = 512,
                            vocab_size: int = 30522,
                            n_labels: int = 2) -> RolloverTensorDataset:
    """Random token-id dataset (BERT input stand-in, tools/bert_save_input.py)."""
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab_size, size=(min(size, 64), seq_len)).astype(np.int32)
    labels = rng.integers(0, n_labels, size=(min(size, 64),))
    return RolloverTensorDataset(size, ids, labels)


def load_dataset_glue(tokenizer, task: str, split: str, ubatch_size: int):
    """GLUE dataset with per-microbatch padding (reference data.py:54-78).
    Requires the HF `datasets` package and a local cache (zero egress)."""
    from datasets import load_dataset  # gated import
    dataset = load_dataset('glue', task, split=split)

    def _tokenize(examples):
        enc = tokenizer(examples['sentence'], padding=True, truncation=True,
                        return_tensors='np')
        return {'input_ids': enc['input_ids'], 'label': examples['label']}

    dataset = dataset.map(_tokenize, batched=True, batch_size=ubatch_size)
    items = [(np.asarray(d['input_ids'], dtype=np.int32), int(d['label']))
             for d in dataset]
    ids = [i for i, _ in items]
    labels = np.asarray([l for _, l in items])
    return list(zip(ids, labels))


def load_dataset_imagenet(feature_extractor, root: str, split: str = 'val'):
    """ImageNet via torchvision ImageFolder + HF feature extractor
    (reference data.py:81-89). Requires a local dataset directory."""
    from torchvision.datasets import ImageFolder  # gated import

    class _FeatureDataset:
        def __init__(self, folder):
            self._folder = folder

        def __len__(self):
            return len(self._folder)

        def __getitem__(self, idx):
            img, label = self._folder[idx]
            pixels = feature_extractor(images=[img], return_tensors='np'
                                       )['pixel_values'][0]
            return pixels, label

    import os
    return _FeatureDataset(ImageFolder(os.path.join(root, split)))


def batch_dataset(dataset, ubatch_size: int):
    """Yield (inputs [u, ...], labels [u]) microbatches, FIFO order."""
    n = len(dataset)
    for start in range(0, n - ubatch_size + 1, ubatch_size):
        items = [dataset[i] for i in range(start, start + ubatch_size)]
        inputs = np.stack([x for x, _ in items])
        labels = np.asarray([y for _, y in items])
        yield inputs, labels
