"""Version-bridging shims for the jax APIs this tree uses.

The parallel modules are written against the promoted `jax.shard_map` /
`jax.lax.axis_size` API (jax >= 0.6); older jaxlibs (0.4.x) ship the same
machinery as `jax.experimental.shard_map.shard_map` with the replication
check under its old `check_rep` name and the static in-body axis size
behind `jax.core.axis_frame`. One import site per concept keeps every
caller version-agnostic — kernels and meshes are identical either way.
"""
from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    def shard_map(f, *, mesh, in_specs, out_specs):
        """`jax.shard_map` with the replication/VMA check disabled (every
        body in this tree manages its own collectives)."""
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
else:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        """experimental shard_map; check_rep is check_vma's old name."""
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


if hasattr(jax.lax, "axis_size"):
    def axis_size(name) -> int:
        """Static size of a shard_map/pmap mesh axis, from inside the body."""
        return jax.lax.axis_size(name)
else:  # jax 0.4.x: axis_frame resolves the name to its static size
    def axis_size(name) -> int:
        """Static size of a shard_map/pmap mesh axis, from inside the body."""
        return jax.core.axis_frame(name)
