"""Sharded checkpoint save/load via Orbax (+ per-stage slicing).

The reference's "checkpoint" story is npz weight archives where each stage
lazily loads only its own layers' keys (SURVEY.md §5.4; reference
vit.py:93-118). This module keeps that capability contract and adds the
TPU-native format on top:

- `save_params` / `load_params`: one parameter pytree <-> an Orbax
  checkpoint directory (async-capable, content-addressed, the standard JAX
  checkpoint format). `load_params` accepts a `shardings` pytree
  (NamedSharding leaves) for sharded direct-to-device restore — each host
  reads only the slices it owns, the Orbax equivalent of the reference's
  lazy npz key loading.
- `save_stage_checkpoints` / `load_stage_checkpoint`: materialize one
  checkpoint per pipeline stage from a reference-format npz archive, so a
  DCN rank restores exactly its stage shard from disk without ever touching
  other stages' weights (parity with module_shard_factory's npz slicing,
  registry.py:111-136).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

_MANIFEST = "manifest.json"


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save_params(path: str, params: Dict) -> None:
    """Write a parameter pytree as an Orbax checkpoint at `path`."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), params, force=True)
    ckptr.wait_until_finished()


def load_params(path: str, shardings: Optional[Any] = None,
                like: Optional[Any] = None) -> Dict:
    """Restore a pytree saved by `save_params`.

    With `shardings` (a pytree of jax.sharding.Sharding congruent with the
    saved tree, or a single Sharding applied to every leaf), leaves restore
    directly into the requested placement.

    With `like` (a congruent pytree of arrays, e.g. a freshly initialized
    training state), the restore target takes ITS structure and per-leaf
    shardings — container types (optax NamedTuples etc.) survive, and
    every leaf lands on its mesh placement. Metadata-derived targets
    (the other modes) flatten containers to plain dicts/lists.
    """
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    if like is not None:
        def abstract(x):
            a = jax.numpy.asarray(x)
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=a.sharding)

        return ckptr.restore(path,
                             jax.tree_util.tree_map(abstract, like))
    if shardings is None:
        # Don't trust saved sharding metadata: a checkpoint written on one
        # topology (e.g. a TPU host) must restore on another (e.g. a CPU
        # test process). Default every leaf onto the current backend.
        shardings = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
    meta = ckptr.metadata(path)
    item_meta = getattr(meta, "item_metadata", meta)
    single = isinstance(shardings, jax.sharding.Sharding)
    if single:
        target = jax.tree_util.tree_map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype,
                                           sharding=shardings), item_meta)
    else:
        target = jax.tree_util.tree_map(
            lambda m, sh: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=sh),
            item_meta, shardings)
    return ckptr.restore(path, target)


def stage_dir(root: str, stage: int) -> str:
    return os.path.join(os.path.abspath(root), f"stage_{stage:03d}")


def save_stage_checkpoints(model_name: str, npz_path: str, out_root: str,
                           partition: Sequence[Tuple[int, int]],
                           dtype=None) -> List[str]:
    """Slice a reference-format npz into one Orbax checkpoint per stage.

    Returns the per-stage checkpoint directories. Stage i's checkpoint holds
    exactly the parameters `module_shard_factory` would build for
    partition[i] — nothing else is read into memory at restore time.
    """
    import jax.numpy as jnp

    from ..models import registry

    if dtype is None:
        dtype = jnp.float32
    entry = registry.get_model_entry(model_name)
    dirs = []
    with np.load(npz_path) as weights:
        for i, (l, r) in enumerate(partition):
            sc = registry.make_shard_config(model_name, l, r)
            params = entry.family.load_params(entry.config, sc, weights,
                                              dtype=dtype)
            d = stage_dir(out_root, i)
            save_params(d, params)
            dirs.append(d)
    os.makedirs(os.path.abspath(out_root), exist_ok=True)
    with open(os.path.join(os.path.abspath(out_root), _MANIFEST), "w",
              encoding="utf8") as f:
        json.dump({"model_name": model_name,
                   "partition": [list(p) for p in partition]}, f)
    return dirs


def read_manifest(out_root: str) -> Optional[Dict]:
    """The {model_name, partition} manifest written next to the stage dirs
    (None for pre-manifest checkpoints)."""
    path = os.path.join(os.path.abspath(out_root), _MANIFEST)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf8") as f:
        return json.load(f)


def check_stage_compatible(out_root: str, model_name: str, stage: int,
                           layer_range: Tuple[int, int]) -> None:
    """Raise if the checkpoint's conversion partition disagrees with the
    runtime schedule — stage-index restore with a shifted partition would
    otherwise load the wrong layers' weights silently."""
    manifest = read_manifest(out_root)
    if manifest is None:
        return
    if manifest["model_name"] != model_name:
        raise ValueError(
            f"stage checkpoint {out_root} is for model "
            f"{manifest['model_name']!r}, not {model_name!r}")
    saved = [tuple(p) for p in manifest["partition"]]
    if stage >= len(saved) or saved[stage] != tuple(layer_range):
        raise ValueError(
            f"stage {stage} layer range {tuple(layer_range)} does not match "
            f"checkpoint partition {saved} (re-run tools/"
            f"convert_checkpoint.py with the runtime partition)")


def load_stage_checkpoint(out_root: str, stage: int,
                          shardings: Optional[Any] = None) -> Dict:
    """Restore one stage's parameter pytree written by
    `save_stage_checkpoints`."""
    return load_params(stage_dir(out_root, stage), shardings=shardings)
