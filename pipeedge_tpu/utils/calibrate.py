"""Offline per-channel calibration for the int8 compute path.

Sweeps a calibration batch through a shard with an observer installed on
the tagged denses (models/layers.py `_QC_OBSERVER`), aggregates per-tag
activation statistics, and derives Banner-optimal clip thresholds from
`ops/clamp.py`'s clamp lineage: tagged activations are near-Laplace
(alpha = W(3*4^b) * sqrt(var/2)), except the MLP-down input which is
post-GeLU (half bell curve, alpha = W(3*4^(b+1)) * sqrt(E[x^2])) — the
same two distributions the wire codec's clamp already assumes
(parallel/pipeline.py `_encode_payload`).

The result is a scale sidecar written NEXT to the checkpoint
(`<ckpt>.int8scales.npz`): per-tag clamp alphas plus per-channel weight
scales for every dense in the shard. At serve time
`quantize_compute_from_sidecar` turns the sidecar into a `QuantizeCompute`
config whose alphas fold into the int8 matmul's pre-quantization clip
(ops/int8_matmul.int8_dense) as trace-time constants.

Observation runs EAGERLY (no jit) over unrolled block params so the
observer sees concrete arrays — `tools/calibrate.py` is the entrypoint.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, Iterable, List, Mapping, Optional

import numpy as np

from ..models import layers
from ..ops.clamp import clamp_factor_gelu, clamp_factor_laplace

# tags whose observed input is post-GeLU (half bell curve): everything
# else calibrates with the Laplace factor
GELU_TAGS = ("mlp.down",)


@dataclasses.dataclass
class TagStats:
    """Running activation moments for one dense tag across calibration
    batches (and across blocks — all blocks share a tag, so one alpha
    serves the whole shard, like the wire clamp)."""
    amax: float = 0.0
    sum_sq: float = 0.0
    sum_: float = 0.0
    count: int = 0

    def update(self, x) -> None:
        xf = np.asarray(x, np.float32)
        self.amax = max(self.amax, float(np.max(np.abs(xf))))
        self.sum_sq += float(np.sum(np.square(xf, dtype=np.float64)))
        self.sum_ += float(np.sum(xf, dtype=np.float64))
        self.count += xf.size

    @property
    def var(self) -> float:
        if not self.count:
            return 0.0
        mean = self.sum_ / self.count
        return max(self.sum_sq / self.count - mean * mean, 0.0)

    @property
    def second_moment(self) -> float:
        return self.sum_sq / self.count if self.count else 0.0


def collect_activation_stats(run_fn: Callable, params,
                             batches: Iterable) -> Dict[str, TagStats]:
    """Run `run_fn(params, batch)` eagerly for each calibration batch with
    the tag observer installed; returns per-tag running stats.

    `run_fn` must be the UNJITTED shard function over unrolled block
    params (registry.module_shard_factory(..., unroll=True) + its
    `.__wrapped__`) — under jit or lax.scan the observer would see
    tracers, not data.
    """
    stats: Dict[str, TagStats] = {}

    def observer(tag: str, x) -> None:
        import jax
        if isinstance(x, jax.core.Tracer):
            raise RuntimeError(
                f"calibration observer saw a tracer for tag {tag!r}: run "
                "the shard eagerly (unjitted, unrolled blocks)")
        stats.setdefault(tag, TagStats()).update(x)

    prev = layers._QC_OBSERVER
    layers._QC_OBSERVER = observer
    try:
        for batch in batches:
            run_fn(params, batch)
    finally:
        layers._QC_OBSERVER = prev
    if not stats:
        raise RuntimeError("calibration saw no tagged denses — the model "
                           "family has no int8-routable layers")
    return stats


def compute_alphas(stats: Mapping[str, TagStats],
                   bit: int = 8) -> Dict[str, float]:
    """Banner-optimal clip threshold per tag (ops/clamp.py lineage)."""
    alphas: Dict[str, float] = {}
    for tag, st in stats.items():
        if tag in GELU_TAGS:
            alpha = clamp_factor_gelu(bit) * float(
                np.sqrt(st.second_moment))
        else:
            alpha = clamp_factor_laplace(bit) * float(
                np.sqrt(0.5 * st.var))
        # clipping NOTHING is always safe; clipping below the observed
        # range only ever helps if the distribution assumption holds, so
        # never clamp tighter than half the observed amax (outlier-robust
        # floor: a degenerate calibration batch can't zero a layer out)
        alphas[tag] = max(alpha, 0.5 * st.amax) if st.amax else 1.0
    return alphas


def weight_channel_scales(params, prefix: str = "") -> Dict[str, np.ndarray]:
    """Per-output-channel int8 scales for every dense `{w, b}` dict in a
    shard's parameter pytree, keyed by slash-joined path."""
    from ..ops.int8_matmul import quantize_weight

    out: Dict[str, np.ndarray] = {}

    def walk(node, path):
        if isinstance(node, Mapping):
            if "w" in node and getattr(node["w"], "ndim", 0) == 2:
                out[path] = np.asarray(quantize_weight(node["w"])[1])
                return
            for key, sub in node.items():
                walk(sub, f"{path}/{key}" if path else str(key))
        elif isinstance(node, (tuple, list)):
            for i, sub in enumerate(node):
                walk(sub, f"{path}/{i}" if path else str(i))

    walk(params, prefix)
    return out


def sidecar_path(model_file: str) -> str:
    """The sidecar lives next to the checkpoint it calibrates."""
    return model_file + ".int8scales.npz"


def write_sidecar(path: str, alphas: Mapping[str, float],
                  wscales: Mapping[str, np.ndarray],
                  meta: Optional[dict] = None) -> None:
    arrays = {f"alpha/{tag}": np.float32(a) for tag, a in alphas.items()}
    arrays.update({f"wscale/{k}": np.asarray(v, np.float32)
                   for k, v in wscales.items()})
    arrays["meta"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)


def load_sidecar(path: str) -> dict:
    """Inverse of `write_sidecar`: {'alphas': {...}, 'weight_scales':
    {...}, 'meta': {...}}."""
    with np.load(path) as z:
        alphas = {k[len("alpha/"):]: float(z[k]) for k in z.files
                  if k.startswith("alpha/")}
        wscales = {k[len("wscale/"):]: z[k] for k in z.files
                   if k.startswith("wscale/")}
        meta = json.loads(bytes(z["meta"]).decode()) if "meta" in z.files \
            else {}
    return {"alphas": alphas, "weight_scales": wscales, "meta": meta}


def quantize_compute_from_sidecar(
        path: str, skip_tags: Iterable[str] = (),
        block_k: int = 128, tunnel: bool = False) -> layers.QuantizeCompute:
    """Build the runtime config from a calibration sidecar."""
    side = load_sidecar(path)
    return layers.QuantizeCompute(
        enabled=True, block_k=block_k, skip_tags=frozenset(skip_tags),
        clamp_alphas=dict(side["alphas"]), tunnel=tunnel)


def calibrate_shard(model_name: str, model_file: Optional[str],
                    layer_start: int, layer_end: int,
                    batches: List, bit: int = 8):
    """One-call calibration: build the shard (unrolled, unjitted), sweep
    the batches, return (alphas, weight_scales, stats)."""
    from ..models import registry

    fn, params, _ = registry.module_shard_factory(
        model_name, model_file, layer_start, layer_end, unroll=True)
    raw_fn = getattr(fn, "__wrapped__", fn)
    stats = collect_activation_stats(raw_fn, params, batches)
    alphas = compute_alphas(stats, bit=bit)
    wscales = weight_channel_scales(params)
    return alphas, wscales, stats
