"""Thread utilities: lock factories, readers-writer lock, waitable counter.

Capability parity with /root/reference/utils/threads.py (RWLock at 5-57,
ThreadSafeCounter at 60-91). The TPU runtime is single-controller and far
less thread-heavy than the reference's 4-threads-per-rank design, but the
monitoring facade and host-driven pipeline still use these.

`make_lock`/`make_rlock`/`make_condition` are the repo's lock constructors
(docs/STATIC_ANALYSIS.md): plain stdlib primitives normally, and NAMED
`analysis/lockdep.py` tracked locks when the runtime lock-order witness is
on (env PIPEEDGE_LOCKDEP=1) — per-thread acquisition stacks feed a global
order graph so the tier-1 suite convicts lock-order inversions and
blocking-calls-under-lock the moment a PR introduces them. The name is the
graph node: instances of one lock site share it (``dcn.dead``), indexed
sites embed the index (``dcn.conn[3]``).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

from ..analysis import lockdep


def make_lock(name: str) -> "threading.Lock":
    """A mutex for lock site `name`: tracked when the witness is on."""
    if lockdep.enabled():
        return lockdep.TrackedLock(lockdep.state(), name)
    return threading.Lock()


def make_rlock(name: str) -> "threading.RLock":
    """A re-entrant mutex for lock site `name` (witness-aware)."""
    if lockdep.enabled():
        return lockdep.TrackedRLock(lockdep.state(), name)
    return threading.RLock()


def make_condition(name: str) -> "threading.Condition":
    """A condition variable for lock site `name` (witness-aware): the
    tracked variant rides a `TrackedRLock`, and `wait()` releases the
    witness's held stack with the lock — parking in a wait is not
    'holding a lock across a blocking call'."""
    if lockdep.enabled():
        return threading.Condition(
            lockdep.TrackedRLock(lockdep.state(), name))
    return threading.Condition()


class RWLock:
    """A readers-writer lock: many concurrent readers, exclusive writers."""

    def __init__(self, name: str = "rwlock"):
        self._cond = make_condition(name)
        self._readers = 0
        self._writer = False

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            while self._writer or self._readers > 0:
                self._cond.wait()
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def lock_read(self):
        """Context manager for read access."""
        self.acquire_read()
        try:
            yield self
        finally:
            self.release_read()

    @contextmanager
    def lock_write(self):
        """Context manager for exclusive write access."""
        self.acquire_write()
        try:
            yield self
        finally:
            self.release_write()


class ThreadSafeCounter:
    """A counter whose waiters can block until a threshold is reached
    (reference utils/threads.py:60-91; used to count pipeline results)."""

    def __init__(self, value: int = 0, name: str = "counter"):
        self._value = value
        self._cond = make_condition(name)

    @property
    def value(self) -> int:
        with self._cond:
            return self._value

    def add(self, quantity: int = 1) -> None:
        with self._cond:
            self._value += quantity
            self._cond.notify_all()

    def set(self, value: int) -> None:
        with self._cond:
            self._value = value
            self._cond.notify_all()

    def wait_gte(self, threshold: int, timeout: float = None) -> bool:
        """Block until value >= threshold; returns False on timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: self._value >= threshold,
                                       timeout=timeout)
