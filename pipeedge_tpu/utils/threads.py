"""Thread utilities: readers-writer lock and waitable counter.

Capability parity with /root/reference/utils/threads.py (RWLock at 5-57,
ThreadSafeCounter at 60-91). The TPU runtime is single-controller and far
less thread-heavy than the reference's 4-threads-per-rank design, but the
monitoring facade and host-driven pipeline still use these.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager


class RWLock:
    """A readers-writer lock: many concurrent readers, exclusive writers."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False

    def acquire_read(self) -> None:
        with self._cond:
            while self._writer:
                self._cond.wait()
            self._readers += 1

    def release_read(self) -> None:
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self) -> None:
        with self._cond:
            while self._writer or self._readers > 0:
                self._cond.wait()
            self._writer = True

    def release_write(self) -> None:
        with self._cond:
            self._writer = False
            self._cond.notify_all()

    @contextmanager
    def lock_read(self):
        """Context manager for read access."""
        self.acquire_read()
        try:
            yield self
        finally:
            self.release_read()

    @contextmanager
    def lock_write(self):
        """Context manager for exclusive write access."""
        self.acquire_write()
        try:
            yield self
        finally:
            self.release_write()


class ThreadSafeCounter:
    """A counter whose waiters can block until a threshold is reached
    (reference utils/threads.py:60-91; used to count pipeline results)."""

    def __init__(self, value: int = 0):
        self._value = value
        self._cond = threading.Condition()

    @property
    def value(self) -> int:
        with self._cond:
            return self._value

    def add(self, quantity: int = 1) -> None:
        with self._cond:
            self._value += quantity
            self._cond.notify_all()

    def set(self, value: int) -> None:
        with self._cond:
            self._value = value
            self._cond.notify_all()

    def wait_gte(self, threshold: int, timeout: float = None) -> bool:
        """Block until value >= threshold; returns False on timeout."""
        with self._cond:
            return self._cond.wait_for(lambda: self._value >= threshold,
                                       timeout=timeout)
