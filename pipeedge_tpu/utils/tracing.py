"""Runtime tracing: JAX profiler (XPlane/TensorBoard/Perfetto) integration.

The reference has NO trace-viewer integration anywhere (SURVEY.md §5.1 —
only wall-clock offline profiling and heartbeat CSVs). On TPU the profiler
is how you actually see MXU utilization, HBM traffic, and collective overlap,
so the runtime exposes it first-class:

- `trace(out_dir)`: context manager capturing a profiler session; view with
  TensorBoard's profile plugin or Perfetto (xplane → trace.json.gz is
  emitted automatically).
- `annotate(name)`: named host-side region that shows up on the trace
  timeline (wraps `jax.profiler.TraceAnnotation`), used by the pipeline
  drivers to label per-microbatch/per-stage work.

Both degrade to no-ops if the profiler backend is unavailable (e.g. a
second concurrent session), mirroring the monitoring subsystem's graceful
energy-meter fallback (reference monitoring.py:104-121).
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(out_dir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace into `out_dir` (no-op when None)."""
    if not out_dir:
        yield
        return
    import jax
    try:
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
    except Exception as exc:  # bad path / profiler busy: degrade gracefully
        logger.warning("trace capture unavailable (%s); continuing without",
                       exc)
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
            logger.info("trace written to %s (view: tensorboard --logdir %s)",
                        out_dir, out_dir)
        except Exception as exc:
            logger.warning("trace stop failed: %s", exc)


class _SafeAnnotation:
    """TraceAnnotation wrapper that degrades to a no-op if the profiler
    backend rejects entry (e.g. a second concurrent session) — the same
    graceful fallback `trace()` applies, honoring the module contract."""

    __slots__ = ("_inner", "_entered")

    def __init__(self, inner):
        self._inner = inner
        self._entered = False

    def __enter__(self):
        try:
            self._inner.__enter__()
            self._entered = True
        except Exception as exc:  # profiler busy/unavailable: no-op region
            logger.warning("annotate unavailable (%s); continuing without",
                           exc)
        return self

    def __exit__(self, *exc):
        if not self._entered:
            return False
        self._entered = False
        try:
            return self._inner.__exit__(*exc)
        except Exception as err:
            logger.warning("annotate exit failed: %s", err)
            return False


def annotate(name: str):
    """Named region on the profiler timeline (host + linked device ops);
    degrades to a no-op context manager when the profiler backend is
    unavailable, like `trace()`."""
    try:
        import jax
        return _SafeAnnotation(jax.profiler.TraceAnnotation(name))
    except Exception as exc:  # import/constructor failure: degrade
        logger.warning("annotate unavailable (%s); continuing without", exc)
        return contextlib.nullcontext()
