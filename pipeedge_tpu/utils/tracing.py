"""Runtime tracing: JAX profiler (XPlane/TensorBoard/Perfetto) integration.

The reference has NO trace-viewer integration anywhere (SURVEY.md §5.1 —
only wall-clock offline profiling and heartbeat CSVs). On TPU the profiler
is how you actually see MXU utilization, HBM traffic, and collective overlap,
so the runtime exposes it first-class:

- `trace(out_dir)`: context manager capturing a profiler session; view with
  TensorBoard's profile plugin or Perfetto (xplane → trace.json.gz is
  emitted automatically).
- `annotate(name)`: named host-side region that shows up on the trace
  timeline (wraps `jax.profiler.TraceAnnotation`), used by the pipeline
  drivers to label per-microbatch/per-stage work.

Both degrade to no-ops if the profiler backend is unavailable (e.g. a
second concurrent session), mirroring the monitoring subsystem's graceful
energy-meter fallback (reference monitoring.py:104-121).
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Iterator, Optional

logger = logging.getLogger(__name__)


@contextlib.contextmanager
def trace(out_dir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace into `out_dir` (no-op when None)."""
    if not out_dir:
        yield
        return
    import jax
    try:
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
    except Exception as exc:  # bad path / profiler busy: degrade gracefully
        logger.warning("trace capture unavailable (%s); continuing without",
                       exc)
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
            logger.info("trace written to %s (view: tensorboard --logdir %s)",
                        out_dir, out_dir)
        except Exception as exc:
            logger.warning("trace stop failed: %s", exc)


def annotate(name: str):
    """Named region on the profiler timeline (host + linked device ops)."""
    import jax
    return jax.profiler.TraceAnnotation(name)
