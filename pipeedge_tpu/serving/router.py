"""Front-end router for a fleet of decode replicas (docs/SERVING.md).

One decode process is a single point of failure no matter how much
fault tolerance lives behind it: the heartbeats, epoch fencing, and
lease/ack shipping of the pipeline planes all sit BEHIND one HTTP
process (tools/serve.py), so a replica crash loses every in-flight
session. This module is the other half of production shape — N decode
replicas behind one router that keeps serving through any single
replica failure:

- **ReplicaRegistry**: the routing table. Each replica carries an EWMA
  degradation score fed by `/healthz` polls (the `health/scorer.py`
  discipline applied to HTTP probes: a failed poll is instant
  degradation 1.0, a slow one degrades linearly up to `latency_bad_s`)
  and walks healthy -> suspect -> dead with hysteresis
  (`suspect_threshold` > `readmit_threshold`), plus the administrative
  `drained` state. `fail_dead` consecutive poll failures convict
  outright — a vanished process must not wait out EWMA smoothing — and
  a respawned replica readmits after `readmit` consecutive clean polls.
- **Prefix-aware routing**: `pick()` sends a prompt to the replica
  whose `PrefixTrie` already holds its leading pages (a sticky
  affinity map keyed on the prompt's leading tokens — the loadgen
  `shared:` distribution is the workload), falling back to
  least-in-flight. Affinity entries follow their pages when a drain
  migrates them (`reassign_affinity`).
- **DecodeRouter**: the proxy. Per-request timeout, bounded
  retry-with-backoff to a DIFFERENT replica on connection failure
  (marking the failed replica dead immediately — the poll loop would
  take `fail_dead` windows), optional tail hedging for the interactive
  class, and mid-STREAM failover: a replica dying under a streaming
  request re-dispatches the whole request to a survivor and suppresses
  the step lines the client already saw — decode is deterministic on
  pinned seeds, so the continuation is token-identical (re-prefill
  recovery; tests/test_router_fleet.py pins it). Graceful drain ships
  the drained replica's warm prefix pages to a survivor over the
  wire-v2 KV ship codec instead (`/kv/export` -> `/kv/import`,
  kv/ship.py), then detaches.

The registry is pure logic under one lock (unit-testable without
sockets: tests/test_router.py); all I/O — health polls, proxied
requests, drain migration — happens OUTSIDE the lock on snapshots
(comm/dcn.py's _declare_dead discipline). Failure semantics follow
docs/FAULT_TOLERANCE.md's replica lifecycle section.
"""
from __future__ import annotations

import base64
import inspect
import json
import logging
import queue
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import telemetry
from ..telemetry import metrics as prom
from ..utils.threads import make_lock

logger = logging.getLogger(__name__)

REPLICA_HEALTHY = "healthy"
REPLICA_SUSPECT = "suspect"
REPLICA_DRAINED = "drained"
REPLICA_DEAD = "dead"

# numeric codes for the per-replica state gauge (docs/OBSERVABILITY.md)
STATE_CODES = {REPLICA_HEALTHY: 0, REPLICA_SUSPECT: 1,
               REPLICA_DRAINED: 2, REPLICA_DEAD: 3}

ROUTE_OUTCOMES = ("ok", "shed", "deadline", "error", "no_replica")

# Hop-propagation headers (docs/OBSERVABILITY.md fleet observatory).
# The router mints the request id and carries it to the replica on
# RID_HEADER (per-attempt derived rids: `rid.tN` retries, `rid.hedge`
# hedge branch, `rid.foN` mid-stream failover replays) plus the hop
# index on HOP_HEADER; responses echo RID_HEADER (the BASE rid — the
# root of the derivation tree) and REPLICA_HEADER (which replica
# actually served) so a client complaint cross-references straight to
# a postmortem bundle without body parsing.
RID_HEADER = "X-PipeEdge-Rid"
HOP_HEADER = "X-PipeEdge-Hop"
REPLICA_HEADER = "X-PipeEdge-Replica"

# /metrics plane. Per-replica label matrices are pre-declared in
# `ReplicaRegistry.add`, when the fleet membership is known (PL501);
# the fixed-domain matrices are declared right here.
_M_REQUESTS = prom.REGISTRY.counter(
    "pipeedge_router_requests_total",
    "requests through the router, by terminal outcome")
_M_FAILOVERS = prom.REGISTRY.counter(
    "pipeedge_router_failovers_total",
    "requests re-dispatched to a different replica after a replica "
    "failure (connection error or mid-stream death)")
_M_RETRIES = prom.REGISTRY.counter(
    "pipeedge_router_retries_total",
    "route retries, by reason (connect = replica unreachable, "
    "shed = replica 503, try another)")
_M_HEDGES = prom.REGISTRY.counter(
    "pipeedge_router_hedges_total",
    "tail hedges fired, by which branch won")
_M_DRAINS = prom.REGISTRY.counter(
    "pipeedge_router_drains_total",
    "graceful replica drains orchestrated")
_M_MIGRATED = prom.REGISTRY.counter(
    "pipeedge_router_migrated_prefixes_total",
    "warm prefixes shipped replica-to-replica during drains "
    "(kv/ship.py codec)")
_M_STATE = prom.REGISTRY.gauge(
    "pipeedge_router_replica_state",
    "replica lifecycle state (0 healthy, 1 suspect, 2 drained, 3 dead)")
_M_SCORE = prom.REGISTRY.gauge(
    "pipeedge_router_replica_score",
    "EWMA health-poll degradation score per replica "
    "(0 = healthy, 1 = fully degraded)")
_M_INFLIGHT = prom.REGISTRY.gauge(
    "pipeedge_router_replica_inflight",
    "requests currently proxied to each replica")
for _o in ROUTE_OUTCOMES:
    _M_REQUESTS.declare(outcome=_o)
for _r in ("connect", "shed"):
    _M_RETRIES.declare(reason=_r)
for _w in ("primary", "hedge"):
    _M_HEDGES.declare(winner=_w)
_M_FAILOVERS.declare()
_M_DRAINS.declare()
_M_MIGRATED.declare()


class NoReplicaAvailable(RuntimeError):
    """Every replica is dead, drained, or already tried — the router's
    own shed (503 + Retry-After, PL403)."""

    def __init__(self, detail: str, retry_after: float = 1.0):
        super().__init__(detail)
        self.retry_after = float(retry_after)


class RouterPolicy:
    """The router's knobs. The health-score half mirrors
    `health/scorer.py`'s HealthPolicy (same hysteresis contract:
    `suspect_threshold` > `readmit_threshold`, scores between them
    change nothing); the routing half bounds how much work one request
    may cause (`route_retries` re-dispatches, exponential backoff)."""

    def __init__(self,
                 poll_interval_s: float = 0.5,
                 health_timeout_s: float = 2.0,
                 alpha: float = 0.5,
                 suspect_threshold: float = 0.4,
                 readmit_threshold: float = 0.2,
                 readmit: int = 2,
                 fail_dead: int = 3,
                 latency_bad_s: float = 1.0,
                 request_timeout_s: float = 120.0,
                 route_retries: int = 2,
                 backoff_s: float = 0.25,
                 backoff_max_s: float = 2.0,
                 hedge_ms: float = 0.0,
                 affinity_tokens: int = 32,
                 affinity_capacity: int = 512,
                 drain_timeout_s: float = 60.0):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if not 0.0 < readmit_threshold < suspect_threshold <= 1.0:
            raise ValueError(
                "need 0 < readmit_threshold < suspect_threshold <= 1, "
                f"got {readmit_threshold} / {suspect_threshold}")
        if readmit < 1 or fail_dead < 1:
            raise ValueError("readmit/fail_dead must be >= 1")
        if route_retries < 0:
            raise ValueError("route_retries must be >= 0")
        if latency_bad_s <= 0 or poll_interval_s <= 0:
            raise ValueError("latency_bad_s/poll_interval_s must be > 0")
        if hedge_ms < 0:
            raise ValueError("hedge_ms must be >= 0")
        self.poll_interval_s = float(poll_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.alpha = float(alpha)
        self.suspect_threshold = float(suspect_threshold)
        self.readmit_threshold = float(readmit_threshold)
        self.readmit = int(readmit)
        self.fail_dead = int(fail_dead)
        self.latency_bad_s = float(latency_bad_s)
        self.request_timeout_s = float(request_timeout_s)
        self.route_retries = int(route_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.hedge_ms = float(hedge_ms)
        self.affinity_tokens = int(affinity_tokens)
        self.affinity_capacity = int(affinity_capacity)
        self.drain_timeout_s = float(drain_timeout_s)


class _Replica:
    """One replica's registry record (internal; guarded by the
    registry lock)."""

    __slots__ = ("name", "url", "state", "score", "fail_streak",
                 "ok_streak", "in_flight", "last_ok", "epoch")

    def __init__(self, name: str, url: str):
        self.name = name
        self.url = url
        self.state = REPLICA_HEALTHY
        self.score = 0.0
        self.fail_streak = 0     # consecutive failed polls
        self.ok_streak = 0       # consecutive clean polls toward readmit
        self.in_flight = 0
        self.last_ok = 0.0       # monotonic stamp of the last OK poll
        self.epoch = 0           # supervisor respawn incarnation


class ReplicaRegistry:
    """The routing table: replica lifecycle + prefix-affinity scoring.

    Pure logic under one lock — `observe()` folds one health poll,
    `pick()` chooses a route — so the whole decision matrix is
    unit-testable without a socket in sight (tests/test_router.py)."""

    def __init__(self, policy: Optional[RouterPolicy] = None):
        self.policy = policy or RouterPolicy()
        self._lock = make_lock("router.registry")
        self._replicas: Dict[str, _Replica] = {}
        # leading-token key -> replica name, LRU-bounded: the sticky
        # prefix-affinity map (shared: traffic keeps hitting the
        # replica whose trie holds the pages)
        self._affinity: "OrderedDict[Tuple[int, ...], str]" = OrderedDict()
        self.transitions: List[Tuple[str, str, str, str]] = []

    # -- membership -------------------------------------------------------

    def add(self, name: str, url: str,
            state: str = REPLICA_HEALTHY) -> None:
        """Register a replica. `state` is the entry state: the
        autoscaler adds a freshly spawned replica as REPLICA_SUSPECT so
        it is warm-up gated — `pick()` never prefers it over a healthy
        replica, and it only earns traffic through the same `readmit`
        consecutive-clean-poll confirmation a recovered replica does."""
        if state not in STATE_CODES:
            raise ValueError(f"unknown replica state {state!r}")
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            r = _Replica(name, url)
            r.state = state
            self._replicas[name] = r
            # PL501: this replica's label matrix exists from this instant
            _M_STATE.set(float(STATE_CODES[state]), replica=name)
            _M_SCORE.set(0.0, replica=name)
            _M_INFLIGHT.set(0.0, replica=name)
            if state != REPLICA_HEALTHY:
                self.transitions.append((name, "new", state, "added"))

    def remove(self, name: str) -> None:
        """Deregister a replica (autoscale scale-down, after its drain
        + migration completed). Its affinity entries must already have
        been reassigned; any stragglers are dropped so `pick()` never
        resolves to a ghost."""
        with self._lock:
            r = self._replicas.pop(name, None)
            if r is None:
                return
            for key in [k for k, v in self._affinity.items() if v == name]:
                del self._affinity[key]
            self.transitions.append((name, r.state, "removed", "scale-in"))
            # park the gauges at the dead code: the label matrix stays
            # declared (PL501) but reads as not-serving
            _M_STATE.set(float(STATE_CODES[REPLICA_DEAD]), replica=name)
            _M_INFLIGHT.set(0.0, replica=name)
        logger.info("replica %s: %s -> removed (scale-in)", name, r.state)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def url_of(self, name: str) -> str:
        with self._lock:
            return self._replicas[name].url

    def state_of(self, name: str) -> str:
        with self._lock:
            return self._replicas[name].state

    def score_of(self, name: str) -> float:
        with self._lock:
            return self._replicas[name].score

    # -- lifecycle --------------------------------------------------------

    def _move(self, r: _Replica, to: str, reason: str) -> str:
        frm = r.state
        r.state = to
        r.ok_streak = 0
        self.transitions.append((r.name, frm, to, reason))
        _M_STATE.set(float(STATE_CODES[to]), replica=r.name)
        logger.info("replica %s: %s -> %s (%s)", r.name, frm, to, reason)
        return to

    def observe(self, name: str, ok: bool,
                latency_s: Optional[float] = None,
                epoch: Optional[int] = None) -> Optional[str]:
        """Fold one health poll; returns the state transitioned TO, if
        this poll fired one. A failed poll is instant degradation 1.0; a
        clean one degrades linearly in its latency up to
        `latency_bad_s` (a replica that answers in 2x the anchor is as
        suspect as one that doesn't answer)."""
        pol = self.policy
        with self._lock:
            r = self._replicas[name]
            if epoch is not None:
                r.epoch = int(epoch)
            if ok:
                r.fail_streak = 0
                r.last_ok = time.monotonic()
                d = min(1.0, max(0.0, (latency_s or 0.0)
                                 / pol.latency_bad_s))
            else:
                r.fail_streak += 1
                d = 1.0
            r.score = (1.0 - pol.alpha) * r.score + pol.alpha * d
            _M_SCORE.set(r.score, replica=name)
            clean = ok and r.score <= pol.readmit_threshold
            r.ok_streak = r.ok_streak + 1 if clean else 0

            if not ok and r.fail_streak >= pol.fail_dead \
                    and r.state != REPLICA_DEAD:
                return self._move(r, REPLICA_DEAD,
                                  f"{r.fail_streak} consecutive poll "
                                  "failures")
            if r.state == REPLICA_HEALTHY:
                if r.score >= pol.suspect_threshold:
                    return self._move(r, REPLICA_SUSPECT,
                                      f"score {r.score:.3f} >= "
                                      f"{pol.suspect_threshold}")
                return None
            if r.state in (REPLICA_SUSPECT, REPLICA_DEAD):
                # hysteresis + confirmation: readmit needs `readmit`
                # consecutive clean polls BELOW the readmit threshold —
                # a score oscillating in the band changes nothing, and
                # a respawned process must prove itself before traffic
                if r.ok_streak >= pol.readmit:
                    return self._move(r, REPLICA_HEALTHY,
                                      f"{r.ok_streak} clean polls, score "
                                      f"{r.score:.3f}")
                return None
            return None     # drained: administrative, polls don't exit it

    def mark_failed(self, name: str) -> None:
        """Request-path hard failure (connection refused/reset): convict
        NOW — the poll loop would take `fail_dead` more windows to
        notice, and every routed request in between would fail too."""
        with self._lock:
            r = self._replicas[name]
            r.score = 1.0
            _M_SCORE.set(1.0, replica=name)
            if r.state != REPLICA_DEAD:
                self._move(r, REPLICA_DEAD, "request connection failure")

    def drain(self, name: str) -> bool:
        """Administratively stop routing to `name` (planned
        maintenance). Returns False when the replica is already dead —
        there is nothing graceful left to do."""
        with self._lock:
            r = self._replicas[name]
            if r.state == REPLICA_DEAD:
                return False
            if r.state != REPLICA_DRAINED:
                self._move(r, REPLICA_DRAINED, "drain requested")
            return True

    def undrain(self, name: str) -> None:
        """Lift a drain on a still-running external replica (supervised
        drains end in a respawn instead, which readmits via observe)."""
        with self._lock:
            r = self._replicas[name]
            if r.state == REPLICA_DRAINED:
                self._move(r, REPLICA_SUSPECT, "drain lifted; reproving")

    # -- routing ----------------------------------------------------------

    def _affinity_key(self, tokens: Sequence[int]) \
            -> Optional[Tuple[int, ...]]:
        if not tokens:
            return None
        return tuple(int(t) for t in
                     tokens[:self.policy.affinity_tokens])

    def pick(self, tokens: Optional[Sequence[int]] = None,
             exclude: Iterable[str] = ()) -> Optional[str]:
        """Choose a route: the prompt's affinity owner when it is
        routable, else the least-loaded routable replica (healthy
        first; suspect replicas only when no healthy one exists —
        degraded-but-alive beats shedding). Learns the affinity of a
        fresh prefix on the way out."""
        shut = set(exclude)
        with self._lock:
            healthy = [r for r in self._replicas.values()
                       if r.state == REPLICA_HEALTHY and r.name not in shut]
            pool = healthy or [
                r for r in self._replicas.values()
                if r.state == REPLICA_SUSPECT and r.name not in shut]
            if not pool:
                return None
            key = self._affinity_key(tokens) if tokens is not None else None
            if key is not None:
                owner = self._affinity.get(key)
                if owner is not None and any(r.name == owner
                                             for r in pool):
                    self._affinity.move_to_end(key)
                    return owner
            choice = min(pool, key=lambda r: (r.in_flight, r.name))
            if key is not None:
                self._affinity[key] = choice.name
                self._affinity.move_to_end(key)
                while len(self._affinity) > self.policy.affinity_capacity:
                    self._affinity.popitem(last=False)
            return choice.name

    def affinity_owner(self, tokens: Sequence[int]) -> Optional[str]:
        key = self._affinity_key(tokens)
        with self._lock:
            return self._affinity.get(key) if key is not None else None

    def affinity_keys_of(self, name: str) -> List[Tuple[int, ...]]:
        """Every affinity key currently routed to `name` (the drain
        migration's work list — these prompts' pages are warm there)."""
        with self._lock:
            return [k for k, v in self._affinity.items() if v == name]

    def reassign_affinity(self, frm: str, to: str) -> int:
        """Point `frm`'s affinity entries at `to` (their pages just
        migrated there, or `frm` died and `to` will re-prefill them)."""
        with self._lock:
            moved = 0
            for k, v in self._affinity.items():
                if v == frm:
                    self._affinity[k] = to
                    moved += 1
            return moved

    def note_route(self, name: str) -> None:
        with self._lock:
            r = self._replicas[name]
            r.in_flight += 1
            _M_INFLIGHT.set(float(r.in_flight), replica=name)

    def done(self, name: str) -> None:
        with self._lock:
            r = self._replicas[name]
            r.in_flight = max(0, r.in_flight - 1)
            _M_INFLIGHT.set(float(r.in_flight), replica=name)

    def snapshot(self) -> Dict[str, dict]:
        """Per-replica state for the router's /healthz fleet block."""
        now = time.monotonic()
        with self._lock:
            return {r.name: {
                "url": r.url,
                "state": r.state,
                "score": round(r.score, 4),
                "in_flight": r.in_flight,
                "epoch": r.epoch,
                "fail_streak": r.fail_streak,
                "last_ok_age_s": (round(now - r.last_ok, 3)
                                  if r.last_ok else None),
            } for r in self._replicas.values()}


# -- HTTP plumbing (injectable for tests) ---------------------------------

def http_post_json(url: str, path: str, payload: dict,
                   timeout: float,
                   headers: Optional[Dict[str, str]] = None) \
        -> Tuple[int, dict, List[Tuple[str, str]]]:
    """POST one JSON body; returns (status, body, passthrough headers).
    HTTP error statuses are RETURNED (they are answers — a 503 shed
    must flow back to the client with its Retry-After); transport
    failures raise OSError for the caller's failover logic. `headers`
    adds per-request headers (the rid/hop propagation pair)."""
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(
        f"{url}{path}", data=json.dumps(payload).encode(),
        headers=hdrs, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = json.loads(resp.read() or b"{}")
            return resp.status, body, _passthrough(resp.headers)
    except urllib.error.HTTPError as exc:
        body = json.loads(exc.read() or b"{}")
        return exc.code, body, _passthrough(exc.headers)


def http_get_json(url: str, path: str, timeout: float) -> Tuple[int, dict]:
    try:
        with urllib.request.urlopen(f"{url}{path}",
                                    timeout=timeout) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


def _passthrough(headers) -> List[Tuple[str, str]]:
    out = []
    if headers is None:
        return out
    for h in ("Retry-After", RID_HEADER, REPLICA_HEADER):
        v = headers.get(h)
        if v is not None:
            out.append((h, v))
    return out


def _with_identity(headers: Iterable[Tuple[str, str]], rid: Optional[str],
                   replica: Optional[str]) -> List[Tuple[str, str]]:
    """Make the router authoritative for the identity echo: drop any
    replica-echoed rid/replica headers and append the BASE rid plus the
    replica that actually served (None skips that header)."""
    out = [(h, v) for h, v in headers
           if h not in (RID_HEADER, REPLICA_HEADER)]
    if rid is not None:
        out.append((RID_HEADER, rid))
    if replica is not None:
        out.append((REPLICA_HEADER, replica))
    return out


def _adapt_post_fn(fn: Callable) -> Callable:
    """Tolerate injected post fns written against the pre-observatory
    4-arg signature (url, path, payload, timeout): drop the `headers`
    kwarg when the fn cannot take it."""
    try:
        sig = inspect.signature(fn)
        takes_headers = any(
            p.name == "headers" or p.kind == p.VAR_KEYWORD
            for p in sig.parameters.values())
    except (TypeError, ValueError):      # builtins/C callables: assume new
        takes_headers = True
    if takes_headers:
        return fn

    def adapted(url, path, payload, timeout, headers=None):
        return fn(url, path, payload, timeout)
    return adapted


class _ReplicaStreamError(RuntimeError):
    """A replica surfaced a terminal {"error": ...} line mid-stream
    (its executor died under the request) — failover-eligible, but not
    a transport conviction."""


class DecodeRouter:
    """The proxy: routes, retries, hedges, fails over, drains.

    `post_fn`/`get_fn`/`stream_fn` are injectable so the decision logic
    tests without sockets; production uses the urllib defaults."""

    def __init__(self, replicas: Dict[str, str],
                 policy: Optional[RouterPolicy] = None,
                 supervisor=None,
                 post_fn: Optional[Callable] = None,
                 get_fn: Optional[Callable] = None):
        self.policy = policy or RouterPolicy()
        self.registry = ReplicaRegistry(self.policy)
        self.supervisor = supervisor
        self._post = (_adapt_post_fn(post_fn) if post_fn is not None
                      else http_post_json)
        self._get = get_fn or http_get_json
        # rid mint: the router is the root of every request's rid tree
        self._rid_lock = make_lock("router.rids")
        self._next_rid = 0
        self._stop = threading.Event()
        self._poller: Optional[threading.Thread] = None
        # replica-name -> supervisor rank (supervised fleets only):
        # lets a drain end in a respawn and the poll loop surface epochs
        self._ranks: Dict[str, int] = {}
        # router-side prefix registrations: router prefix id ->
        # {"tokens": [...], "replicas": {name: replica_prefix_id}}
        self._prefix_lock = make_lock("router.prefixes")
        self._prefixes: Dict[str, dict] = {}
        self._next_prefix = 0
        # latest raw /healthz body per replica (fleet block passthrough)
        self._health_lock = make_lock("router.health_cache")
        self._health: Dict[str, dict] = {}
        for name, url in replicas.items():
            self.registry.add(name, url)

    def bind_rank(self, name: str, rank: int) -> None:
        self._ranks[name] = int(rank)

    def mint_rid(self) -> str:
        """Router-minted request ids (`R<n>`): the root of the
        derivation tree `rid[.tN|.hedge|.foN]*` — distinct from the
        replica-local `q<n>` mint, which now only fires for direct
        (unrouted) requests."""
        with self._rid_lock:
            n = self._next_rid
            self._next_rid += 1
        return f"R{n}"

    @staticmethod
    def _clean_rid(raw: Optional[str]) -> Optional[str]:
        """Accept a caller-supplied rid if it is sane (printable,
        bounded — it lands in headers, logs, and span rings)."""
        if not raw or not isinstance(raw, str):
            return None
        rid = raw.strip()
        if not rid or len(rid) > 128 or not rid.isprintable():
            return None
        return rid

    # -- health poll loop -------------------------------------------------

    def start(self) -> None:
        self._poller = threading.Thread(target=self._poll_loop,
                                        daemon=True, name="router-poll")
        self._poller.start()

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=5)

    def _poll_once(self) -> None:
        pol = self.policy
        sup_snap = self.supervisor.snapshot() if self.supervisor else {}
        for name, rec in self.registry.snapshot().items():
            t0 = time.monotonic()
            try:
                status, body = self._get(rec["url"], "/healthz",
                                         pol.health_timeout_s)
                ok = status == 200 and bool(body.get("ok", False))
            except (OSError, ValueError):
                ok, body = False, None
            latency = time.monotonic() - t0
            epoch = None
            rank = self._ranks.get(name)
            if rank is not None and str(rank) in sup_snap:
                epoch = sup_snap[str(rank)]["epoch"]
            self.registry.observe(name, ok, latency_s=latency,
                                  epoch=epoch)
            if body is not None:
                with self._health_lock:
                    self._health[name] = body

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.policy.poll_interval_s):
            with telemetry.span("router", "health_poll"):
                self._poll_once()

    # -- /healthz ---------------------------------------------------------

    def healthz(self) -> Tuple[int, dict]:
        fleet = self.registry.snapshot()
        with self._health_lock:
            for name, rec in fleet.items():
                body = self._health.get(name)
                if body is not None:
                    rec["draining"] = bool(body.get("draining", False))
                    rec["active"] = (body.get("stats") or {}).get(
                        "active")
        routable = any(rec["state"] in (REPLICA_HEALTHY, REPLICA_SUSPECT)
                       for rec in fleet.values())
        out = {"ok": routable, "role": "router", "fleet": fleet}
        if self.supervisor is not None:
            out["workers"] = self.supervisor.snapshot()
        return (200 if routable else 503), out

    def health_snapshot(self) -> Dict[str, dict]:
        """Copy of the latest raw /healthz body per replica — the fleet
        collector mines it for nested scrape targets (prefill-worker
        observability URLs a replica reports under
        serving.kv.prefill.workers)."""
        with self._health_lock:
            return dict(self._health)

    def scrape_targets(self) -> Dict[str, str]:
        """The fleet collector's CURRENT target set: every registered
        replica, plus any prefill-worker observability endpoints the
        replicas report in their health bodies."""
        targets = {name: rec["url"]
                   for name, rec in self.registry.snapshot().items()}
        for name, body in self.health_snapshot().items():
            workers = ((((body.get("serving") or {}).get("kv") or {})
                        .get("prefill") or {}).get("workers") or {})
            if not isinstance(workers, dict):
                continue
            for rank, rec in workers.items():
                url = (rec or {}).get("http_url")
                if url:
                    targets[f"{name}.pf{rank}"] = url
        return targets

    # -- the routed request path ------------------------------------------

    @staticmethod
    def _route_tokens(payload: dict) -> Optional[List[int]]:
        ids = payload.get("ids")
        if not ids:
            return None
        row = ids[0] if isinstance(ids[0], list) else ids
        return row if row and all(isinstance(t, int) for t in row) \
            else None

    def _prepare(self, name: str, payload: dict) -> dict:
        """Per-attempt payload rewrite: a router-level prefix_id becomes
        the TARGET replica's prefix id (registered there lazily — and
        re-registered on the failover target when the first choice
        died)."""
        rp = payload.get("prefix_id")
        if rp is None:
            return payload
        with self._prefix_lock:
            entry = self._prefixes.get(rp)
        if entry is None:
            # not ours: pass through (a raw replica id still works on
            # a single-replica fleet; anything else 400s at the replica)
            return payload
        replica_pid = entry["replicas"].get(name)
        if replica_pid is None:
            status, body, _ = self._post(
                self.registry.url_of(name), "/prefix",
                {"ids": entry["tokens"]}, self.policy.request_timeout_s)
            if status != 200:
                raise OSError(f"prefix registration on {name} failed "
                              f"({status}): {body.get('error')}")
            replica_pid = body["prefix_id"]
            with self._prefix_lock:
                entry["replicas"][name] = replica_pid
        out = dict(payload)
        out["prefix_id"] = replica_pid
        return out

    def register_prefix(self, ids: Sequence[int]) -> Tuple[str, int]:
        """Router-level /prefix: remember the tokens; replicas get the
        registration lazily at first routed use (and again on
        failover targets)."""
        tokens = [int(t) for t in ids]
        with self._prefix_lock:
            pid = f"rp{self._next_prefix}"
            self._next_prefix += 1
            self._prefixes[pid] = {"tokens": tokens, "replicas": {}}
        return pid, len(tokens)

    def _prefix_tokens(self, payload: dict) -> Optional[List[int]]:
        rp = payload.get("prefix_id")
        if rp is not None:
            with self._prefix_lock:
                entry = self._prefixes.get(rp)
            if entry is not None:
                return list(entry["tokens"])
        return self._route_tokens(payload)

    def dispatch(self, payload: dict, path: str = "/generate") \
            -> Tuple[int, dict, List[Tuple[str, str]]]:
        """Route one non-streaming request: bounded
        retry-with-backoff to a DIFFERENT replica on transport failure
        (the failed one is convicted immediately), one shed-retry hop
        on a replica 503 (another replica may have capacity). Terminal
        outcomes land in pipeedge_router_requests_total."""
        rid = self._clean_rid(payload.get("rid")) or self.mint_rid()
        if self.policy.hedge_ms > 0 \
                and payload.get("class", "interactive") == "interactive" \
                and not payload.get("stream"):
            return self._dispatch_hedged(payload, path, rid=rid)
        return self._dispatch_plain(payload, path, exclude=(), rid=rid)

    def _dispatch_plain(self, payload: dict, path: str,
                        exclude: Iterable[str],
                        rid: Optional[str] = None) \
            -> Tuple[int, dict, List[Tuple[str, str]]]:
        pol = self.policy
        rid = rid or self._clean_rid(payload.get("rid")) or self.mint_rid()
        tokens = self._prefix_tokens(payload)
        tried = list(exclude)
        backoff = pol.backoff_s
        retries_left = pol.route_retries
        attempt = 0
        while True:
            name = self.registry.pick(tokens, exclude=tried)
            if name is None:
                _M_REQUESTS.inc(outcome="no_replica")
                return 503, {"error": "no routable replica",
                             "no_replica": True}, \
                    _with_identity([("Retry-After", "1")], rid, None)
            # attempt 0 rides the base rid; every re-dispatch derives a
            # child (`rid.tN`) so the logical request stays one tree
            arid = rid if attempt == 0 else f"{rid}.t{attempt}"
            self.registry.note_route(name)
            try:
                body = self._prepare(name, payload)
                if "rid" in body:
                    body = {k: v for k, v in body.items() if k != "rid"}
                with telemetry.span("router", f"dispatch:{name}",
                                    rid=arid):
                    status, out, headers = self._post(
                        self.registry.url_of(name), path, body,
                        pol.request_timeout_s,
                        headers={RID_HEADER: arid,
                                 HOP_HEADER: str(attempt)})
            except OSError as exc:
                self.registry.mark_failed(name)
                tried.append(name)
                if retries_left <= 0:
                    _M_REQUESTS.inc(outcome="error")
                    return 503, {"error": f"replica {name} unreachable "
                                          f"({exc}); retries exhausted"}, \
                        _with_identity([("Retry-After", "1")], rid, None)
                retries_left -= 1
                attempt += 1
                _M_RETRIES.inc(reason="connect")
                _M_FAILOVERS.inc()
                time.sleep(backoff)
                backoff = min(backoff * 2, pol.backoff_max_s)
                continue
            finally:
                self.registry.done(name)
            if status == 503 and retries_left > 0 \
                    and len(tried) + 1 < len(self.registry.names()):
                # shed here does not mean shed everywhere: spend one
                # retry on a different replica before surfacing it
                tried.append(name)
                retries_left -= 1
                attempt += 1
                _M_RETRIES.inc(reason="shed")
                continue
            _M_REQUESTS.inc(outcome=self._outcome(status, out))
            headers = _with_identity(headers, rid, name)
            if status == 503 and not any(h == "Retry-After"
                                         for h, _ in headers):
                headers = list(headers) + [("Retry-After", "1")]
            return status, out, headers

    @staticmethod
    def _outcome(status: int, body: dict) -> str:
        if status == 200:
            return "ok"
        if status == 503:
            return "shed"
        if status == 504:
            return "deadline"
        return "error"

    def _dispatch_hedged(self, payload: dict, path: str,
                         rid: Optional[str] = None) \
            -> Tuple[int, dict, List[Tuple[str, str]]]:
        """Tail hedging for the interactive class: if the primary has
        not answered within `hedge_ms`, duplicate the request to a
        second replica and take whichever answers first — decode is
        deterministic, so either answer is THE answer. The hedge branch
        rides the derived rid `rid.hedge` (its own retries nest:
        `rid.hedge.t1`)."""
        rid = rid or self._clean_rid(payload.get("rid")) or self.mint_rid()
        tokens = self._prefix_tokens(payload)
        primary = self.registry.pick(tokens)
        if primary is None:
            _M_REQUESTS.inc(outcome="no_replica")
            return 503, {"error": "no routable replica",
                         "no_replica": True}, \
                _with_identity([("Retry-After", "1")], rid, None)
        results: "queue.Queue" = queue.Queue()

        def run(branch: str, exclude: Iterable[str]) -> None:
            brid = rid if branch == "primary" else f"{rid}.hedge"
            try:
                results.put((branch,
                             self._dispatch_plain(payload, path, exclude,
                                                  rid=brid)))
            except BaseException as exc:   # noqa: BLE001 — joined below
                results.put((branch, exc))

        t1 = threading.Thread(target=run, args=("primary", ()),
                              daemon=True, name="router-hedge-primary")
        t1.start()
        try:
            branch, result = results.get(
                timeout=self.policy.hedge_ms / 1e3)
        except queue.Empty:
            hedge_target = self.registry.pick(tokens, exclude=[primary])
            if hedge_target is not None:
                t2 = threading.Thread(target=run,
                                      args=("hedge", [primary]),
                                      daemon=True,
                                      name="router-hedge-secondary")
                t2.start()
            branch, result = results.get()
            _M_HEDGES.inc(winner=branch)
        if isinstance(result, BaseException):
            raise result
        status, out, headers = result
        # whichever branch won, the client is told the BASE rid — the
        # resolvable root of the whole hedge tree
        served = next((v for h, v in headers if h == REPLICA_HEADER),
                      None)
        return status, out, _with_identity(headers, rid, served)

    def stream(self, payload: dict):
        """Route one STREAMING request; yields ("status", code,
        headers) first, then ("line", obj) x-ndjson lines. Mid-stream
        replica death re-dispatches the whole request to a survivor
        and suppresses the first `emitted` step lines — deterministic
        decode makes the continuation token-identical (the re-prefill
        recovery path; a drained replica's pages migrate instead).

        Rid derivation: the first dispatch rides the base rid, each
        failover replay derives `rid.foN`, each shed-retry hop
        `rid.tN`. The 200 status (with X-PipeEdge-Rid/-Replica
        headers) is held until the first line actually reaches the
        client, so a pre-first-byte failover names the SURVIVOR in the
        response headers; once streaming has begun the terminal line
        carries `replica` instead (headers are already on the wire)."""
        pol = self.policy
        rid = self._clean_rid(payload.get("rid")) or self.mint_rid()
        tokens = self._prefix_tokens(payload)
        tried: List[str] = []
        emitted = 0
        started = False     # 200 headers already yielded to the client
        retries_left = pol.route_retries
        backoff = pol.backoff_s
        failovers = 0
        shed_hops = 0
        while True:
            name = self.registry.pick(tokens, exclude=tried)
            if name is None:
                _M_REQUESTS.inc(outcome="no_replica")
                if not started:
                    yield ("status", 503,
                           _with_identity([("Retry-After", "1")], rid,
                                          None))
                yield ("line", {"error": "no routable replica",
                                "no_replica": True, "rid": rid})
                return
            if failovers == 0 and shed_hops == 0:
                arid = rid
            elif failovers > 0:
                arid = f"{rid}.fo{failovers}"
            else:
                arid = f"{rid}.t{shed_hops}"
            self.registry.note_route(name)
            failure = None
            try:
                body = self._prepare(name, payload)
                if "rid" in body:
                    body = {k: v for k, v in body.items() if k != "rid"}
                skip = emitted
                terminal = False
                with telemetry.span("router", f"stream:{name}",
                                    rid=arid):
                    for kind, item in self._stream_from(
                            name, body, rid=arid,
                            hop=failovers + shed_hops):
                        if kind == "refusal":
                            code, headers, rbody = item
                            if code == 503 and retries_left > 0 \
                                    and len(tried) + 1 \
                                    < len(self.registry.names()):
                                # shed here != shed everywhere: spend a
                                # retry on a different replica first
                                failure = "shed"
                                break
                            if not started:
                                headers = _with_identity(headers, rid,
                                                         name)
                                if code == 503 and not any(
                                        h == "Retry-After"
                                        for h, _ in headers):
                                    headers = list(headers) + [
                                        ("Retry-After", "1")]
                                yield ("status", code, headers)
                                started = True
                            yield ("line", rbody)
                            _M_REQUESTS.inc(
                                outcome=self._outcome(code, rbody))
                            terminal = True
                            break
                        if kind == "ok":
                            # hold the 200 until the first line: a
                            # failover before first byte then names
                            # the survivor in the response headers
                            continue
                        obj = item
                        if "step" in obj:
                            if skip > 0:
                                # this replica is replaying a failed-
                                # over request from step 0: the client
                                # already has these tokens
                                skip -= 1
                                continue
                            emitted += 1
                            if not started:
                                yield ("status", 200,
                                       _with_identity([], rid, name))
                                started = True
                            yield ("line", obj)
                        elif "error" in obj:
                            raise _ReplicaStreamError(
                                str(obj.get("error")))
                        else:
                            # the terminal line: annotate who actually
                            # served and the base rid (replayed streams
                            # already sent headers naming the first
                            # replica)
                            obj = dict(obj)
                            obj["replica"] = name
                            # the BASE rid, not this leg's derived one:
                            # the client resolves the whole tree from it
                            obj["rid"] = rid
                            if not started:
                                yield ("status", 200,
                                       _with_identity([], rid, name))
                                started = True
                            yield ("line", obj)
                            _M_REQUESTS.inc(outcome="ok")
                            terminal = True
                            break
                if terminal:
                    return
                if failure is None:
                    # the iterator ended with no terminal line: the
                    # socket dropped mid-body (replica death)
                    raise OSError("stream truncated")
            except OSError:
                self.registry.mark_failed(name)
                failure = "connect"
            except _ReplicaStreamError:
                failure = "connect"
            finally:
                self.registry.done(name)
            tried.append(name)
            if retries_left <= 0:
                _M_REQUESTS.inc(outcome="error")
                if not started:
                    yield ("status", 503,
                           _with_identity([("Retry-After", "1")], rid,
                                          None))
                yield ("line", {"error": f"replica {name} failed; "
                                         "retries exhausted",
                                "rid": rid})
                return
            retries_left -= 1
            _M_RETRIES.inc(reason=failure)
            if failure == "connect":
                failovers += 1
                _M_FAILOVERS.inc()
            else:
                shed_hops += 1
            time.sleep(backoff)
            backoff = min(backoff * 2, pol.backoff_max_s)

    def _stream_from(self, name: str, payload: dict,
                     rid: Optional[str] = None, hop: int = 0):
        """One replica's streaming response: ("refusal", (code,
        headers, body)) for a pre-stream non-200 (shed/400 — complete
        and terminal), else ("ok", None) then ("line", obj) per
        x-ndjson line. Transport failures raise OSError into
        stream()'s failover arm. `rid`/`hop` propagate on the request
        headers (the per-attempt derived rid)."""
        url = self.registry.url_of(name)
        hdrs = {"Content-Type": "application/json"}
        if rid is not None:
            hdrs[RID_HEADER] = rid
            hdrs[HOP_HEADER] = str(hop)
        req = urllib.request.Request(
            f"{url}/generate", data=json.dumps(payload).encode(),
            headers=hdrs, method="POST")
        try:
            resp = urllib.request.urlopen(
                req, timeout=self.policy.request_timeout_s)
        except urllib.error.HTTPError as exc:
            try:
                body = json.loads(exc.read() or b"{}")
            except ValueError:
                body = {"error": f"replica {name} answered {exc.code}"}
            yield ("refusal", (exc.code, _passthrough(exc.headers),
                               body))
            return
        with resp:
            if resp.status != 200:
                yield ("refusal", (resp.status,
                                   _passthrough(resp.headers), {}))
                return
            yield ("ok", None)
            for raw in resp:
                raw = raw.strip()
                if not raw:
                    continue
                try:
                    yield ("line", json.loads(raw))
                except ValueError as exc:
                    raise OSError(f"malformed stream line from {name}: "
                                  f"{raw[:80]!r}") from exc

    # -- graceful drain + KV migration ------------------------------------

    def drain_replica(self, name: str, migrate: bool = True,
                      respawn: bool = True) -> dict:
        """Planned maintenance: stop routing to `name`, let its
        in-flight requests finish, ship its warm prefix pages to a
        survivor over the kv/ship.py codec, then detach (supervised
        replicas are restarted — the respawn readmits with epoch+1;
        external ones stay drained). `respawn=False` is the autoscale
        scale-in half: the drained replica is NOT restarted — the
        caller retires its supervisor rank and removes it from the
        registry once this returns."""
        pol = self.policy
        if not self.registry.drain(name):
            return {"drained": False, "error": f"replica {name} is dead"}
        _M_DRAINS.inc()
        with telemetry.span("router", f"drain:{name}"):
            url = self.registry.url_of(name)
            try:
                self._post(url, "/drain", {}, pol.request_timeout_s)
            except OSError:
                self.registry.mark_failed(name)
                return {"drained": False,
                        "error": f"replica {name} died during drain"}
            deadline = time.monotonic() + pol.drain_timeout_s
            while time.monotonic() < deadline:
                try:
                    _, body = self._get(url, "/healthz",
                                        pol.health_timeout_s)
                except (OSError, ValueError):
                    break
                active = (body.get("stats") or {}).get("active", 0)
                if not active:
                    break
                time.sleep(0.2)
            migrated = 0
            target = self.registry.pick()
            if migrate and target is not None:
                migrated = self._migrate_prefixes(name, target)
                self.registry.reassign_affinity(name, target)
            if respawn and self.supervisor is not None \
                    and name in self._ranks:
                self.supervisor.restart(self._ranks[name])
        return {"drained": True, "migrated_prefixes": migrated,
                "target": target}

    # -- autoscale membership actuators -----------------------------------

    def add_replica(self, name: str, url: str,
                    rank: Optional[int] = None) -> None:
        """Scale-out actuator: register a freshly spawned replica
        warm-up gated (REPLICA_SUSPECT — it earns traffic through the
        readmit confirmation, never before its first clean polls)."""
        self.registry.add(name, url, state=REPLICA_SUSPECT)
        if rank is not None:
            self.bind_rank(name, rank)

    def remove_replica(self, name: str) -> dict:
        """Scale-in actuator: graceful drain + KV-prefix migration,
        then deregister without respawn. Returns the drain result with
        `removed` set; a replica that dies mid-drain is still removed
        (it was leaving anyway — `mark_failed` already convicted it)."""
        out = self.drain_replica(name, migrate=True, respawn=False)
        self.registry.remove(name)
        with self._health_lock:
            self._health.pop(name, None)
        rank = self._ranks.pop(name, None)
        out["removed"] = True
        out["rank"] = rank
        return out

    def _migrate_prefixes(self, frm: str, to: str) -> int:
        """Ship `frm`'s warm prefixes to `to`: every router-registered
        prefix `frm` holds plus every affinity key routed there (the
        shared: workload's warm pages). Best-effort per prefix — a
        failed export falls back to re-prefill on first use."""
        pol = self.policy
        src, dst = self.registry.url_of(frm), self.registry.url_of(to)
        work: Dict[Tuple[int, ...], List[int]] = {}
        with self._prefix_lock:
            for entry in self._prefixes.values():
                if frm in entry["replicas"]:
                    work[tuple(entry["tokens"])] = list(entry["tokens"])
        for key in self.registry.affinity_keys_of(frm):
            work.setdefault(key, list(key))
        migrated = 0
        for tokens in work.values():
            try:
                status, body, _ = self._post(
                    src, "/kv/export", {"ids": tokens},
                    pol.request_timeout_s)
                if status != 200 or not body.get("pages"):
                    continue
                status, body, _ = self._post(
                    dst, "/kv/import",
                    {"ids": tokens, "blob": body["blob"]},
                    pol.request_timeout_s)
                if status == 200 and body.get("installed_pages", 0) >= 0:
                    migrated += 1
                    _M_MIGRATED.inc()
            except OSError as exc:
                logger.warning("prefix migration %s -> %s failed: %s",
                               frm, to, exc)
        return migrated


def encode_ship_blob(frames) -> str:
    """kv/ship.py tensor frames -> the JSON-safe base64 form the
    /kv/export|import endpoints carry."""
    from ..kv import ship
    return base64.b64encode(ship.frames_to_bytes(frames)).decode()


def decode_ship_blob(blob: str):
    from ..kv import ship
    return ship.frames_from_bytes(base64.b64decode(blob))
