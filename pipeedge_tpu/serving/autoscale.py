"""Closed-loop capacity control over the membership plane (ROADMAP item 4).

Every mechanical piece of an autoscaler already exists in this tree —
PR 5's membership plane (benched spares, epoch fencing,
`sched/rebalance.py expand_partition`), PR 12's peer-health detection,
PR 17's router drain/respawn, PR 18's SLO burn-rate gauges — but until
now a human closed the loop. `CapacityController` is that loop: a
governor-ticked decision engine that consumes the signals the fleet
already publishes (admission queue depth, brownout rung,
`pipeedge_slo_burn_rate{class,window}`, bubble/compute attribution) and
drives capacity through existing actuators at two levels:

- **replica level** (tools/serve.py `--role router --autoscale`): spawn
  a new supervised decode replica (next DCN epoch, warm-up gated — it
  joins the registry SUSPECT and earns traffic through the readmit
  confirmation) or gracefully drain one through the existing
  drain + KV-prefix-migration path, then retire the process.
- **pipeline level** (runtime.py `--autoscale-ranks`): expand a
  contracted partition onto benched spares via `sched/failover.py
  plan_rejoin` at a round boundary (scale-up = planned rejoin), or
  bench the least-needed rank (scale-down = planned bench through the
  same re-plan cascade quarantine uses, refused by the min-fleet floor).

The controller itself is built to be *convictable* — every decision
survives the PR 12 discipline before it moves anything:

    observe -> confirm -> plan -> apply | held

- **confirm**: N consecutive same-direction pressure windows (a single
  hot scrape moves nothing);
- **dwell**: time-based hysteresis in BOTH directions — the streak must
  also have *lasted* `dwell_up_s`/`dwell_down_s`;
- **cooldown + flap damper**: a decision arms a cooldown; each decision
  that REVERSES the previous direction doubles the effective cooldown
  (capped), and a confirmed decision suppressed by the damped portion
  renders as a visible `flap_damped` transition instead of silence;
- **brownout ordering**: scale-down is strictly ordered BEHIND
  brownout — capacity is never shed while the ladder sits above rung 0
  (shedding work and shedding capacity at once is how outages compound);
- **dry-run plan**: an un-runnable decision (min-fleet floor, no spare,
  no migration survivor) renders as a visible `held` transition, never
  an outage.

Modes: `off` (no controller), `advise` (decisions logged + counted but
never applied — the A/B control arm), `auto` (decisions applied).

Observability (PL501/PL502-clean, docs/OBSERVABILITY.md):
`pipeedge_autoscale_decisions_total{direction,outcome}` with the full
matrix pre-declared at import, `pipeedge_fleet_target_size` /
`pipeedge_fleet_actual_size` gauges, and paired `autoscale` spans
(`plan:<dir>` / `apply:<dir>` / `held:<dir>` / `flap_damped:<dir>`)
that report.py/trace_report fold into an `autoscale` section.

Pure logic under an injectable clock (the brownout.py idiom): every
hysteresis path unit-tests without a fleet (tests/test_autoscale.py).
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import telemetry
from ..telemetry import metrics as prom

logger = logging.getLogger(__name__)

MODES = ("off", "advise", "auto")
DIRECTIONS = ("up", "down")
# decision outcomes (the counter's label domain):
#   applied     auto mode moved capacity
#   advised     advise mode would have moved capacity (the control arm)
#   held        the dry-run plan refused (floor/no-spare/no-survivor) —
#               visible, like PR 12's floor-held quarantine
#   flap_damped a confirmed decision suppressed by the flap-doubled
#               cooldown (a reversal arrived too soon after the last)
OUTCOMES = ("applied", "advised", "held", "flap_damped")

# PL501: the full direction x outcome matrix exists before any decision
_M_DECISIONS = prom.REGISTRY.counter(
    "pipeedge_autoscale_decisions_total",
    "autoscale decisions by direction (up/down) and outcome "
    "(applied / advised / held / flap_damped)")
for _d in DIRECTIONS:
    for _o in OUTCOMES:
        _M_DECISIONS.declare(direction=_d, outcome=_o)
_M_TARGET = prom.REGISTRY.gauge(
    "pipeedge_fleet_target_size",
    "capacity units the autoscaler currently wants (replicas at the "
    "router, pipeline stages under --autoscale-ranks)")
_M_ACTUAL = prom.REGISTRY.gauge(
    "pipeedge_fleet_actual_size",
    "capacity units currently serving")
_M_FLAP = prom.REGISTRY.gauge(
    "pipeedge_autoscale_cooldown_factor",
    "flap-damper multiplier on the decision cooldown (1 = calm; "
    "doubles on each direction reversal)")


class CapacityPolicy:
    """The autoscaler's knobs. The hysteresis contract mirrors
    health/scorer.py's HealthPolicy: thresholds must leave a dead band
    (`queue_low < queue_high`, `burn_low < burn_high`) so a signal
    oscillating between them changes nothing."""

    def __init__(self,
                 min_size: int = 1,
                 max_size: int = 2,
                 confirm: int = 3,
                 cooldown_s: float = 10.0,
                 dwell_up_s: float = 0.0,
                 dwell_down_s: float = 0.0,
                 queue_high: float = 4.0,
                 queue_low: float = 0.5,
                 burn_high: float = 1.0,
                 burn_low: float = 0.25,
                 flap_cap: float = 8.0):
        if not 1 <= min_size <= max_size:
            raise ValueError(f"need 1 <= min_size <= max_size, got "
                             f"{min_size}/{max_size}")
        if confirm < 1:
            raise ValueError("confirm must be >= 1")
        if cooldown_s < 0 or dwell_up_s < 0 or dwell_down_s < 0:
            raise ValueError("cooldown/dwell must be >= 0")
        if not 0.0 <= queue_low < queue_high:
            raise ValueError(f"need 0 <= queue_low < queue_high, got "
                             f"{queue_low}/{queue_high}")
        if not 0.0 <= burn_low < burn_high:
            raise ValueError(f"need 0 <= burn_low < burn_high, got "
                             f"{burn_low}/{burn_high}")
        if flap_cap < 1:
            raise ValueError("flap_cap must be >= 1")
        self.min_size = int(min_size)
        self.max_size = int(max_size)
        self.confirm = int(confirm)
        self.cooldown_s = float(cooldown_s)
        self.dwell_up_s = float(dwell_up_s)
        self.dwell_down_s = float(dwell_down_s)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        self.flap_cap = float(flap_cap)


class Decision:
    """One autoscale decision (any outcome). `line()` is the
    machine-parseable stdout form tools/chaos_dcn.py and CI grep."""

    __slots__ = ("direction", "frm", "to", "outcome", "reason", "at",
                 "plan")

    def __init__(self, direction: str, frm: int, to: int, outcome: str,
                 reason: str, at: float, plan: Optional[dict] = None):
        self.direction = direction
        self.frm = int(frm)
        self.to = int(to)
        self.outcome = outcome
        self.reason = reason
        self.at = float(at)
        self.plan = plan

    def line(self) -> str:
        return (f"autoscale_decision direction={self.direction} "
                f"from={self.frm} to={self.to} outcome={self.outcome} "
                f"reason={self.reason}")

    def to_dict(self) -> dict:
        return {"direction": self.direction, "from": self.frm,
                "to": self.to, "outcome": self.outcome,
                "reason": self.reason, "at": round(self.at, 3)}


def default_classify(policy: CapacityPolicy, signals: dict) -> int:
    """Pressure sign from the fleet's published signals: +1 (want more
    capacity), -1 (want less), 0 (neutral — streaks reset).

    Up pressure: the brownout ladder left rung 0 anywhere, per-unit
    admission queue depth crossed `queue_high`, or the short-window SLO
    burn rate crossed `burn_high` (the budget is burning faster than
    capacity can absorb). Down pressure only when EVERY signal is calm
    below the low watermarks — and never while brownout is active
    (scale-down is ordered strictly behind brownout)."""
    size = max(1, int(signals.get("size", 1)))
    queue = float(signals.get("queue_depth", 0.0)) / size
    rung = int(signals.get("brownout_level", 0))
    burn = float(signals.get("burn_rate", 0.0))
    if rung > 0 or queue >= policy.queue_high or burn >= policy.burn_high:
        return 1
    if rung == 0 and queue <= policy.queue_low and burn <= policy.burn_low:
        return -1
    return 0


class CapacityController:
    """The decision engine: `tick(signals)` folds one observation
    window and returns a Decision when one fires (None otherwise).

    `size_fn()` reports current capacity; `plan_fn(direction, frm, to)`
    dry-runs the move and returns `{"ok": bool, "reason": str, ...}`
    (extra keys ride into `apply_fn`); `apply_fn(plan)` executes it
    (auto mode only). `classify_fn(policy, signals)` maps a signals
    dict to a pressure sign — the default reads the serving plane's
    queue/brownout/burn signals; runtime.py substitutes its
    bubble-attribution classifier. `now` is injectable everywhere
    (brownout.py discipline) so hysteresis unit-tests run clockless."""

    def __init__(self, policy: Optional[CapacityPolicy] = None,
                 mode: str = "advise",
                 size_fn: Optional[Callable[[], int]] = None,
                 plan_fn: Optional[Callable[[str, int, int], dict]] = None,
                 apply_fn: Optional[Callable[[dict], None]] = None,
                 classify_fn: Optional[Callable] = None,
                 label: str = "replicas"):
        if mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {mode!r}")
        self.policy = policy or CapacityPolicy()
        self.mode = mode
        self.label = label
        self._size_fn = size_fn or (lambda: self.policy.min_size)
        self._plan_fn = plan_fn
        self._apply_fn = apply_fn
        self._classify = classify_fn or default_classify
        # conviction state
        self._streak_dir = 0            # +1 / -1 / 0
        self._streak_n = 0
        self._streak_since: Optional[float] = None
        self._last_decision_t: Optional[float] = None
        self._last_direction: Optional[str] = None
        self._flap_factor = 1.0
        self._damped_streak = False     # one flap_damped per episode
        self.decisions: List[Decision] = []
        self.ticks = 0
        size = max(self.policy.min_size, int(self._size_fn()))
        # gauge zeroing IS the declaration (PL501 idiom for gauges)
        _M_TARGET.set(float(size))
        _M_ACTUAL.set(float(size))
        _M_FLAP.set(1.0)

    # -- the decision pipeline -------------------------------------------

    def tick(self, signals: dict,
             now: Optional[float] = None) -> Optional[Decision]:
        """observe -> confirm -> plan -> apply | held. One call per
        governor tick / round boundary."""
        now = time.monotonic() if now is None else float(now)
        pol = self.policy
        self.ticks += 1
        cur = int(self._size_fn())
        _M_ACTUAL.set(float(cur))
        sig = dict(signals)
        sig.setdefault("size", cur)
        sign = self._classify(pol, sig)
        rung = int(sig.get("brownout_level", 0))
        if sign < 0 and rung > 0:
            # scale-down ordered strictly behind brownout: a classifier
            # override cannot shed capacity while the ladder sheds work
            sign = 0
        if sign != self._streak_dir or sign == 0:
            self._streak_dir = sign
            self._streak_n = 1 if sign else 0
            self._streak_since = now if sign else None
            self._damped_streak = False
            if sign == 0:
                return None
        else:
            self._streak_n += 1
        direction = "up" if sign > 0 else "down"
        # confirm: N consecutive same-direction windows
        if self._streak_n < pol.confirm:
            return None
        # dwell: the streak must also have LASTED (hysteresis in time,
        # independent of tick rate)
        dwell = pol.dwell_up_s if sign > 0 else pol.dwell_down_s
        if self._streak_since is not None \
                and now - self._streak_since < dwell:
            return None
        # cooldown (+ flap damper): the damped portion renders visibly
        if self._last_decision_t is not None:
            since = now - self._last_decision_t
            if since < pol.cooldown_s:
                return None
            if since < pol.cooldown_s * self._flap_factor:
                if not self._damped_streak:
                    self._damped_streak = True
                    with telemetry.span("autoscale",
                                        f"flap_damped:{direction}"):
                        pass
                    _M_DECISIONS.inc(direction=direction,
                                     outcome="flap_damped")
                    d = Decision(direction, cur, cur, "flap_damped",
                                 f"cooldown x{self._flap_factor:g} "
                                 "(recent reversal)", now)
                    self.decisions.append(d)
                    logger.info("autoscale: %s", d.line())
                    return d
                return None
        target = min(pol.max_size, max(pol.min_size, cur + sign))
        if target == cur:
            # at a bound: steady state, not a decision — a clean fleet
            # parked at the floor must record ZERO decisions
            return None
        return self._decide(direction, cur, target, sig, now)

    def _decide(self, direction: str, cur: int, target: int,
                signals: dict, now: float) -> Decision:
        plan = None
        if self._plan_fn is not None:
            with telemetry.span("autoscale", f"plan:{direction}"):
                try:
                    plan = self._plan_fn(direction, cur, target)
                except Exception as exc:  # noqa: BLE001 — a crashed
                    plan = {"ok": False,   # planner must read as held
                            "reason": f"plan failed: {exc}"}
        if plan is not None and not plan.get("ok", False):
            with telemetry.span("autoscale", f"held:{direction}"):
                pass
            _M_DECISIONS.inc(direction=direction, outcome="held")
            d = Decision(direction, cur, cur, "held",
                         str(plan.get("reason", "plan refused")), now,
                         plan=plan)
            self._arm(d, now)
            logger.warning("autoscale: %s", d.line())
            return d
        if self.mode == "auto" and self._apply_fn is not None:
            with telemetry.span("autoscale", f"apply:{direction}"):
                try:
                    self._apply_fn(plan or {"direction": direction,
                                            "from": cur, "to": target})
                except Exception as exc:  # noqa: BLE001 — a failed
                    # actuator is a held decision, not an outage
                    with telemetry.span("autoscale", f"held:{direction}"):
                        pass
                    _M_DECISIONS.inc(direction=direction, outcome="held")
                    d = Decision(direction, cur, cur, "held",
                                 f"apply failed: {exc}", now, plan=plan)
                    self._arm(d, now)
                    logger.error("autoscale: %s", d.line())
                    return d
            outcome = "applied"
        else:
            outcome = "advised"
        _M_DECISIONS.inc(direction=direction, outcome=outcome)
        _M_TARGET.set(float(target))
        reason = (f"queue={signals.get('queue_depth', 0):g} "
                  f"rung={signals.get('brownout_level', 0)} "
                  f"burn={signals.get('burn_rate', 0):g} "
                  f"confirm={self._streak_n}")
        d = Decision(direction, cur, target, outcome,
                     reason.replace(" ", ","), now, plan=plan)
        self._arm(d, now)
        logger.warning("autoscale: %s", d.line())
        return d

    def _arm(self, d: Decision, now: float) -> None:
        """Every rendered decision arms the cooldown and resets the
        streak; applied/advised moves also update the flap damper (a
        reversal doubles the effective cooldown, a same-direction move
        calms it back to 1)."""
        self.decisions.append(d)
        self._last_decision_t = now
        self._streak_dir = 0
        self._streak_n = 0
        self._streak_since = None
        self._damped_streak = False
        if d.outcome in ("applied", "advised"):
            if self._last_direction is not None \
                    and d.direction != self._last_direction:
                self._flap_factor = min(self.policy.flap_cap,
                                        self._flap_factor * 2)
            else:
                self._flap_factor = 1.0
            self._last_direction = d.direction
            _M_FLAP.set(self._flap_factor)

    # -- introspection ----------------------------------------------------

    @property
    def flap_factor(self) -> float:
        return self._flap_factor

    def snapshot(self) -> dict:
        """The /healthz + /fleet autoscale block."""
        by_outcome: Dict[str, int] = {o: 0 for o in OUTCOMES}
        for d in self.decisions:
            by_outcome[d.outcome] = by_outcome.get(d.outcome, 0) + 1
        return {
            "mode": self.mode,
            "label": self.label,
            "min": self.policy.min_size,
            "max": self.policy.max_size,
            "size": int(self._size_fn()),
            "ticks": self.ticks,
            "streak": {"direction": self._streak_dir,
                       "n": self._streak_n},
            "cooldown_factor": self._flap_factor,
            "decisions": by_outcome,
            "last": (self.decisions[-1].to_dict()
                     if self.decisions else None),
        }


def signals_from_fleet(fleet: dict, size: int) -> dict:
    """Mine a FleetCollector.fleet_snapshot() into the controller's
    signals dict: summed admission queue depth, the max per-replica
    brownout rung (telemetry/collector.py scrapes
    `pipeedge_brownout_level` per target), and the worst short-window
    burn rate across classes."""
    burn = 0.0
    slo = fleet.get("slo") or {}
    for windows in (slo.get("burn_rate") or {}).values():
        burn = max(burn, float(windows.get("short", 0.0)))
    return {
        "queue_depth": float(fleet.get("queue_depth", 0.0)),
        "brownout_level": int(fleet.get("brownout_level", 0)),
        "burn_rate": burn,
        "size": int(size),
    }


class AutoscaleRunner:
    """The router-side governor thread: every `interval_s`, mine the
    fleet collector's snapshot into signals and tick the controller.
    Decisions print as machine-parseable `autoscale_decision` lines
    (tools/chaos_dcn.py and the CI autoscale-chaos job grep them)."""

    def __init__(self, controller: CapacityController,
                 signals_fn: Callable[[], dict],
                 interval_s: float = 1.0,
                 emit: Optional[Callable[[str], None]] = None):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.controller = controller
        self._signals_fn = signals_fn
        self.interval_s = float(interval_s)
        self._emit = emit or (lambda line: print(line, flush=True))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscale-governor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def tick_once(self) -> Optional[Decision]:
        try:
            signals = self._signals_fn()
        except Exception as exc:  # noqa: BLE001 — an unscrapeable fleet
            logger.info("autoscale: signals unavailable (%s)", exc)
            return None            # is a skipped window, not a crash
        d = self.controller.tick(signals)
        if d is not None:
            self._emit(d.line())
        return d

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick_once()
