"""Brownout ladder: watermark-driven graceful degradation with hysteresis.

When the serving plane runs hot (queue depth, windowed p95 latency), it
degrades DELIBERATELY, one rung at a time, shedding the cheapest quality
first — instead of letting the overload degrade everyone uniformly:

| level | name              | effect                                     |
|-------|-------------------|--------------------------------------------|
| 0     | normal            | —                                          |
| 1     | no_speculative    | speculative requests run plain greedy      |
|       |                   | (token-identical; frees the draft model's  |
|       |                   | serialized dispatch + cache memory)        |
| 2     | clamp_tokens      | new_tokens clamped to `clamp_new_tokens`;  |
|       |                   | chunked-prefill chunk size clamped to      |
|       |                   | `clamp_chunk_tokens` when that lever is    |
|       |                   | armed (shorter pipeline holds per chunk)   |
| 3     | evict_cold_pages  | reclaim cached-but-idle prefix KV pages    |
|       |                   | (the paged-KV trie's cold pages — capacity |
|       |                   | only future requests would miss, spent     |
|       |                   | BEFORE any live request is shed)           |
| 4     | colocate_prefill  | disaggregated serving degrades to          |
|       |                   | COLOCATED prefill: prompt passes stop      |
|       |                   | shipping to the remote prefill fleet and   |
|       |                   | run in the decode executor instead         |
|       |                   | (token-identical; sheds the ship edge's    |
|       |                   | latency/fault surface when the plane is    |
|       |                   | already hot — docs/FAULT_TOLERANCE.md)     |
| 5     | shed_best_effort  | best_effort class shed at admission        |
| 6     | shed_batch        | batch class shed too (interactive only)    |

Stepping is governed by watermarks + dwell times (hysteresis): the hot
condition must persist `dwell_up_s` before each step up, and the calm
condition `dwell_down_s` before each step down — a ladder, not a
flip-flop. A FLOOR composes the degraded->healing->healed lifecycle in:
while a failover window is open (docs/FAULT_TOLERANCE.md), the effective
level is at least 1 (healing capacity must not be spent on speculative
drafts), whatever the watermarks say.

The governor thread in tools/serve.py calls `update()` periodically with
the admission queue depth and the p95 of the request-latency histogram's
last window; everything here is plain state + arithmetic (injectable
`now` for deterministic tests).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..telemetry import metrics as prom

LEVEL_NAMES = ("normal", "no_speculative", "clamp_tokens",
               "evict_cold_pages", "colocate_prefill",
               "shed_best_effort", "shed_batch")
MAX_LEVEL = len(LEVEL_NAMES) - 1
EVICT_LEVEL = LEVEL_NAMES.index("evict_cold_pages")
COLOCATE_LEVEL = LEVEL_NAMES.index("colocate_prefill")


@dataclass
class Watermarks:
    """Step-up/step-down thresholds. Hot when EITHER signal is above its
    high mark; calm only when BOTH are below their low marks (missing
    p95 — an idle window — counts as calm)."""
    queue_high: int = 8
    queue_low: int = 1
    p95_high_s: float = 2.0
    p95_low_s: float = 0.5
    dwell_up_s: float = 0.5
    dwell_down_s: float = 2.0

    def __post_init__(self):
        if self.queue_low > self.queue_high:
            raise ValueError("queue_low must be <= queue_high")
        if self.p95_low_s > self.p95_high_s:
            raise ValueError("p95_low_s must be <= p95_high_s")


class BrownoutLadder:
    """The ladder's state machine. Not internally locked: the governor
    thread is the only writer; readers (`level`, `shed_classes`, ...)
    see GIL-atomic ints."""

    def __init__(self, marks: Optional[Watermarks] = None,
                 max_level: int = MAX_LEVEL,
                 clamp_new_tokens: int = 16,
                 clamp_chunk_tokens: int = 0,
                 registry: Optional[prom.Registry] = None):
        if not 0 <= max_level <= MAX_LEVEL:
            raise ValueError(f"max_level must be in [0, {MAX_LEVEL}]")
        if clamp_new_tokens < 1:
            raise ValueError("clamp_new_tokens must be >= 1")
        if clamp_chunk_tokens < 0:
            raise ValueError("clamp_chunk_tokens must be >= 0")
        self.marks = marks if marks is not None else Watermarks()
        self.max_level = int(max_level)
        self.clamp_new_tokens = int(clamp_new_tokens)
        # the clamp_tokens rung's SECOND lever (0 = not armed): shrink
        # the chunked-prefill chunk size while hot, so prompt ingress
        # yields more step boundaries to waiting decode steps
        # (tools/serve.py's governor applies it via set_chunk_tokens)
        self.clamp_chunk_tokens = int(clamp_chunk_tokens)
        self._stepped = 0       # watermark-driven rung
        self._floor = 0         # lifecycle-driven minimum (healing >= 1)
        self._hot_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        # the evict_cold_pages rung's lever: `hook() -> pages freed`
        # (the paged-KV backend's cold-prefix sweep, tools/serve.py);
        # called on every governor tick while the level holds >= 3, so
        # pages that re-chill during a long hot spell keep reclaiming
        self.evict_hook: Optional[object] = None
        reg = prom.REGISTRY if registry is None else registry
        self.m_level = reg.gauge(
            "pipeedge_brownout_level",
            f"current brownout rung (0={LEVEL_NAMES[0]} .. "
            f"{MAX_LEVEL}={LEVEL_NAMES[-1]}; docs/SERVING.md ladder)")
        self.m_level.set(0)
        self.m_steps = reg.counter(
            "pipeedge_brownout_transitions_total",
            "brownout rung changes, by direction")
        self.m_steps.declare(direction="up")
        self.m_steps.declare(direction="down")

    # -- state ------------------------------------------------------------

    @property
    def level(self) -> int:
        """Effective level: the stepped rung, floored by the lifecycle."""
        return max(self._stepped, self._floor)

    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self.level]

    def set_floor(self, floor: int) -> int:
        """Lifecycle floor (0 or 1+): healing implies at least level 1."""
        before = self.level
        self._floor = max(0, min(int(floor), self.max_level))
        after = self.level
        if after != before:
            self.m_level.set(after)
            self.m_steps.inc(direction="up" if after > before else "down")
        return after

    # -- the ladder -------------------------------------------------------

    def update(self, queue_depth: int, p95_s: Optional[float],
               now: Optional[float] = None) -> int:
        """One governor tick: classify the signals, dwell, maybe step.
        Returns the effective level."""
        import time
        now = time.monotonic() if now is None else now
        m = self.marks
        hot = (queue_depth >= m.queue_high
               or (p95_s is not None and p95_s >= m.p95_high_s))
        calm = (queue_depth <= m.queue_low
                and (p95_s is None or p95_s <= m.p95_low_s))
        before = self.level
        if hot:
            self._calm_since = None
            if self._hot_since is None:
                self._hot_since = now
            elif (now - self._hot_since >= m.dwell_up_s
                  and self._stepped < self.max_level):
                self._stepped += 1
                self._hot_since = now      # re-arm: one rung per dwell
        elif calm:
            self._hot_since = None
            if self._calm_since is None:
                self._calm_since = now
            elif (now - self._calm_since >= m.dwell_down_s
                  and self._stepped > 0):
                self._stepped -= 1
                self._calm_since = now     # re-arm: one rung per dwell
        else:
            # between the marks: hold the rung, reset both dwells
            self._hot_since = None
            self._calm_since = None
        after = self.level
        if after != before:
            self.m_steps.inc(direction="up" if after > before else "down")
        self.m_level.set(after)
        if after >= EVICT_LEVEL and self.evict_hook is not None:
            self.evict_hook()
        return after

    # -- effects ----------------------------------------------------------

    def allow_speculative(self) -> bool:
        return self.level < 1

    def clamp(self, new_tokens: int) -> int:
        """Level >= 2: long generations are clamped so each admitted
        request's service time (and cache residency) is bounded."""
        if self.level >= 2:
            return min(int(new_tokens), self.clamp_new_tokens)
        return int(new_tokens)

    def clamp_chunk(self, chunk_tokens: int) -> int:
        """Level >= 2 with the lever armed: the chunked-prefill chunk
        size shrinks to `clamp_chunk_tokens` so each prompt chunk holds
        the pipeline for less time — more step boundaries per second
        for the decode steps already in flight. Identity when chunking
        is off (chunk_tokens == 0 stays 0: clamping would ENABLE
        chunking, a semantic change, not a degradation)."""
        if (self.level >= 2 and self.clamp_chunk_tokens
                and chunk_tokens > 0):
            return min(int(chunk_tokens), self.clamp_chunk_tokens)
        return int(chunk_tokens)

    def allow_disaggregate(self) -> bool:
        """Level >= 4 (`colocate_prefill`): stop shipping prompt passes
        to the remote prefill fleet — run them colocated in the decode
        executor (token-identical; drops the ship edge's latency and
        fault surface while the plane is hot)."""
        return self.level < COLOCATE_LEVEL

    def shed_classes(self) -> frozenset:
        if self.level >= 6:
            return frozenset(("best_effort", "batch"))
        if self.level >= 5:
            return frozenset(("best_effort",))
        return frozenset()

    def snapshot(self) -> dict:
        return {"level": self.level, "name": self.level_name,
                "stepped": self._stepped, "floor": self._floor,
                "evicting": self.level >= EVICT_LEVEL
                and self.evict_hook is not None,
                "clamp_new_tokens": (self.clamp_new_tokens
                                     if self.level >= 2 else None),
                "clamp_chunk_tokens": (self.clamp_chunk_tokens
                                       if self.level >= 2
                                       and self.clamp_chunk_tokens
                                       else None)}
