"""Overload protection for the serving plane (docs/SERVING.md).

Overload is a fault class, not a steady state to be endured: without
admission control a surge degrades latency for *every* request instead
of shedding the excess (ROADMAP item 3's "per-class SLOs with admission
control and 503/Retry-After backpressure"). This package gives
tools/serve.py the three mechanisms that bound the damage:

- `admission`: per-class token-bucket rate limits, a bounded
  earliest-deadline-first admission queue, load shedding with a
  Retry-After computed from the observed service rate, deadline
  bookkeeping, and — with a paged KV plane (pipeedge_tpu/kv) — a KV
  TOKEN budget: each grant charges the request's prompt+max-new-tokens
  page reservation, so concurrency is bounded by cache tokens instead
  of `max_active` slots (`AdmissionController`).
- `brownout`: a watermark-driven degradation ladder that steps through
  disable-speculative -> clamp new_tokens -> evict cold KV pages ->
  shed best-effort -> shed batch, and steps back down with hysteresis
  (`BrownoutLadder`).
- deadline propagation itself lives in the executors
  (`parallel/batcher.py`): each request's absolute deadline rides into
  the decode loop, and expiry fires the existing `cancel` flag at the
  next decode-step boundary so dead work stops consuming TPU time.
- `router`: the routed decode fleet's front end (`--role router`) — a
  health-checked replica registry with EWMA-scored hysteresis
  (healthy→suspect→drained→dead), prefix-affinity routing, bounded
  retry/failover, tail hedging, graceful drain with KV page migration
  over the ship codec (`DecodeRouter`, `ReplicaRegistry`,
  `RouterPolicy` — docs/SERVING.md router topology).
- `autoscale`: the closed capacity loop over that membership plane
  (`--autoscale {off,advise,auto}`) — a governor-ticked
  `CapacityController` with confirm/dwell hysteresis, a flap damper,
  scale-down ordered behind brownout, and dry-run `held` transitions
  (docs/FAULT_TOLERANCE.md autoscale lifecycle).
"""
from .admission import (AdmissionController, AdmissionShed, ClassPolicy,
                        DeadlineExceeded, EDFQueue, REQUEST_CLASSES,
                        ServiceRateEstimator, TokenBucket, default_policies,
                        parse_class_map)
from .autoscale import (AutoscaleRunner, CapacityController,  # noqa: F401
                        CapacityPolicy)
from .brownout import BrownoutLadder, LEVEL_NAMES, Watermarks
from .router import (DecodeRouter, NoReplicaAvailable,  # noqa: F401
                     REPLICA_DEAD, REPLICA_DRAINED, REPLICA_HEALTHY,
                     REPLICA_SUSPECT, ReplicaRegistry, RouterPolicy)

__all__ = [
    "AdmissionController", "AdmissionShed", "AutoscaleRunner",
    "BrownoutLadder", "CapacityController", "CapacityPolicy",
    "ClassPolicy", "DeadlineExceeded", "DecodeRouter", "EDFQueue",
    "LEVEL_NAMES", "NoReplicaAvailable", "REPLICA_DEAD",
    "REPLICA_DRAINED", "REPLICA_HEALTHY", "REPLICA_SUSPECT",
    "REQUEST_CLASSES", "ReplicaRegistry", "RouterPolicy",
    "ServiceRateEstimator", "TokenBucket", "Watermarks",
    "default_policies", "parse_class_map",
]
