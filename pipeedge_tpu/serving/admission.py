"""SLO-aware admission control: request classes, token buckets, a bounded
EDF queue, and load shedding with a service-rate-derived Retry-After.

The serving front end admits one HTTP handler thread per request; this
module decides — BEFORE any TPU work is dispatched — whether that request
runs now, waits, or is shed:

1. **Request classes** (`interactive` / `batch` / `best_effort`): each
   carries an optional sustained-rate token bucket and an optional
   default deadline. Classes are the unit of brownout shedding
   (serving/brownout.py) and of the per-class SLO report
   (tools/loadgen.py).
2. **Bounded EDF queue**: waiting requests are ordered by absolute
   deadline (earliest first — an interactive request with a 2 s deadline
   overtakes a batch request with a 60 s one). The queue is BOUNDED:
   when full, the latest-deadline entry is shed, so a surge converts to
   503s instead of an unbounded backlog of work that will miss its SLO
   anyway.
3. **Shedding with honest backpressure**: every shed carries a
   Retry-After computed from the observed completion rate
   (`ServiceRateEstimator`): backlog / rate, clamped — "come back when
   the queue you would join has drained", not a hard-coded constant.

Thread model: `admit()` blocks the calling handler thread until the
request is granted an execution slot or shed (`AdmissionShed`); the
caller MUST pair every successful admit with `release()`. All state is
guarded by one controller lock; grant events are per-ticket so a release
wakes exactly the next EDF head.
"""
from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from ..telemetry import metrics as prom
from ..utils.threads import make_lock

# shed order under brownout is reverse priority: best_effort first
REQUEST_CLASSES = ("interactive", "batch", "best_effort")

# admission waits are short by design (the queue is bounded); buckets
# resolve the sub-second region the request-latency buckets blur
ADMISSION_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                             0.5, 1.0, 2.5, 5.0, 10.0)


@dataclass(frozen=True)
class ClassPolicy:
    """One request class's admission contract."""
    name: str
    priority: int                        # lower = more important
    rate: Optional[float] = None         # sustained admits/s (None = off)
    burst: float = 1.0                   # token-bucket capacity
    deadline_s: Optional[float] = None   # default deadline when the
    #                                      request carries none


def default_policies(rates: Optional[Dict[str, float]] = None,
                     deadlines_s: Optional[Dict[str, float]] = None,
                     ) -> Dict[str, ClassPolicy]:
    """The three standard classes, with optional per-class rate limits
    and default deadlines layered on (serve.py's CLI knobs)."""
    rates = rates or {}
    deadlines_s = deadlines_s or {}
    out = {}
    for pri, name in enumerate(REQUEST_CLASSES):
        rate = rates.get(name)
        if rate is not None and rate <= 0:
            # 0 must not silently mean "unlimited" — the opposite of the
            # operator's likely intent (use brownout/shed to block a class)
            raise ValueError(
                f"class {name!r}: rate must be > 0 (omit the class for "
                f"unlimited; shed it via brownout to block it)")
        out[name] = ClassPolicy(
            name=name, priority=pri, rate=rate,
            burst=max(1.0, rate) if rate is not None else 1.0,
            deadline_s=deadlines_s.get(name))
    return out


def parse_class_map(pairs: Optional[Iterable[str]],
                    what: str) -> Dict[str, float]:
    """`interactive=2.5`-style repeated CLI pairs -> {class: float}.
    Shared by tools/serve.py and tools/loadgen.py (each maps the
    ValueError onto its own error channel)."""
    out: Dict[str, float] = {}
    for item in pairs or ():
        name, sep, val = item.partition("=")
        if not sep or name not in REQUEST_CLASSES:
            raise ValueError(
                f"{what} expects CLASS=VALUE with CLASS one of "
                f"{sorted(REQUEST_CLASSES)}, got {item!r}")
        try:
            out[name] = float(val)
        except ValueError:
            raise ValueError(f"{what}: {val!r} is not a number") from None
    return out


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill up to `burst`.

    Not internally locked — the controller serializes access under its
    own lock; standalone use needs external synchronization. `now` is
    injectable for deterministic tests."""

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate and burst must be > 0, got "
                             f"rate={rate} burst={burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._t = time.monotonic() if now is None else now

    def try_take(self, n: float = 1.0, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self._tokens = min(self.burst,
                           self._tokens + max(0.0, now - self._t) * self.rate)
        self._t = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    @property
    def tokens(self) -> float:
        return self._tokens


class EDFQueue:
    """Bounded earliest-deadline-first queue with shed-on-full.

    Entries are (deadline, item); `None` deadlines sort last (they can
    wait forever, so they are also the first candidates to shed). When
    the queue is full, `push` sheds the LATEST-deadline entry — the
    arrival itself when its deadline is the latest — and returns the
    shed item (None when nothing was shed). Lazy deletion supports
    `remove()` for waiters that give up (expiry/timeout) without an
    O(n) heap rebuild."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._heap: List[list] = []   # [key, seq, item, alive]
        self._seq = 0
        self._n = 0                   # alive entries

    @staticmethod
    def _key(deadline: Optional[float]) -> float:
        return math.inf if deadline is None else float(deadline)

    def __len__(self) -> int:
        return self._n

    def push(self, item, deadline: Optional[float]):
        """Insert; returns the shed item when the queue was full (possibly
        `item` itself), else None."""
        import heapq
        shed = None
        if self._n >= self.capacity:
            # shed the latest deadline: linear scan over a small bounded
            # heap beats maintaining a mirrored max-heap
            worst = None
            for e in self._heap:
                if e[3] and (worst is None or (e[0], e[1]) > (worst[0],
                                                              worst[1])):
                    worst = e
            if worst is not None and (worst[0], worst[1]) > (
                    self._key(deadline), self._seq):
                worst[3] = False
                self._n -= 1
                shed = worst[2]
            else:
                return item          # the arrival is the worst: shed it
        entry = [self._key(deadline), self._seq, item, True]
        self._seq += 1
        heapq.heappush(self._heap, entry)
        self._n += 1
        return shed

    def pop(self):
        """(item, deadline_key) with the earliest deadline, or None."""
        import heapq
        while self._heap:
            key, _, item, alive = heapq.heappop(self._heap)
            if alive:
                self._n -= 1
                return item, key
        return None

    def peek(self):
        """(item, deadline_key) of the earliest alive entry WITHOUT
        removing it (dead entries are drained in passing) — the
        token-budget grant loop inspects the head and leaves it in
        place when tokens are short, so the head keeps its position
        instead of being re-queued behind same-deadline arrivals."""
        import heapq
        while self._heap and not self._heap[0][3]:
            heapq.heappop(self._heap)
        if not self._heap:
            return None
        return self._heap[0][2], self._heap[0][0]

    def pop_expired(self, now: float) -> List[object]:
        """Remove and return every entry whose deadline has passed —
        work that would be shed the moment it was granted anyway."""
        import heapq
        out = []
        while self._heap and self._heap[0][0] < now:
            key, _, item, alive = heapq.heappop(self._heap)
            if alive:
                self._n -= 1
                out.append(item)
        return out

    def remove(self, item) -> bool:
        """Lazy-delete one entry (a waiter that timed out)."""
        for e in self._heap:
            if e[3] and e[2] is item:
                e[3] = False
                self._n -= 1
                return True
        return False

    def items(self) -> List[object]:
        """Alive entries in deadline order (non-destructive): the
        postmortem/debug view of who is waiting. O(n log n) over a small
        bounded heap."""
        return [e[2] for e in sorted(
            (e for e in self._heap if e[3]),
            key=lambda e: (e[0], e[1]))]


class ServiceRateEstimator:
    """EWMA of the completion rate, and the Retry-After it implies.

    Each completion updates an exponentially weighted mean of the
    inter-completion interval (half-life `halflife_s`); the service rate
    is its reciprocal. `retry_after(backlog)` answers "when will the
    backlog I would join have drained": (backlog + 1) / rate, clamped —
    the dynamic replacement for a hard-coded Retry-After constant."""

    def __init__(self, halflife_s: float = 10.0):
        self.halflife_s = float(halflife_s)
        self._last: Optional[float] = None
        self._ewma: Optional[float] = None
        self._n = 0

    def observe(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last is not None:
            dt = max(1e-6, now - self._last)
            if self._ewma is None:
                self._ewma = dt
            else:
                # per-sample decay scaled by the observed interval, so the
                # half-life is in SECONDS, not samples
                alpha = 1.0 - 0.5 ** (dt / self.halflife_s)
                self._ewma += alpha * (dt - self._ewma)
        self._last = now
        self._n += 1

    def rate(self) -> Optional[float]:
        """Completions/s, None until two completions have been seen."""
        if self._ewma is None or self._ewma <= 0:
            return None
        return 1.0 / self._ewma

    def retry_after(self, backlog: int, fallback: float = 5.0,
                    lo: float = 0.5, hi: float = 60.0) -> float:
        r = self.rate()
        if r is None:
            return float(fallback)
        return float(min(hi, max(lo, (backlog + 1) / r)))


class AdmissionShed(RuntimeError):
    """The request was refused (rate limit / queue full / brownout /
    expired in queue): HTTP 503 with the carried Retry-After."""

    def __init__(self, request_class: str, reason: str, retry_after: float):
        super().__init__(
            f"request shed ({reason}) for class {request_class!r}; "
            f"retry after {retry_after:g}s")
        self.request_class = request_class
        self.reason = reason
        self.retry_after = retry_after


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired while it was EXECUTING: the
    executors cancelled it at a decode-step boundary (HTTP 504). Distinct
    from an in-queue expiry, which sheds with 503 + Retry-After (the work
    never started)."""

    def __init__(self, request_class: str, deadline_s: float):
        super().__init__(
            f"deadline exceeded for class {request_class!r} request "
            f"(budget {deadline_s:g}s); generation cancelled mid-flight")
        self.request_class = request_class
        self.deadline_s = deadline_s


SHED_REASONS = ("rate", "queue_full", "brownout", "expired", "shutdown",
                "budget")


class _Ticket:
    __slots__ = ("request_class", "deadline", "t_enq", "event",
                 "shed_reason", "granted", "rid", "tokens")

    def __init__(self, request_class: str, deadline: Optional[float],
                 t_enq: float, rid: Optional[str] = None,
                 tokens: int = 0):
        self.request_class = request_class
        self.deadline = deadline
        self.t_enq = t_enq
        self.event = threading.Event()
        self.shed_reason: Optional[str] = None
        self.granted = False
        # request id (trace context): queue-wait spans and the admission
        # snapshot in a postmortem bundle name WHO is waiting, not just
        # how many (docs/OBSERVABILITY.md request tracing)
        self.rid = rid
        # KV-token charge under a token budget (docs/SERVING.md paged
        # KV): held from grant to release
        self.tokens = int(tokens)


class AdmissionController:
    """Per-class admission with `concurrency` execution slots and a
    bounded EDF wait queue.

    `admit(cls, deadline)` blocks until granted or raises
    `AdmissionShed`; every grant MUST be paired with `release()`
    (completions feed the service-rate estimator that prices
    Retry-After). `set_shed_classes` is the brownout ladder's lever:
    listed classes shed at the door."""

    def __init__(self, concurrency: int, queue_capacity: int = 64,
                 policies: Optional[Dict[str, ClassPolicy]] = None,
                 registry: Optional[prom.Registry] = None,
                 rate_halflife_s: float = 10.0,
                 retry_after_fallback: float = 5.0,
                 token_budget: Optional[int] = None):
        if concurrency < 1:
            raise ValueError(f"concurrency must be >= 1, got {concurrency}")
        if token_budget is not None and token_budget < 1:
            raise ValueError(f"token_budget must be >= 1, got "
                             f"{token_budget}")
        self.policies = (default_policies() if policies is None
                         else dict(policies))
        self.concurrency = int(concurrency)
        self._free = int(concurrency)
        # the paged-KV admission unit (docs/SERVING.md): admission
        # charges each request's KV-token reservation (prompt +
        # max-new-tokens pages) against this budget; completion frees
        # it. None = slot-only admission (the dense-cache behavior).
        self.token_budget = (None if token_budget is None
                             else int(token_budget))
        self._tokens_free = self.token_budget
        self._queue = EDFQueue(queue_capacity)
        self._lock = make_lock("serving.admission")
        self._closed = False
        self._buckets = {
            name: TokenBucket(p.rate, p.burst)
            for name, p in self.policies.items() if p.rate is not None}
        self._shed_classes: frozenset = frozenset()
        self.estimator = ServiceRateEstimator(halflife_s=rate_halflife_s)
        self.retry_after_fallback = float(retry_after_fallback)
        reg = prom.REGISTRY if registry is None else registry
        self.m_shed = reg.counter(
            "pipeedge_requests_shed_total",
            "requests refused at admission, by class and reason "
            "(rate / queue_full / brownout / expired / shutdown)")
        # the full (class, reason) matrix renders from the first scrape
        for name in self.policies:
            for reason in SHED_REASONS:
                self.m_shed.declare(**{"class": name, "reason": reason})
        self.m_adm_latency = reg.histogram(
            "pipeedge_admission_latency_seconds",
            "time from arrival to execution-slot grant, by class",
            buckets=ADMISSION_LATENCY_BUCKETS)
        self.m_queue_depth = reg.gauge(
            "pipeedge_admission_queue_depth",
            "requests waiting in the EDF admission queue")
        self.m_queue_depth.set(0)
        self.m_tokens_free = reg.gauge(
            "pipeedge_admission_tokens_free",
            "unreserved KV tokens under the admission token budget "
            "(absent series when no budget is configured)")
        if self.token_budget is not None:
            self.m_tokens_free.set(self.token_budget)
        self.m_step_grants = reg.counter(
            "pipeedge_admission_step_grants_total",
            "queued tickets granted by a decode-step notify_step pass "
            "(iteration-level joins, not release-driven ones)")
        self.m_step_grants.declare()

    # -- policy helpers ---------------------------------------------------

    def policy(self, request_class: str) -> ClassPolicy:
        try:
            return self.policies[request_class]
        except KeyError:
            raise KeyError(
                f"unknown request class {request_class!r} (expected one "
                f"of {sorted(self.policies)})") from None

    def deadline_for(self, request_class: str,
                     deadline_s: Optional[float] = None,
                     now: Optional[float] = None) -> Optional[float]:
        """Absolute (monotonic) deadline: the request's own budget when
        given, else the class default, else None."""
        now = time.monotonic() if now is None else now
        if deadline_s is None:
            deadline_s = self.policy(request_class).deadline_s
        if deadline_s is None:
            return None
        return now + float(deadline_s)

    def set_shed_classes(self, names: Iterable[str]) -> None:
        self._shed_classes = frozenset(names)

    @property
    def shed_classes(self) -> frozenset:
        return self._shed_classes

    # -- admission --------------------------------------------------------

    def _shed(self, request_class: str, reason: str,
              backlog: Optional[int] = None) -> AdmissionShed:
        if backlog is None:
            backlog = len(self._queue) + (self.concurrency - self._free)
        self.m_shed.inc(**{"class": request_class, "reason": reason})
        return AdmissionShed(request_class, reason,
                             self.retry_after(backlog))

    def retry_after(self, backlog: Optional[int] = None) -> float:
        """The dynamic Retry-After: queue-drain time at the observed
        service rate (fallback when no completions have been seen)."""
        if backlog is None:
            with self._lock:
                backlog = len(self._queue) + (self.concurrency - self._free)
        return self.estimator.retry_after(
            backlog, fallback=self.retry_after_fallback)

    def admit(self, request_class: str = "interactive",
              deadline: Optional[float] = None,
              now: Optional[float] = None,
              rid: Optional[str] = None,
              tokens: int = 0) -> _Ticket:
        """Block until granted an execution slot (EDF order) or shed.
        `deadline` is ABSOLUTE monotonic time (see `deadline_for`);
        `rid` request-tags the ticket for snapshots/postmortems.
        `tokens` is the request's KV-token reservation under a token
        budget (prompt + max-new-tokens pages, tools/serve.py): the
        grant requires both a slot AND the tokens, so concurrency is
        bounded by cache TOKENS, not request count."""
        now = time.monotonic() if now is None else now
        self.policy(request_class)          # KeyError -> caller's 400
        tokens = int(tokens) if self.token_budget is not None else 0
        ticket = _Ticket(request_class, deadline, now, rid=rid,
                         tokens=tokens)
        shed_waiter: Optional[_Ticket] = None
        with self._lock:
            if self._closed:
                raise self._shed(request_class, "shutdown")
            if request_class in self._shed_classes:
                raise self._shed(request_class, "brownout")
            if self.token_budget is not None \
                    and tokens > self.token_budget:
                # bigger than the WHOLE budget: waiting can never help
                raise self._shed(request_class, "budget")
            bucket = self._buckets.get(request_class)
            if bucket is not None and not bucket.try_take(now=now):
                raise self._shed(request_class, "rate")
            if deadline is not None and deadline <= now:
                raise self._shed(request_class, "expired")
            if self._free > 0 and not len(self._queue) \
                    and self._tokens_ok_locked(tokens):
                self._free -= 1
                self._take_tokens_locked(tokens)
                ticket.granted = True
            else:
                shed_item = self._queue.push(ticket, deadline)
                if shed_item is ticket:
                    raise self._shed(request_class, "queue_full")
                if shed_item is not None:
                    shed_waiter = shed_item
                    shed_waiter.shed_reason = "queue_full"
                self.m_queue_depth.set(len(self._queue))
        if shed_waiter is not None:
            self.m_shed.inc(**{"class": shed_waiter.request_class,
                               "reason": "queue_full"})
            shed_waiter.event.set()
        if ticket.granted:
            self.m_adm_latency.observe(0.0, **{"class": request_class})
            return ticket
        # queued: wait until a release grants us, our deadline passes, or
        # the controller closes
        while True:
            timeout = (None if ticket.deadline is None
                       else max(0.0, ticket.deadline - time.monotonic()))
            fired = ticket.event.wait(timeout)
            with self._lock:
                if ticket.granted:
                    break
                if ticket.shed_reason is not None:
                    # same backlog basis as a door shed (queue + in
                    # flight) so two 503s under the same load advertise
                    # the same Retry-After; the shed counter was already
                    # bumped by whoever displaced us
                    backlog = (len(self._queue)
                               + (self.concurrency - self._free))
                    raise AdmissionShed(ticket.request_class,
                                        ticket.shed_reason,
                                        self.estimator.retry_after(
                                            backlog,
                                            fallback=self.retry_after_fallback))
                if not fired:
                    # deadline passed while queued: withdraw ourselves
                    self._queue.remove(ticket)
                    self.m_queue_depth.set(len(self._queue))
                    raise self._shed(request_class, "expired")
        wait_s = time.monotonic() - ticket.t_enq
        self.m_adm_latency.observe(wait_s, **{"class": request_class})
        return ticket

    def _tokens_ok_locked(self, tokens: int) -> bool:
        return (self.token_budget is None
                or self._tokens_free >= tokens)

    def _take_tokens_locked(self, tokens: int) -> None:
        if self.token_budget is not None and tokens:
            self._tokens_free -= tokens
            self.m_tokens_free.set(self._tokens_free)

    def release(self, ticket: Optional[_Ticket] = None,
                completed: bool = True,
                now: Optional[float] = None) -> None:
        """Return an execution slot (and the ticket's token
        reservation) and grant the next EDF head(s). `completed=True`
        feeds the service-rate estimator (sheds and failures should not
        inflate the observed service rate)."""
        now = time.monotonic() if now is None else now
        to_wake: List[_Ticket] = []
        expired: List[_Ticket] = []
        with self._lock:
            self._free = min(self.concurrency, self._free + 1)
            if self.token_budget is not None and ticket is not None \
                    and ticket.tokens:
                self._tokens_free = min(self.token_budget,
                                        self._tokens_free + ticket.tokens)
                self.m_tokens_free.set(self._tokens_free)
            if completed:
                self.estimator.observe(now)
            self._grant_locked(now, to_wake, expired)
        for t in expired:
            self.m_shed.inc(**{"class": t.request_class,
                               "reason": "expired"})
            t.event.set()
        for t in to_wake:
            t.event.set()

    def notify_step(self, now: Optional[float] = None) -> None:
        """Re-run the grant pass at a decode-step boundary (the
        executors' `on_step` hook, tools/serve.py). Slots and tokens
        free when `release` runs, but a token-budget head-of-line wait
        can also unblock when the STEP-granular picture changes (an
        expired waiter sheds, a clamp lands); stepping the grant pass
        here makes admission joinable at iteration boundaries instead
        of request boundaries — and costs one short lock when nothing
        changed. Counted by `pipeedge_admission_step_grants_total`."""
        now = time.monotonic() if now is None else now
        to_wake: List[_Ticket] = []
        expired: List[_Ticket] = []
        with self._lock:
            if self._closed:
                return
            self._grant_locked(now, to_wake, expired)
        if to_wake:
            self.m_step_grants.inc(len(to_wake))
        for t in expired:
            self.m_shed.inc(**{"class": t.request_class,
                               "reason": "expired"})
            t.event.set()
        for t in to_wake:
            t.event.set()

    def _grant_locked(self, now: float, to_wake: List[_Ticket],
                      expired: List[_Ticket]) -> None:
        # in-queue entries whose deadline already passed are shed, not
        # granted: running them would only produce a mid-flight 504
        for t in self._queue.pop_expired(now):
            t.shed_reason = "expired"
            expired.append(t)
        while self._free > 0:
            nxt = self._queue.peek()
            if nxt is None:
                break
            t, _ = nxt
            if not self._tokens_ok_locked(t.tokens):
                # head-of-line under the token budget: the EDF head
                # stays IN PLACE (peek, not pop) waiting for token
                # releases — re-queueing would assign a fresh tie-break
                # seq and let same-deadline arrivals overtake it,
                # starving big-context requests under sustained small-
                # request load
                break
            self._queue.pop()          # the same head, under the lock
            self._free -= 1
            self._take_tokens_locked(t.tokens)
            t.granted = True
            to_wake.append(t)
        self.m_queue_depth.set(len(self._queue))

    # -- introspection / lifecycle ---------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self.concurrency - self._free

    def snapshot(self) -> dict:
        """Best-effort state for /healthz's `serving` block (and the
        admission slice of a postmortem bundle: `waiting` names the
        queued request ids in grant order)."""
        with self._lock:
            depth = len(self._queue)
            in_flight = self.concurrency - self._free
            waiting = [{"rid": t.rid, "class": t.request_class}
                       for t in self._queue.items()]
        rate = self.estimator.rate()
        out = {"queue_depth": depth, "in_flight": in_flight,
               "concurrency": self.concurrency,
               "queue_capacity": self._queue.capacity,
               "shed_classes": sorted(self._shed_classes),
               "waiting": waiting,
               "service_rate_rps": (None if rate is None
                                    else round(rate, 3)),
               "shed_total": int(self.m_shed.total())}
        if self.token_budget is not None:
            with self._lock:
                out["token_budget"] = self.token_budget
                out["tokens_free"] = self._tokens_free
        return out

    def close(self) -> None:
        """Shed every waiter (shutdown) and refuse new admissions."""
        waiters: List[_Ticket] = []
        with self._lock:
            self._closed = True
            while True:
                nxt = self._queue.pop()
                if nxt is None:
                    break
                t, _ = nxt
                t.shed_reason = "shutdown"
                waiters.append(t)
            self.m_queue_depth.set(0)
        for t in waiters:
            self.m_shed.inc(**{"class": t.request_class,
                               "reason": "shutdown"})
            t.event.set()
