"""Bubble attribution and latency analysis over a merged span timeline.

The analysis the MPMD pipeline-parallelism literature does by hand
(PAPERS.md: arxiv 2412.14374 attributes throughput loss to pipeline
bubbles; arxiv 2110.14895 to inter-stage transfer skew), computed from the
span stream this repo's runtime emits:

- pipeline bubble %: per stage, idle time inside the active window (union
  of that stage's compute/dispatch intervals vs the fleet-wide window);
  the headline number is the mean across stages — 0% is a perfectly
  packed pipeline, (S-1)/S-ish is a fill/drain-dominated one.
- per-edge wire-time share: each wire track's busy time over the window
  (how much of the round each edge spent moving bytes).
- per-microbatch end-to-end latency: for every mb id, last span end minus
  first span start across ALL ranks (the timeline is already aligned), so
  p50/p95/p99 reflect the true hop-to-hop path including queueing.
- failover breakdown: the detection and recovery spans the runtime records
  around a mid-run death (docs/FAULT_TOLERANCE.md).
- rejoin breakdown: JOIN admissions and heal spans of the elastic
  membership plane — each heal span's duration is that episode's
  time-to-full-capacity (first detection -> partition healed).
- gray-failure breakdown: peer-health lifecycle transitions (suspect /
  quarantine / readmit / recovered / floor-held, pipeedge_tpu/health/)
  per affected rank — the zero-false-quarantines assertion on a clean
  run and the exactly-one-quarantine gate on a straggler run both read
  this section.
- autoscale breakdown: capacity-controller decision spans
  (pipeedge_tpu/serving/autoscale.py) — plan / apply / held /
  flap_damped per direction, with apply durations — the
  zero-decisions-on-a-steady-fleet assertion and the scale-up-then-
  scale-down chaos gate both read this section.
- span_overhead_pct: the recorder's own cost — per-record cost measured
  live on this host times the span count, over the window — the number
  that keeps the observability plane honest about its hot-path tax.

Consumed by `tools/trace_report.py` (one JSON line, chaos_dcn idiom) and
the tests' hand-built timelines.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import SpanRecorder, round_segments, segment_index

# categories that represent a stage doing useful work (bubble accounting)
BUSY_CATEGORIES = frozenset(("stage", "compute"))
WIRE_CATEGORY = "wire"
FAILOVER_CATEGORY = "failover"


def _union_ns(intervals: Sequence[Tuple[int, int]]) -> int:
    """Total length of the union of [t0, t1) intervals."""
    total = 0
    end = None
    for t0, t1 in sorted(intervals):
        if end is None or t0 > end:
            total += max(0, t1 - t0)
            end = t1
        elif t1 > end:
            total += t1 - end
            end = t1
    return total


def measure_span_cost_ns(n: int = 2000) -> float:
    """Per-record cost of the span recorder on THIS host (ns), measured on
    a throwaway ring — the basis of `span_overhead_pct`."""
    rec = SpanRecorder(rank=0, capacity=min(n, 4096))
    t0 = time.monotonic_ns()
    for i in range(n):
        with rec.span("bench", "record", mb=i):
            pass
    return (time.monotonic_ns() - t0) / n


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile (no numpy needed for a report tool)."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def percentile(vals: Sequence[float], q: float) -> float:
    """Public nearest-rank percentile over (not necessarily sorted)
    samples — the one percentile definition every report surface shares
    (trace reports, benchkit records, loadgen summaries)."""
    return _percentile(sorted(vals), q)


# span categories whose per-name duration distributions are worth a
# segment breakdown (the dispatch/transfer/emit gap-hunting view)
SEGMENT_CATEGORIES = frozenset(("stage", "wire", "quant", "feed",
                                "results"))


def segment_medians(spans: Sequence[dict],
                    cats: Optional[frozenset] = None) -> Dict[str, dict]:
    """Per-(category, name) duration percentiles over a span list:
    `{"cat/name": {"n", "p50_ms", "p95_ms"}}`. The per-segment view of
    where a microbatch's end-to-end time goes — dispatch vs transfer vs
    emit — consumed by `tools/trace_report.py` and bench.py's latency
    breakdown. Feed/results names embed microbatch ids; they are folded
    to their prefix so the table stays bounded."""
    cats = SEGMENT_CATEGORIES if cats is None else cats
    series: Dict[str, List[float]] = {}
    for s in spans:
        if s.get("cat") not in cats or s.get("t1") is None:
            continue
        name = str(s.get("name", ""))
        # fold per-mb names ("mb17") and per-peer names ("send->r2") so
        # one segment key aggregates the whole series
        for sep in ("->", "<-"):
            if sep in name:
                name = name.split(sep)[0] + sep
        if name.startswith("mb") and name[2:].isdigit():
            name = "mb"
        series.setdefault(f"{s['cat']}/{name}", []).append(
            (int(s["t1"]) - int(s["t0"])) / 1e6)
    out = {}
    for key in sorted(series):
        vals = sorted(series[key])
        out[key] = {"n": len(vals),
                    "p50_ms": round(_percentile(vals, 50), 3),
                    "p95_ms": round(_percentile(vals, 95), 3)}
    return out


def analyze_spans(spans: Sequence[dict],
                  span_cost_ns: Optional[float] = None) -> dict:
    """One merged-timeline span list -> the report record (plain dict,
    json-serializable)."""
    spans = [s for s in spans if s.get("t1") is not None]
    if not spans:
        return {"spans": 0}
    t_min = min(int(s["t0"]) for s in spans)
    t_max = max(int(s["t1"]) for s in spans)
    window_ns = max(1, t_max - t_min)

    # -- per-stage busy/idle + bubble % --------------------------------
    # Two lenses: `stage_busy` counts every stage/compute span (the
    # historical bubble number), `stage_busy_core` excludes the `emit`
    # span — the downstream hand-off, which BACKPRESSURE and slow links
    # inflate (REBALANCE.md "backpressure-inflated emit"): a straggling
    # edge makes every stage LOOK busy and deflates the all-span bubble.
    # The core lens counts only genuine work (dispatch/readback/compute),
    # so a slow-link straggler honestly reads as idle — the number the
    # gray-failure A/B compares (docs/FAULT_TOLERANCE.md).
    stage_busy: Dict[str, List[Tuple[int, int]]] = {}
    stage_busy_core: Dict[str, List[Tuple[int, int]]] = {}
    for s in spans:
        if s.get("cat") in BUSY_CATEGORIES:
            stage = s.get("stage")
            key = (f"stage{stage}" if stage is not None
                   else f"rank{s.get('rank', 0)}")
            iv = (int(s["t0"]), int(s["t1"]))
            stage_busy.setdefault(key, []).append(iv)
            if not (s.get("cat") == "stage" and s.get("name") == "emit"):
                stage_busy_core.setdefault(key, []).append(iv)
    stages = {}
    bubble_by_key = {}
    for key in sorted(stage_busy):
        busy_ns = _union_ns(stage_busy[key])
        idle_ns = max(0, window_ns - busy_ns)
        pct = 100.0 * idle_ns / window_ns
        core_ns = _union_ns(stage_busy_core.get(key, ()))
        stages[key] = {"busy_s": round(busy_ns / 1e9, 6),
                       "idle_s": round(idle_ns / 1e9, 6),
                       "bubble_pct": round(pct, 3),
                       "bubble_compute_pct": round(
                           100.0 * max(0, window_ns - core_ns)
                           / window_ns, 3)}
        bubble_by_key[key] = pct
    # headline bubble: mean over stage-indexed tracks when any span carried
    # a stage id (the rankN fallback tracks shadow the same work on DCN
    # ranks and would double-count), else over the rank tracks
    staged = [v for k, v in bubble_by_key.items() if k.startswith("stage")]
    pool = staged if staged else list(bubble_by_key.values())
    bubble_pct = round(sum(pool) / len(pool), 3) if pool else None

    # -- per-edge wire share -------------------------------------------
    edge_busy: Dict[str, List[Tuple[int, int]]] = {}
    for s in spans:
        if s.get("cat") == WIRE_CATEGORY:
            key = f"r{s.get('rank', 0)}:{s.get('name', '')}"
            edge_busy.setdefault(key, []).append(
                (int(s["t0"]), int(s["t1"])))
    edges = {}
    for key in sorted(edge_busy):
        busy_ns = _union_ns(edge_busy[key])
        edges[key] = {"busy_s": round(busy_ns / 1e9, 6),
                      "share_pct": round(100.0 * busy_ns / window_ns, 3)}

    # -- per-microbatch end-to-end latency -----------------------------
    # mb ids restart every schedule round (replays, --measure-rounds):
    # bound each (round, mb) pair separately or a two-round trace would
    # report whole-run "latencies"
    segments = round_segments(spans)
    mb_bounds: Dict[tuple, Tuple[int, int]] = {}
    for s in spans:
        mb = s.get("mb")
        if mb is None or s.get("cat") == "serve":
            continue
        t0, t1 = int(s["t0"]), int(s["t1"])
        key = (segment_index(segments, t0), int(mb))
        cur = mb_bounds.get(key)
        mb_bounds[key] = ((t0, t1) if cur is None
                          else (min(cur[0], t0), max(cur[1], t1)))
    lat_ms = sorted((t1 - t0) / 1e6 for t0, t1 in mb_bounds.values())
    mb_latency = {
        "n": len(lat_ms),
        "p50_ms": round(_percentile(lat_ms, 50), 3),
        "p95_ms": round(_percentile(lat_ms, 95), 3),
        "p99_ms": round(_percentile(lat_ms, 99), 3),
    }

    # -- failover detection -> recovery breakdown ----------------------
    failover = {}
    fo = [s for s in spans if s.get("cat") == FAILOVER_CATEGORY]
    if fo:
        by_name: Dict[str, int] = {}
        for s in fo:
            by_name[str(s["name"])] = (by_name.get(str(s["name"]), 0)
                                       + int(s["t1"]) - int(s["t0"]))
        failover = {name: round(ns / 1e9, 6)
                    for name, ns in sorted(by_name.items())}
        # each recover span already runs detection -> replay completion,
        # so per-event recovery is its own duration (summing or pairing
        # across events would count healthy time between two failovers)
        recov = sorted((int(s["t1"]) - int(s["t0"])) / 1e9
                       for s in fo if s["name"] == "recover")
        if recov:
            failover["recoveries_s"] = [round(v, 6) for v in recov]
            failover["detect_to_recover_s"] = round(max(recov), 6)

    # -- per-round bubble ----------------------------------------------
    # the same busy/idle math per schedule round: round 0 carries compile
    # and connection setup, later rounds are warm — and on a --rebalance
    # auto run, the LAST round shows the settled partition. Comparing
    # final rounds is how the rebalance A/B avoids chasing startup noise.
    rounds = []
    for t0_seg, t1_seg in segments:
        seg_window = max(1, t1_seg - t0_seg)

        def seg_mean(busy_map):
            seg_bubbles = {}
            for key, intervals in busy_map.items():
                clipped = [(max(t0, t0_seg), min(t1, t1_seg))
                           for t0, t1 in intervals
                           if t1 > t0_seg and t0 < t1_seg]
                if not clipped:
                    # the stage recorded nothing this round (e.g. failed
                    # over away): absent, not 100% idle — it must not
                    # inflate the round's mean
                    continue
                busy_ns = _union_ns(clipped)
                seg_bubbles[key] = 100.0 * max(0, seg_window - busy_ns) \
                    / seg_window
            staged_seg = [v for k, v in seg_bubbles.items()
                          if k.startswith("stage")]
            seg_pool = (staged_seg if staged_seg
                        else list(seg_bubbles.values()))
            return (round(sum(seg_pool) / len(seg_pool), 3)
                    if seg_pool else None)

        rounds.append({
            "window_s": round(seg_window / 1e9, 6),
            "bubble_pct": seg_mean(stage_busy),
            # emit excluded (see the two-lens comment above): the
            # steady-state number the gray-failure A/B compares
            "bubble_compute_pct": seg_mean(stage_busy_core),
        })

    # -- transport tiers (docs/DCN_WIRE.md selection matrix) -----------
    # negotiation instants (cat "transport", name "tier:src->dst") count
    # edges per tier; wire-span names split busy time into the colocated
    # hand-off ("local->...") vs the socket paths — the view that proves
    # where an edge's host-hop time went after a tier switch
    # edge -> (t0, tier): the runtime renegotiates every round build, so
    # an edge's tier is its LATEST negotiation, and counts are unique
    # edges — not negotiation events
    edge_tier: Dict[str, Tuple[int, str]] = {}
    for s in spans:
        if s.get("cat") == "transport":
            tier, _, edge = str(s.get("name", "")).partition(":")
            t0 = int(s.get("t0", 0))
            if edge not in edge_tier or t0 >= edge_tier[edge][0]:
                edge_tier[edge] = (t0, tier)
    tier_edges: Dict[str, int] = {}
    for _, tier in edge_tier.values():
        tier_edges[tier] = tier_edges.get(tier, 0) + 1
    local_busy = _union_ns([(int(s["t0"]), int(s["t1"])) for s in spans
                            if s.get("cat") == WIRE_CATEGORY
                            and str(s.get("name", "")).startswith("local")])
    wire_busy = _union_ns([(int(s["t0"]), int(s["t1"])) for s in spans
                           if s.get("cat") == WIRE_CATEGORY])
    transport = {
        "edges_by_tier": dict(sorted(tier_edges.items())),
        "local_edges": tier_edges.get("local", 0),
        "local_busy_s": round(local_busy / 1e9, 6),
        "local_share_pct": round(100.0 * local_busy / wire_busy, 3)
        if wire_busy else 0.0,
    }

    # -- quantized ICI collectives: per-stage bits moved ---------------
    # instant "collective" spans (ops/qcollectives.record_collectives)
    # carry their run-total wire bytes in the name ("psum8:253440"): the
    # per-stage view that separates ICI-collective traffic from the
    # DCN-edge traffic the `edges` section times — bubble attribution
    # can then say whether a stage's wire time is inter-stage (DCN) or
    # intra-stage (quantized psum/all_gather over ICI)
    collectives = {}
    col = [s for s in spans if s.get("cat") == "collective"]
    if col:
        col_per_stage: Dict[str, dict] = {}
        col_by_kind: Dict[str, int] = {}
        col_bytes = 0
        for s in col:
            kindbit, _, nbytes_str = str(s.get("name", "")).partition(":")
            try:
                nbytes = int(nbytes_str)
            except ValueError:
                nbytes = 0
            stage = s.get("stage")
            key = (f"stage{stage}" if stage is not None
                   else f"rank{s.get('rank', 0)}")
            st = col_per_stage.setdefault(key, {"sites": 0, "wire_bytes": 0})
            st["sites"] += 1
            st["wire_bytes"] += nbytes
            col_by_kind[kindbit] = col_by_kind.get(kindbit, 0) + nbytes
            col_bytes += nbytes
        dcn_busy_s = round(wire_busy / 1e9, 6)
        collectives = {
            "sites": len(col),
            "wire_bytes": col_bytes,
            "by_kind": dict(sorted(col_by_kind.items())),
            "per_stage": {k: col_per_stage[k] for k in sorted(col_per_stage)},
            # the ICI-vs-DCN split: bytes the collectives moved beside the
            # time the DCN edges spent (the edges section holds per-edge
            # detail; this is the one-glance comparison)
            "dcn_edge_busy_s": dcn_busy_s,
        }

    # -- closed-loop rebalancing --------------------------------------
    # "plan" spans time every consideration; an instant "apply" span marks
    # each ACCEPTED re-partition (the zero-churn assertion counts these)
    rebalance_events = sum(1 for s in spans
                           if s.get("cat") == "rebalance"
                           and s.get("name") == "apply")

    # -- elastic membership: rejoin -> heal breakdown ------------------
    # an instant "admit" span per JOIN admission; each "heal" span runs
    # the episode's first death detection -> partition healed, i.e. its
    # duration IS the time-to-full-capacity (docs/FAULT_TOLERANCE.md)
    rejoin = {}
    rj = [s for s in spans if s.get("cat") == "rejoin"]
    if rj:
        heals = sorted((int(s["t1"]) - int(s["t0"])) / 1e9
                       for s in rj if s["name"] == "heal")
        rejoin = {
            "admissions": sum(1 for s in rj if s["name"] == "admit"),
            "heals": len(heals),
        }
        if heals:
            rejoin["heals_s"] = [round(v, 6) for v in heals]
            rejoin["time_to_full_capacity_s"] = round(max(heals), 6)

    # -- gray failures: peer-health transitions ------------------------
    # instant "health" spans, one per lifecycle transition, with the
    # affected rank in the name ("quarantine:r2"): suspect / quarantine /
    # readmit (quarantined -> probation) / recovered (probation ->
    # healthy) / held (min-fleet floor refused the bench) — the section
    # the gray-failure CI smoke gates on (exactly one quarantine on the
    # chaos run, ZERO on the clean run). docs/FAULT_TOLERANCE.md.
    gray = {}
    hl = [s for s in spans if s.get("cat") == "health"]
    if hl:
        by_kind: Dict[str, int] = {}
        by_rank: Dict[str, List[str]] = {}
        for s in hl:
            kind, _, target = str(s.get("name", "")).partition(":")
            by_kind[kind] = by_kind.get(kind, 0) + 1
            if target:
                by_rank.setdefault(target, []).append(kind)
        gray = {
            "suspects": by_kind.get("suspect", 0),
            "quarantines": by_kind.get("quarantine", 0),
            "readmits": by_kind.get("readmit", 0),
            "recovered": by_kind.get("recovered", 0),
            "held": by_kind.get("held", 0),
            "by_rank": {k: by_rank[k] for k in sorted(by_rank)},
        }

    # -- autoscale: capacity-controller decisions ----------------------
    # cat "autoscale" spans from the CapacityController: "plan:{dir}"
    # (dry-run duration), "apply:{dir}" (actuation duration), instant
    # "held:{dir}" (un-runnable plan / failed actuator) and
    # "flap_damped:{dir}" (damper swallowed a reversal). The chaos CI
    # gates on this section: scale-up AND scale-down observed under the
    # ramp, ZERO decisions on the steady control run.
    autoscale = {}
    al = [s for s in spans if s.get("cat") == "autoscale"]
    if al:
        as_kinds: Dict[str, int] = {}
        as_dirs: Dict[str, Dict[str, int]] = {}
        apply_ms: List[float] = []
        for s in al:
            kind, _, direction = str(s.get("name", "")).partition(":")
            as_kinds[kind] = as_kinds.get(kind, 0) + 1
            if direction:
                d = as_dirs.setdefault(direction, {})
                d[kind] = d.get(kind, 0) + 1
            if kind == "apply":
                apply_ms.append((int(s["t1"]) - int(s["t0"])) / 1e6)
        autoscale = {
            "plans": as_kinds.get("plan", 0),
            "applies": as_kinds.get("apply", 0),
            "held": as_kinds.get("held", 0),
            "flap_damped": as_kinds.get("flap_damped", 0),
            "by_direction": {k: dict(sorted(v.items()))
                             for k, v in sorted(as_dirs.items())},
        }
        if apply_ms:
            apply_ms.sort()
            autoscale["apply_ms"] = {
                "n": len(apply_ms),
                "p50": round(_percentile(apply_ms, 50), 3),
                "max": round(apply_ms[-1], 3)}

    # -- serving plane: admission waits / sheds / brownout -------------
    # tools/serve.py records cat "serve" spans: "admit:{class}" (duration
    # = EDF-queue wait of an ADMITTED request — shed waits record under
    # "shed:{class}:{reason}" so they can't skew this stat), instant
    # "brownout:{level}" per ladder transition, and "generate"/
    # "speculative" around each admitted request (docs/SERVING.md)
    serving = {}
    sv = [s for s in spans if s.get("cat") == "serve"]
    if sv:
        admit_waits: Dict[str, List[float]] = {}
        sheds_by_class: Dict[str, int] = {}
        sheds_by_reason: Dict[str, int] = {}
        levels: List[int] = []
        for s in sv:
            name = str(s["name"])
            if name.startswith("admit:"):
                admit_waits.setdefault(name[len("admit:"):], []).append(
                    (int(s["t1"]) - int(s["t0"])) / 1e6)
            elif name.startswith("shed:"):
                _, cls, reason = name.split(":", 2)
                sheds_by_class[cls] = sheds_by_class.get(cls, 0) + 1
                sheds_by_reason[reason] = sheds_by_reason.get(reason, 0) + 1
            elif name.startswith("brownout:"):
                levels.append(int(name[len("brownout:"):]))
        serving = {
            "requests": sum(1 for s in sv
                            if s["name"] in ("generate", "speculative")),
            "admit_wait_ms": {
                cls: {"n": len(vals),
                      "p50": round(_percentile(sorted(vals), 50), 3),
                      "p95": round(_percentile(sorted(vals), 95), 3)}
                for cls, vals in sorted(admit_waits.items())},
            "sheds": sum(sheds_by_class.values()),
            "sheds_by_class": dict(sorted(sheds_by_class.items())),
            "sheds_by_reason": dict(sorted(sheds_by_reason.items())),
            "brownout": {"transitions": len(levels),
                         "max_level": max(levels) if levels else 0},
        }

    # -- request dimension (request-scoped tracing) --------------------
    # every rid-tagged span belongs to one request's causal timeline;
    # the worst list is the "which request do I trace_report --request"
    # entry point when no loadgen/504 artifact named one
    rid_bounds: Dict[str, Tuple[int, int]] = {}
    for s in spans:
        rid = s.get("rid")
        if rid is None:
            continue
        t0, t1 = int(s["t0"]), int(s["t1"])
        cur = rid_bounds.get(rid)
        rid_bounds[rid] = ((t0, t1) if cur is None
                           else (min(cur[0], t0), max(cur[1], t1)))
    worst = sorted(((t1 - t0) / 1e6, rid)
                   for rid, (t0, t1) in rid_bounds.items())[-3:]
    requests = {}
    if rid_bounds:
        requests = {"n": len(rid_bounds),
                    "worst": [{"rid": rid, "ms": round(ms, 3)}
                              for ms, rid in reversed(worst)]}

    if span_cost_ns is None:
        span_cost_ns = measure_span_cost_ns()
    overhead_pct = 100.0 * len(spans) * span_cost_ns / window_ns

    return {
        "spans": len(spans),
        "ranks": sorted({int(s.get("rank", 0)) for s in spans}),
        "window_s": round(window_ns / 1e9, 6),
        "bubble_pct": bubble_pct,
        "rounds": rounds,
        "stages": stages,
        "edges": edges,
        "segments": segment_medians(spans),
        "transport": transport,
        "collectives": collectives,
        "mb_latency": mb_latency,
        "serving": serving,
        "requests": requests,
        "failover": failover,
        "rejoin": rejoin,
        "gray": gray,
        "autoscale": autoscale,
        "rebalance_events": rebalance_events,
        "span_cost_ns": round(span_cost_ns, 1),
        "span_overhead_pct": round(overhead_pct, 4),
    }


# -- request-scoped causal timeline (trace_report --request) -------------

def _segment_key(s: dict) -> Optional[str]:
    """Attribution bucket of one request-tagged span: the named slice of
    the request's end-to-end time this span explains. None = an envelope
    span (the whole-request wrapper) that must not compete with its own
    parts for the dominant-stall title."""
    cat = str(s.get("cat", ""))
    name = str(s.get("name", ""))
    stage = s.get("stage")
    if cat == "serve":
        if name.startswith("admit:"):
            return "queue_wait"
        if name.startswith("shed:"):
            return "shed_wait"
        return None                     # generate/speculative: envelope
    if cat == "router":
        if name.startswith(("dispatch:", "stream:")):
            # route hop to a named replica — the fleet timeline's
            # router-side view of each attempt/failover leg
            return f"route/{name.split(':', 1)[1]}"
        return None                     # admit/health_poll: envelope
    if cat == "compute":
        return f"stage{stage}/compute" if stage is not None else "compute"
    if cat == "stage":
        if name in ("dispatch", "readback", "emit"):
            return (f"stage{stage}/{name}" if stage is not None
                    else name)
        # executor exec{i} / host-pipeline stage{i}: per-stage compute
        return (f"stage{stage}/compute" if stage is not None
                else f"{name}/compute")
    if cat == "wire":
        return f"wire/{name}"
    if cat == "quant":
        return f"stage{stage}/quant" if stage is not None else "quant"
    if cat == "feed":
        return "feed"
    if cat == "results":
        return "retire"
    return None


def _rid_tree_member(span_rid, rid: str) -> bool:
    """`span_rid` is `rid` itself or a dot-suffixed descendant — the
    derivation grammar `rid[.tN|.hedge|.foN|.replay]*` the router and
    executors mint (docs/OBSERVABILITY.md fleet observatory)."""
    if not isinstance(span_rid, str):
        return False
    return span_rid == rid or span_rid.startswith(rid + ".")


def request_timeline(spans: Sequence[dict], rid: str,
                     max_events: int = 400, tree: bool = True) -> dict:
    """One request's causal timeline from a merged span list: every span
    in `rid`'s derivation tree (the rid plus its retry/hedge/failover-
    replay children — `tree=False` pins exact-match), ordered,
    attributed to named segments (queue wait, route hops, per-stage
    compute/dispatch/readback/emit, per-edge transfer, feed, retire),
    with the DOMINANT STALL — the segment whose union-busy time
    explains the largest share of the request's end-to-end window —
    called out. The artifact that answers "why was THIS request slow"
    (ISSUE 10 acceptance; ISSUE 18 extends it across the routed
    fleet)."""
    if tree:
        mine = [s for s in spans
                if _rid_tree_member(s.get("rid"), rid)
                and s.get("t1") is not None]
    else:
        mine = [s for s in spans
                if s.get("rid") == rid and s.get("t1") is not None]
    if not mine:
        return {"rid": rid, "found": False}
    mine.sort(key=lambda s: (int(s["t0"]), int(s["t1"])))
    t_lo = min(int(s["t0"]) for s in mine)
    t_hi = max(int(s["t1"]) for s in mine)
    total_ns = max(1, t_hi - t_lo)

    seg_intervals: Dict[str, List[Tuple[int, int]]] = {}
    all_intervals: List[Tuple[int, int]] = []
    for s in mine:
        key = _segment_key(s)
        iv = (int(s["t0"]), int(s["t1"]))
        if key is not None:
            seg_intervals.setdefault(key, []).append(iv)
            all_intervals.append(iv)
    segments = {}
    busy_by_key = {}
    for key in sorted(seg_intervals):
        busy_ns = _union_ns(seg_intervals[key])
        busy_by_key[key] = busy_ns
        segments[key] = {"n": len(seg_intervals[key]),
                         "busy_ms": round(busy_ns / 1e6, 3),
                         "share_pct": round(100.0 * busy_ns / total_ns, 3)}
    dominant = None
    if segments:
        # rank on raw ns (rounded ms would tie sub-ms segments)
        name = max(busy_by_key, key=busy_by_key.get)
        dominant = {"segment": name, **segments[name]}
    unattributed_ns = max(0, total_ns - _union_ns(all_intervals))

    timeline = [{"t_ms": round((int(s["t0"]) - t_lo) / 1e6, 3),
                 "dur_ms": round((int(s["t1"]) - int(s["t0"])) / 1e6, 3),
                 "cat": s.get("cat"), "name": s.get("name"),
                 "rank": s.get("rank"), "stage": s.get("stage"),
                 "mb": s.get("mb")}
                for s in mine[:max_events]]
    return {
        "rid": rid,
        "found": True,
        "spans": len(mine),
        "rids": sorted({str(s.get("rid")) for s in mine}),
        "ranks": sorted({int(s.get("rank", 0)) for s in mine}),
        "stages": sorted({int(s["stage"]) for s in mine
                          if s.get("stage") is not None}),
        "mbs": sorted({int(s["mb"]) for s in mine
                       if s.get("mb") is not None}),
        "total_ms": round(total_ns / 1e6, 3),
        "segments": segments,
        "dominant_stall": dominant,
        "unattributed_ms": round(unattributed_ns / 1e6, 3),
        "timeline": timeline,
    }
