"""Merged fleet timeline as Chrome trace-event JSON (Perfetto-loadable).

One "process" per rank, one "thread" (track) per span category, microbatch
ids as flow events so a microbatch can be followed hop-to-hop across ranks
— feed on the data rank, dispatch/compute/readback on each stage rank,
wire send/recv on every edge, results back at the data rank.

The input is the per-rank span buffers ALREADY aligned onto one timeline
(telemetry.align_spans with the NTP-style offsets `collect_spans`
estimates); this module only lays them out. Output is deterministic for a
fixed span set: events are emitted in sorted order and no wall-clock or
randomness enters the encoding — byte-identical JSON for byte-identical
inputs (the CI artifact diff relies on this).

View: load the JSON in https://ui.perfetto.dev (or chrome://tracing).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from . import round_segments, segment_index

# stable track order within each rank's process (unknown categories sort
# after these, alphabetically)
_CATEGORY_ORDER = ("runtime", "feed", "stage", "compute", "quant", "wire",
                   "results", "failover", "rebalance", "serve", "monitor")

# categories whose mb-tagged spans carry the microbatch flow arrows; wire
# spans are untagged (the transport does not parse frame payloads), so the
# flow follows the host-side lifecycle spans
_FLOW_CATEGORIES = frozenset(("feed", "stage", "compute", "results"))


def _tid_for(cat: str) -> int:
    try:
        return _CATEGORY_ORDER.index(cat)
    except ValueError:
        return len(_CATEGORY_ORDER) + sum(map(ord, cat)) % 64


def build_trace(spans: Sequence[dict],
                rank_names: Optional[Dict[int, str]] = None) -> dict:
    """Aligned span dicts (any ranks mixed) -> Chrome trace-event document.

    Timestamps are re-based to the earliest span (Perfetto renders from 0)
    and expressed in microseconds with ns precision kept as fractions.
    """
    spans = sorted(spans, key=lambda s: (int(s["t0"]), int(s["t1"]),
                                         int(s["rank"]), str(s["cat"]),
                                         str(s["name"])))
    events: List[dict] = []
    base = int(spans[0]["t0"]) if spans else 0
    seen_tracks = set()
    # mb ids restart each schedule round: flow groups key on (round, mb)
    # so a replayed/re-run microbatch never chains to the previous round's
    segments = round_segments(spans)
    flows: Dict[tuple, List[dict]] = {}
    for s in spans:
        rank, cat = int(s["rank"]), str(s["cat"])
        if (rank, cat) not in seen_tracks:
            seen_tracks.add((rank, cat))
        ts = (int(s["t0"]) - base) / 1e3
        dur = max(int(s["t1"]) - int(s["t0"]), 0) / 1e3
        args = {"rank": rank}
        if s.get("stage") is not None:
            args["stage"] = int(s["stage"])
        if s.get("mb") is not None:
            args["mb"] = int(s["mb"])
        if s.get("rid") is not None:
            # request id (trace context): the key trace_report --request
            # correlates on, and a Perfetto-searchable arg
            args["rid"] = str(s["rid"])
        ev = {"ph": "X", "pid": rank, "tid": _tid_for(cat), "cat": cat,
              "name": str(s["name"]), "ts": ts, "dur": dur, "args": args}
        events.append(ev)
        if s.get("mb") is not None and cat in _FLOW_CATEGORIES:
            seg = segment_index(segments, int(s["t0"]))
            flows.setdefault((seg, int(s["mb"])), []).append(ev)

    # microbatch flow arrows: start at the first hop, step through every
    # later hop ("t" = enclosing-slice binding), so Perfetto draws the
    # hop-to-hop path of each microbatch across rank processes
    for seg, mb in sorted(flows):
        hops = flows[(seg, mb)]
        if len(hops) < 2:
            continue
        # distinct flow id per (round, mb) group; readable mb in the name
        fid = (seg + 1) * 1_000_000 + mb
        for i, ev in enumerate(hops):
            ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
            flow = {"ph": ph, "pid": ev["pid"], "tid": ev["tid"],
                    "cat": "mb", "name": f"mb{mb}", "id": fid,
                    "ts": ev["ts"] + (0.0 if i == 0 else ev["dur"] / 2)}
            if ph == "f":
                flow["bp"] = "e"
            events.append(flow)

    meta: List[dict] = []
    for rank in sorted({r for r, _ in seen_tracks}):
        name = (rank_names or {}).get(rank, f"rank {rank}")
        meta.append({"ph": "M", "pid": rank, "name": "process_name",
                     "args": {"name": name}})
        meta.append({"ph": "M", "pid": rank, "name": "process_sort_index",
                     "args": {"sort_index": rank}})
    for rank, cat in sorted(seen_tracks):
        meta.append({"ph": "M", "pid": rank, "tid": _tid_for(cat),
                     "name": "thread_name", "args": {"name": cat}})
        meta.append({"ph": "M", "pid": rank, "tid": _tid_for(cat),
                     "name": "thread_sort_index",
                     "args": {"sort_index": _tid_for(cat)}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_trace(spans: Sequence[dict], path: str,
               rank_names: Optional[Dict[int, str]] = None) -> dict:
    """Write the merged trace JSON to `path`; returns the document."""
    doc = build_trace(spans, rank_names=rank_names)
    with open(path, "w", encoding="utf8") as f:
        json.dump(doc, f, separators=(",", ":"), sort_keys=True)
    return doc


def trace_to_spans(doc: dict) -> List[dict]:
    """Inverse-ish of `build_trace`: recover span dicts from a trace
    document's complete ("X") events — what `tools/trace_report.py` reads,
    so the report runs off the same artifact Perfetto loads."""
    spans = []
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "X":
            continue
        t0 = int(round(float(ev["ts"]) * 1e3))
        spans.append({"cat": ev.get("cat", ""), "name": ev.get("name", ""),
                      "rank": int(ev.get("pid", 0)),
                      "stage": ev.get("args", {}).get("stage"),
                      "mb": ev.get("args", {}).get("mb"),
                      "rid": ev.get("args", {}).get("rid"),
                      "t0": t0,
                      "t1": t0 + int(round(float(ev.get("dur", 0)) * 1e3))})
    return spans
