"""Measured-profile extraction: span digests -> scheduler-consumable costs.

The bridge the closed loop was missing (ISSUE 4): PR 3's observability
plane *records* what every stage and edge spent per microbatch, but only a
human reading a trace report could act on it. This module turns the same
measurements into the per-stage service-time estimates the rebalancer
(`sched/rebalance.py`) re-solves the partition with:

- a **digest** is the cumulative `(cat, name, stage) -> (count, total_ns)`
  rollup each rank's `SpanRecorder` maintains (telemetry.Digest). It is
  collected per round over the DCN command channel (`collect_digest`) —
  kilobytes, no clock alignment needed (durations only) — and differenced
  against the previous round's digest for a clean per-round window.
- a **StageEstimate** decomposes one stage's measured per-microbatch time
  into the parts the solver treats differently: `dispatch`/`readback`
  scale with the layer range (the jitted shard step's device time lands in
  readback — wire.PendingWire.finalize blocks on it), while `emit` (the
  socket send, including any slow-link stall) is a per-microbatch cost the
  stage keeps no matter how few layers it carries.
- `check_estimates` is the self-test gate: the runtime refuses to rebalance
  on a window whose estimates are incomplete (a dead rank skipped, a stage
  that never dispatched) rather than re-partitioning on garbage.

The span-list entry points (`digest_from_spans`) let offline consumers —
`tools/trace_report.py --emit-profiles` — run the same extraction over a
merged trace file.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from . import DIGEST_CATEGORIES, Digest

# stage-cat span names that scale with the stage's layer range vs. the
# per-microbatch fixed part (see module docstring)
_LAYER_NAMES = frozenset(("dispatch", "readback"))
_FIXED_NAMES = frozenset(("emit",))


@dataclasses.dataclass(frozen=True)
class StageEstimate:
    """Measured per-microbatch timing of one pipeline stage (seconds)."""
    stage: int
    n: int                 # microbatches observed in the window
    dispatch_s: float      # work-thread time: decode + shard-step dispatch
    readback_s: float      # send-thread time: device completion + D2H
    emit_s: float          # send-thread time: socket send (slow links land
    # here — and stay per-microbatch no matter the layer range)
    compute_s: float = 0.0  # host dispatch of the jitted step (informational)

    @property
    def layer_s(self) -> float:
        """The part of the service time that moves with the layer range."""
        return self.dispatch_s + self.readback_s

    @property
    def fixed_s(self) -> float:
        """The part the stage keeps regardless of its layer range."""
        return self.emit_s

    @property
    def service_s(self) -> float:
        """Modeled per-microbatch service time (the solver's currency)."""
        return self.layer_s + self.fixed_s


def diff_digests(current: Digest, previous: Digest) -> Digest:
    """Per-round window: `current - previous` (both cumulative). Keys that
    regressed (a restarted rank with a fresh recorder) fall back to their
    current value rather than going negative."""
    out: Digest = {}
    for key, (n, ns) in current.items():
        pn, pns = previous.get(key, (0, 0))
        if n < pn or ns < pns:
            pn = pns = 0
        if n - pn > 0:
            out[key] = (n - pn, ns - pns)
    return out


def merge_digests(digests: Sequence[Digest]) -> Digest:
    """Sum per-rank digest windows into one fleet digest (keys are
    stage-qualified, so ranks never collide on real stage entries)."""
    out: Dict = {}
    for d in digests:
        for key, (n, ns) in d.items():
            cur = out.get(key)
            out[key] = (n + cur[0], ns + cur[1]) if cur else (n, ns)
    return out


def digest_from_spans(spans: Sequence[dict]) -> Digest:
    """The recorder's rollup, computed from a span list instead — the
    offline path (`trace_report.py --emit-profiles` over a merged trace)."""
    out: Dict = {}
    for s in spans:
        if s.get("cat") not in DIGEST_CATEGORIES or s.get("t1") is None:
            continue
        key = (str(s["cat"]), str(s["name"]), s.get("stage"))
        dur = int(s["t1"]) - int(s["t0"])
        cur = out.get(key)
        out[key] = (cur[0] + 1, cur[1] + dur) if cur else (1, dur)
    return out


def stage_estimates(digest: Digest) -> Dict[int, StageEstimate]:
    """Per-stage timing decomposition from a (fleet-merged, per-round)
    digest window. Only stage-tagged entries contribute — the DCN stage
    threads tag their dispatch/readback/emit spans with the stage id."""
    acc: Dict[int, Dict[str, List[int]]] = {}
    for (cat, name, stage), (n, ns) in digest.items():
        if stage is None:
            continue
        if cat == "stage" and (name in _LAYER_NAMES or name in _FIXED_NAMES):
            part = name
        elif cat == "compute":
            part = "compute"
        else:
            continue
        cell = acc.setdefault(int(stage), {}).setdefault(part, [0, 0])
        cell[0] += n
        cell[1] += ns

    def avg(parts, name):
        n, ns = parts.get(name, (0, 0))
        return (ns / n / 1e9) if n else 0.0

    out = {}
    for stage, parts in acc.items():
        counts = [v[0] for k, v in parts.items() if k in _LAYER_NAMES]
        out[stage] = StageEstimate(
            stage=stage,
            n=max(counts) if counts else 0,
            dispatch_s=avg(parts, "dispatch"),
            readback_s=avg(parts, "readback"),
            emit_s=avg(parts, "emit"),
            compute_s=avg(parts, "compute"))
    return out


def edge_estimates(digest: Digest) -> Dict[str, float]:
    """Mean wire transfer seconds per frame, keyed by the wire span name
    (`send->rN` / `recv<-rN`). Informational alongside the stage
    estimates: the socket time already rides in each stage's `emit`."""
    out = {}
    for (cat, name, _stage), (n, ns) in digest.items():
        if cat == "wire" and n:
            out[name] = ns / n / 1e9
    return out


def check_estimates(estimates: Dict[int, StageEstimate], n_stages: int,
                    min_samples: int = 1) -> List[str]:
    """Self-test of a measurement window before anyone acts on it: every
    stage present, enough microbatches observed, no degenerate timings.
    Returns human-readable problems (empty = trustworthy)."""
    problems = []
    for stage in range(n_stages):
        est = estimates.get(stage)
        if est is None:
            problems.append(f"stage {stage}: no measurements in the window")
            continue
        if est.n < min_samples:
            problems.append(f"stage {stage}: only {est.n} microbatch(es) "
                            f"observed (need >= {min_samples})")
        if est.service_s <= 0.0:
            problems.append(f"stage {stage}: non-positive service time "
                            f"({est.service_s:.9f}s)")
    for stage in sorted(estimates):
        if not 0 <= stage < n_stages:
            problems.append(f"stage {stage}: outside the {n_stages}-stage "
                            "schedule (stale digest window?)")
    return problems
