"""Fleet-wide microbatch tracing: span recorder, clock alignment, wire codec.

The reference ships only wall-clock offline profiling and per-rank heartbeat
CSVs (SURVEY.md §5.1) — nothing answers *why* a pipeline round was slow:
which stage bubbled, which edge's wire time dominated, where a failover
stalled the fleet. This subsystem is the missing correlation layer:

- `SpanRecorder`: a fixed-size per-rank ring buffer of
  `(category, name, rank, stage, mb, t_start_ns, t_end_ns)` records,
  `time.monotonic_ns()`-stamped, drop-oldest under pressure — a `record()`
  NEVER blocks the hot send/dispatch threads it instruments.
- module-level `configure()` / `span()` / `record()`: the instrumentation
  surface. Recording is OFF by default; when off, `span()` returns a shared
  no-op context manager, so the hot-path cost of a disabled probe is one
  global read and one attribute call (see `tools/trace_report.py`'s
  `span_overhead_pct` self-measurement for the enabled cost).
- `spans_to_wire` / `spans_from_wire`: span buffers as a single uint8
  ndarray (UTF-8 JSON), the only payload type the DCN command channel
  carries — how a peer's buffer travels in a `_MSG_SPANS` reply
  (comm/dcn.py `collect_spans`).
- `estimate_clock_offset`: NTP-style offset from request/reply timestamp
  quadruples, so every rank's `monotonic_ns` spans merge onto the
  collector's timeline (chrome_trace.py).

Span categories in use (docs/OBSERVABILITY.md has the full reference):
`wire` (socket send/recv), `stage` (DCN stage dispatch/readback; host
pipeline per-stage dispatch/retire), `compute` (the jitted shard step),
`quant` (wire encode/decode), `feed`/`results` (data-rank microbatch
lifecycle), `runtime` (schedule rounds), `failover` (detection→recovery),
`rejoin` (JOIN admission → heal-to-full-capacity), `serve` (HTTP request
lifecycle).
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.threads import make_lock

ENV_SPAN_CAPACITY = "PIPEEDGE_SPAN_CAPACITY"
DEFAULT_SPAN_CAPACITY = 32768

# dict-record field order (also the ring tuple layout)
_FIELDS = ("cat", "name", "rank", "stage", "mb", "t0", "t1")

# categories folded into the cumulative digest (sched/rebalance.py's
# sensor): bounded name sets only — feed/results names embed microbatch
# ids and would grow the digest without bound
DIGEST_CATEGORIES = frozenset(("stage", "compute", "wire", "quant"))

# a digest maps (cat, name, stage) -> (count, total_ns), CUMULATIVE since
# the recorder was configured — consumers difference two digests to get a
# per-round window (feedback.diff_digests), so the fixed-size ring's
# drop-oldest behavior never corrupts the numbers
Digest = Dict[Tuple[str, str, Optional[int]], Tuple[int, int]]


class SpanRecorder:
    """Fixed-size ring of completed spans (drop-oldest under pressure).

    `record()` is the only hot-path entry: two clock reads happen in the
    caller (`_Span`), so the recorder itself is one short lock + one deque
    append — it never blocks on I/O, never allocates beyond the tuple, and
    overflow silently drops the OLDEST span (the ring keeps the most recent
    window, which is the one a post-mortem wants) while counting drops.
    """

    def __init__(self, rank: int = 0, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.getenv(ENV_SPAN_CAPACITY,
                                     str(DEFAULT_SPAN_CAPACITY)))
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.rank = rank
        self.capacity = capacity
        self.dropped = 0
        self._ring: deque = deque(maxlen=capacity)
        # cumulative (cat, name, stage) -> [count, total_ns] rollup for
        # DIGEST_CATEGORIES spans; what a lightweight per-round collection
        # (dcn.collect_digest) ships instead of the full ring
        self._digest: Dict[Tuple[str, str, Optional[int]], List[int]] = {}
        self._lock = make_lock("telemetry.span_ring")

    def record(self, cat: str, name: str, t0: int, t1: int,
               stage: Optional[int] = None, mb: Optional[int] = None) -> None:
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append((cat, name, self.rank, stage, mb, t0, t1))
            if cat in DIGEST_CATEGORIES:
                cell = self._digest.get((cat, name, stage))
                if cell is None:
                    self._digest[(cat, name, stage)] = [1, t1 - t0]
                else:
                    cell[0] += 1
                    cell[1] += t1 - t0

    def span(self, cat: str, name: str, stage: Optional[int] = None,
             mb: Optional[int] = None) -> "_Span":
        """Context manager recording [enter, exit] as one span."""
        return _Span(self, cat, name, stage, mb)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> List[dict]:
        """Copy the ring as a list of span dicts (oldest first)."""
        with self._lock:
            rows = list(self._ring)
        return [dict(zip(_FIELDS, r)) for r in rows]

    def drain(self) -> List[dict]:
        """Snapshot AND clear the ring (per-round collection)."""
        with self._lock:
            rows = list(self._ring)
            self._ring.clear()
        return [dict(zip(_FIELDS, r)) for r in rows]

    def digest(self) -> "Digest":
        """Cumulative duration rollup of every DIGEST_CATEGORIES span this
        recorder ever saw: (cat, name, stage) -> (count, total_ns). Unlike
        the ring it never drops, so two digests difference cleanly into a
        per-round window (telemetry/feedback.py)."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._digest.items()}


class _Span:
    """Live span: stamps monotonic_ns on enter/exit, records on exit."""

    __slots__ = ("_rec", "_cat", "_name", "_stage", "_mb", "_t0")

    def __init__(self, rec, cat, name, stage, mb):
        self._rec = rec
        self._cat = cat
        self._name = name
        self._stage = stage
        self._mb = mb

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        self._rec.record(self._cat, self._name, self._t0,
                         time.monotonic_ns(), self._stage, self._mb)
        return False


class _NullSpan:
    """Shared no-op context manager: the disabled-probe fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()
_recorder: Optional[SpanRecorder] = None


def configure(rank: int = 0, capacity: Optional[int] = None) -> SpanRecorder:
    """Enable span recording process-wide (idempotent per process: a second
    call replaces the recorder — fresh ring, same instrumentation)."""
    global _recorder  # pylint: disable=global-statement
    _recorder = SpanRecorder(rank=rank, capacity=capacity)
    return _recorder


def disable() -> None:
    """Drop the recorder: probes revert to the no-op fast path."""
    global _recorder  # pylint: disable=global-statement
    _recorder = None


def recorder() -> Optional[SpanRecorder]:
    return _recorder


def enabled() -> bool:
    return _recorder is not None


def span(cat: str, name: str, stage: Optional[int] = None,
         mb: Optional[int] = None):
    """Instrumentation probe: a recording span when configured, the shared
    no-op otherwise. Safe on any thread."""
    rec = _recorder
    if rec is None:
        return _NULL_SPAN
    return _Span(rec, cat, name, stage, mb)


def record(cat: str, name: str, t0: int, t1: int,
           stage: Optional[int] = None, mb: Optional[int] = None) -> None:
    """Record a pre-timed span (e.g. failover detection→recovery, whose
    endpoints live on different threads); no-op when disabled."""
    rec = _recorder
    if rec is not None:
        rec.record(cat, name, t0, t1, stage=stage, mb=mb)


# -- wire codec (DCN command-channel payloads are ndarrays only) ---------

def spans_to_wire(spans: Sequence[dict]) -> np.ndarray:
    """Span dicts -> one uint8 ndarray (UTF-8 JSON) for a command frame."""
    blob = json.dumps([[s.get(f) for f in _FIELDS] for s in spans],
                      separators=(",", ":")).encode()
    return np.frombuffer(blob, np.uint8)


def spans_from_wire(arr: np.ndarray) -> List[dict]:
    """Inverse of `spans_to_wire`; tolerates an empty reply (no recorder
    on the peer)."""
    blob = bytes(np.asarray(arr, np.uint8))
    if not blob:
        return []
    return [dict(zip(_FIELDS, row)) for row in json.loads(blob)]


def digest_to_wire(digest: "Digest") -> np.ndarray:
    """Digest -> one uint8 ndarray (UTF-8 JSON rows
    [cat, name, stage, count, total_ns]) for a command frame — the
    kilobyte-scale payload a per-round rebalance collection ships instead
    of the megabyte-scale full ring."""
    rows = [[cat, name, stage, int(n), int(ns)]
            for (cat, name, stage), (n, ns) in sorted(
                digest.items(), key=lambda kv: (kv[0][0], kv[0][1],
                                                -1 if kv[0][2] is None
                                                else kv[0][2]))]
    blob = json.dumps(rows, separators=(",", ":")).encode()
    return np.frombuffer(blob, np.uint8)


def digest_from_wire(arr: np.ndarray) -> "Digest":
    """Inverse of `digest_to_wire`; tolerates an empty reply (no recorder
    on the peer)."""
    blob = bytes(np.asarray(arr, np.uint8))
    if not blob:
        return {}
    return {(cat, name, stage): (int(n), int(ns))
            for cat, name, stage, n, ns in json.loads(blob)}


# -- clock alignment -----------------------------------------------------

def estimate_clock_offset(samples: Sequence[Tuple[int, int, int, int]]) -> int:
    """NTP-style peer-clock offset from `(t0, t1, t2, t3)` quadruples:
    local send, peer receive, peer reply, local receive (all ns, each on
    its own monotonic clock).

    Returns theta = peer_clock - local_clock (ns), taken from the
    minimum-round-trip sample — the one whose network legs were most
    symmetric, hence the tightest bound (classic NTP filter). Map a peer
    timestamp onto the local timeline with `t_local = t_peer - theta`;
    the residual error is bounded by half that sample's RTT.
    """
    if not samples:
        raise ValueError("need at least one timestamp sample")
    best = min(samples, key=lambda s: (s[3] - s[0]) - (s[2] - s[1]))
    t0, t1, t2, t3 = best
    return ((t1 - t0) + (t2 - t3)) // 2


def round_segments(spans: Sequence[dict]) -> List[Tuple[int, int]]:
    """Merged [t0, t1] interval per named `runtime` round span, sorted by
    start. Microbatch ids restart at 0 every schedule round (re-schedule
    rounds replay the same batch; --measure-rounds reruns it), so any
    consumer correlating spans BY mb id must segment the timeline by these
    intervals first — every rank records its own round span, hence the
    per-name merge."""
    by_name = {}
    for s in spans:
        if s.get("cat") != "runtime":
            continue
        t0, t1 = int(s["t0"]), int(s["t1"])
        cur = by_name.get(s["name"])
        by_name[s["name"]] = ((t0, t1) if cur is None
                              else (min(cur[0], t0), max(cur[1], t1)))
    return sorted(by_name.values())


def segment_index(segments: Sequence[Tuple[int, int]], t: int) -> int:
    """Index of the last segment starting at or before `t` (-1 if none):
    which round a span belongs to."""
    idx = -1
    for i, (t0, _) in enumerate(segments):
        if t0 <= t:
            idx = i
        else:
            break
    return idx


def align_spans(spans: Sequence[dict], offset_ns: int) -> List[dict]:
    """Shift a peer's spans onto the collector's timeline
    (`t_local = t_peer - offset_ns`, see `estimate_clock_offset`)."""
    out = []
    for s in spans:
        s = dict(s)
        s["t0"] = int(s["t0"]) - offset_ns
        s["t1"] = int(s["t1"]) - offset_ns
        out.append(s)
    return out
