"""Fleet-wide microbatch tracing: span recorder, clock alignment, wire codec.

The reference ships only wall-clock offline profiling and per-rank heartbeat
CSVs (SURVEY.md §5.1) — nothing answers *why* a pipeline round was slow:
which stage bubbled, which edge's wire time dominated, where a failover
stalled the fleet. This subsystem is the missing correlation layer:

- `SpanRecorder`: a fixed-size per-rank ring buffer of
  `(category, name, rank, stage, mb, t_start_ns, t_end_ns, rid)` records,
  `time.monotonic_ns()`-stamped, drop-oldest under pressure — a `record()`
  NEVER blocks the hot send/dispatch threads it instruments. `rid` is the
  request id of the span's `TraceContext` (request-scoped tracing), None
  when untraced.
- module-level `configure()` / `span()` / `record()`: the instrumentation
  surface. Recording is OFF by default; when off, `span()` returns a shared
  no-op context manager, so the hot-path cost of a disabled probe is one
  global read and one attribute call (see `tools/trace_report.py`'s
  `span_overhead_pct` self-measurement for the enabled cost).
- `spans_to_wire` / `spans_from_wire`: span buffers as a single uint8
  ndarray (UTF-8 JSON), the only payload type the DCN command channel
  carries — how a peer's buffer travels in a `_MSG_SPANS` reply
  (comm/dcn.py `collect_spans`).
- `estimate_clock_offset`: NTP-style offset from request/reply timestamp
  quadruples, so every rank's `monotonic_ns` spans merge onto the
  collector's timeline (chrome_trace.py).

Span categories in use (docs/OBSERVABILITY.md has the full reference):
`wire` (socket send/recv), `stage` (DCN stage dispatch/readback; host
pipeline per-stage dispatch/retire), `compute` (the jitted shard step),
`quant` (wire encode/decode), `feed`/`results` (data-rank microbatch
lifecycle), `runtime` (schedule rounds), `failover` (detection→recovery),
`rejoin` (JOIN admission → heal-to-full-capacity), `health` (gray-failure
lifecycle transitions, pipeedge_tpu/health/), `serve` (HTTP request
lifecycle).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.threads import make_lock

ENV_SPAN_CAPACITY = "PIPEEDGE_SPAN_CAPACITY"
DEFAULT_SPAN_CAPACITY = 32768

# dict-record field order (also the ring tuple layout). `rid` — the
# request id of the trace context a span belongs to — sits LAST so the
# wire codec stays compatible with pre-request-tracing rows: a 7-field
# row decodes with rid absent (untraced), an 8-field row read by an old
# decoder simply drops the tail (zip truncates).
_FIELDS = ("cat", "name", "rank", "stage", "mb", "t0", "t1", "rid")

# categories folded into the cumulative digest (sched/rebalance.py's
# sensor): bounded name sets only — feed/results names embed microbatch
# ids and would grow the digest without bound
DIGEST_CATEGORIES = frozenset(("stage", "compute", "wire", "quant"))

# a digest maps (cat, name, stage) -> (count, total_ns), CUMULATIVE since
# the recorder was configured — consumers difference two digests to get a
# per-round window (feedback.diff_digests), so the fixed-size ring's
# drop-oldest behavior never corrupts the numbers
Digest = Dict[Tuple[str, str, Optional[int]], Tuple[int, int]]


class SpanRecorder:
    """Fixed-size ring of completed spans (drop-oldest under pressure).

    `record()` is the only hot-path entry: two clock reads happen in the
    caller (`_Span`), so the recorder itself is one short lock + one deque
    append — it never blocks on I/O, never allocates beyond the tuple, and
    overflow silently drops the OLDEST span (the ring keeps the most recent
    window, which is the one a post-mortem wants) while counting drops.
    """

    def __init__(self, rank: int = 0, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.getenv(ENV_SPAN_CAPACITY,
                                     str(DEFAULT_SPAN_CAPACITY)))
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.rank = rank
        self.capacity = capacity
        self.dropped = 0
        self._ring: deque = deque(maxlen=capacity)
        # cumulative (cat, name, stage) -> [count, total_ns] rollup for
        # DIGEST_CATEGORIES spans; what a lightweight per-round collection
        # (dcn.collect_digest) ships instead of the full ring
        self._digest: Dict[Tuple[str, str, Optional[int]], List[int]] = {}
        self._lock = make_lock("telemetry.span_ring")

    def record(self, cat: str, name: str, t0: int, t1: int,
               stage: Optional[int] = None, mb: Optional[int] = None,
               rid: Optional[str] = None) -> None:
        if rid is None:
            ctx = current_trace()
            if ctx is not None:
                rid = ctx.rid
        with self._lock:
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append((cat, name, self.rank, stage, mb, t0, t1,
                               rid))
            if cat in DIGEST_CATEGORIES:
                cell = self._digest.get((cat, name, stage))
                if cell is None:
                    self._digest[(cat, name, stage)] = [1, t1 - t0]
                else:
                    cell[0] += 1
                    cell[1] += t1 - t0

    def span(self, cat: str, name: str, stage: Optional[int] = None,
             mb: Optional[int] = None,
             rid: Optional[str] = None) -> "_Span":
        """Context manager recording [enter, exit] as one span."""
        return _Span(self, cat, name, stage, mb, rid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def snapshot(self) -> List[dict]:
        """Copy the ring as a list of span dicts (oldest first)."""
        with self._lock:
            rows = list(self._ring)
        return [dict(zip(_FIELDS, r)) for r in rows]

    def drain(self) -> List[dict]:
        """Snapshot AND clear the ring (per-round collection)."""
        with self._lock:
            rows = list(self._ring)
            self._ring.clear()
        return [dict(zip(_FIELDS, r)) for r in rows]

    def digest(self) -> "Digest":
        """Cumulative duration rollup of every DIGEST_CATEGORIES span this
        recorder ever saw: (cat, name, stage) -> (count, total_ns). Unlike
        the ring it never drops, so two digests difference cleanly into a
        per-round window (telemetry/feedback.py)."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._digest.items()}


class _Span:
    """Live span: stamps monotonic_ns on enter/exit, records on exit."""

    __slots__ = ("_rec", "_cat", "_name", "_stage", "_mb", "_rid", "_t0")

    def __init__(self, rec, cat, name, stage, mb, rid=None):
        self._rec = rec
        self._cat = cat
        self._name = name
        self._stage = stage
        self._mb = mb
        self._rid = rid

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        self._rec.record(self._cat, self._name, self._t0,
                         time.monotonic_ns(), self._stage, self._mb,
                         rid=self._rid)
        return False


class _NullSpan:
    """Shared no-op context manager: the disabled-probe fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()
_recorder: Optional[SpanRecorder] = None


def configure(rank: int = 0, capacity: Optional[int] = None) -> SpanRecorder:
    """Enable span recording process-wide (idempotent per process: a second
    call replaces the recorder — fresh ring, same instrumentation)."""
    global _recorder  # pylint: disable=global-statement
    _recorder = SpanRecorder(rank=rank, capacity=capacity)
    return _recorder


def disable() -> None:
    """Drop the recorder: probes revert to the no-op fast path."""
    global _recorder  # pylint: disable=global-statement
    _recorder = None


def recorder() -> Optional[SpanRecorder]:
    return _recorder


def enabled() -> bool:
    return _recorder is not None


def span(cat: str, name: str, stage: Optional[int] = None,
         mb: Optional[int] = None, rid: Optional[str] = None):
    """Instrumentation probe: a recording span when configured, the shared
    no-op otherwise. Safe on any thread. `rid` tags the span with a
    request id; None picks up the calling thread's current trace context
    (set_trace / trace_scope) at record time."""
    rec = _recorder
    if rec is None:
        return _NULL_SPAN
    return _Span(rec, cat, name, stage, mb, rid)


def record(cat: str, name: str, t0: int, t1: int,
           stage: Optional[int] = None, mb: Optional[int] = None,
           rid: Optional[str] = None) -> None:
    """Record a pre-timed span (e.g. failover detection→recovery, whose
    endpoints live on different threads); no-op when disabled."""
    rec = _recorder
    if rec is not None:
        rec.record(cat, name, t0, t1, stage=stage, mb=mb, rid=rid)


# -- request-scoped trace context (docs/OBSERVABILITY.md) ----------------

class TraceContext:
    """Compact per-request trace identity, threaded end-to-end: minted at
    admission (tools/serve.py) or per microbatch at the data rank's feed
    (runtime.py), carried through the executors, and across DCN frames
    (`comm/dcn.py` `_MSG_TENSORS_TRACED`) so every rank's spans inherit
    the request id fleet-wide.

    Fields: `rid` (the request id — the correlation key every span
    carries), `cls` (request class, docs/SERVING.md), `deadline_ms`
    (remaining budget at mint time, forensic), `parent` (the minting
    span/site, so a timeline names its origin)."""

    __slots__ = ("rid", "cls", "deadline_ms", "parent")

    def __init__(self, rid: str, cls: str = "interactive",
                 deadline_ms: Optional[float] = None,
                 parent: Optional[str] = None):
        self.rid = str(rid)
        self.cls = str(cls)
        self.deadline_ms = (None if deadline_ms is None
                            else float(deadline_ms))
        self.parent = None if parent is None else str(parent)

    def to_dict(self) -> dict:
        d = {"rid": self.rid, "cls": self.cls}
        if self.deadline_ms is not None:
            d["deadline_ms"] = self.deadline_ms
        if self.parent is not None:
            d["parent"] = self.parent
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TraceContext":
        return cls(d["rid"], d.get("cls", "interactive"),
                   d.get("deadline_ms"), d.get("parent"))

    def to_wire(self) -> np.ndarray:
        """One uint8 ndarray (UTF-8 JSON) — the optional leading tensor a
        traced DCN frame carries (comm/dcn.py)."""
        blob = json.dumps(self.to_dict(), separators=(",", ":")).encode()
        return np.frombuffer(blob, np.uint8)

    @classmethod
    def from_wire(cls, arr) -> Optional["TraceContext"]:
        """Inverse of `to_wire`. Tolerant by contract: an empty,
        truncated, or otherwise undecodable blob means UNTRACED (None),
        never a dead reader thread — a frame without a valid context is
        still a valid frame."""
        try:
            blob = bytes(np.asarray(arr, np.uint8))
            if not blob:
                return None
            d = json.loads(blob)
            if not isinstance(d, dict) or "rid" not in d:
                return None
            return cls.from_dict(d)
        except Exception:  # noqa: BLE001 — any malformed blob = untraced
            return None

    def __repr__(self):
        return (f"TraceContext(rid={self.rid!r}, cls={self.cls!r}, "
                f"deadline_ms={self.deadline_ms}, parent={self.parent!r})")


_TRACE_TLS = threading.local()


def set_trace(ctx: Optional[TraceContext]) -> None:
    """Set (or clear, with None) the calling thread's current trace
    context: spans recorded on this thread without an explicit `rid`
    inherit it."""
    _TRACE_TLS.ctx = ctx


def current_trace() -> Optional[TraceContext]:
    return getattr(_TRACE_TLS, "ctx", None)


class trace_scope:
    """`with trace_scope(ctx):` — install `ctx` as the thread's current
    trace context for the block, restoring the previous one on exit
    (exception paths included). Reentrant; None is a valid ctx (an
    explicitly-untraced block)."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx

    def __enter__(self):
        self._prev = current_trace()
        set_trace(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        set_trace(self._prev)
        return False


# -- wire codec (DCN command-channel payloads are ndarrays only) ---------

def spans_to_wire(spans: Sequence[dict]) -> np.ndarray:
    """Span dicts -> one uint8 ndarray (UTF-8 JSON) for a command frame."""
    blob = json.dumps([[s.get(f) for f in _FIELDS] for s in spans],
                      separators=(",", ":")).encode()
    return np.frombuffer(blob, np.uint8)


def spans_from_wire(arr: np.ndarray) -> List[dict]:
    """Inverse of `spans_to_wire`; tolerates an empty reply (no recorder
    on the peer)."""
    blob = bytes(np.asarray(arr, np.uint8))
    if not blob:
        return []
    return [dict(zip(_FIELDS, row)) for row in json.loads(blob)]


def digest_to_wire(digest: "Digest") -> np.ndarray:
    """Digest -> one uint8 ndarray (UTF-8 JSON rows
    [cat, name, stage, count, total_ns]) for a command frame — the
    kilobyte-scale payload a per-round rebalance collection ships instead
    of the megabyte-scale full ring."""
    rows = [[cat, name, stage, int(n), int(ns)]
            for (cat, name, stage), (n, ns) in sorted(
                digest.items(), key=lambda kv: (kv[0][0], kv[0][1],
                                                -1 if kv[0][2] is None
                                                else kv[0][2]))]
    blob = json.dumps(rows, separators=(",", ":")).encode()
    return np.frombuffer(blob, np.uint8)


def digest_from_wire(arr: np.ndarray) -> "Digest":
    """Inverse of `digest_to_wire`; tolerates an empty reply (no recorder
    on the peer)."""
    blob = bytes(np.asarray(arr, np.uint8))
    if not blob:
        return {}
    return {(cat, name, stage): (int(n), int(ns))
            for cat, name, stage, n, ns in json.loads(blob)}


# -- clock alignment -----------------------------------------------------

def estimate_clock_offset(samples: Sequence[Tuple[int, int, int, int]]) -> int:
    """NTP-style peer-clock offset from `(t0, t1, t2, t3)` quadruples:
    local send, peer receive, peer reply, local receive (all ns, each on
    its own monotonic clock).

    Returns theta = peer_clock - local_clock (ns), taken from the
    minimum-round-trip sample — the one whose network legs were most
    symmetric, hence the tightest bound (classic NTP filter). Map a peer
    timestamp onto the local timeline with `t_local = t_peer - theta`;
    the residual error is bounded by half that sample's RTT.
    """
    if not samples:
        raise ValueError("need at least one timestamp sample")
    best = min(samples, key=lambda s: (s[3] - s[0]) - (s[2] - s[1]))
    t0, t1, t2, t3 = best
    return ((t1 - t0) + (t2 - t3)) // 2


def round_segments(spans: Sequence[dict]) -> List[Tuple[int, int]]:
    """Merged [t0, t1] interval per named `runtime` round span, sorted by
    start. Microbatch ids restart at 0 every schedule round (re-schedule
    rounds replay the same batch; --measure-rounds reruns it), so any
    consumer correlating spans BY mb id must segment the timeline by these
    intervals first — every rank records its own round span, hence the
    per-name merge."""
    by_name = {}
    for s in spans:
        if s.get("cat") != "runtime":
            continue
        t0, t1 = int(s["t0"]), int(s["t1"])
        cur = by_name.get(s["name"])
        by_name[s["name"]] = ((t0, t1) if cur is None
                              else (min(cur[0], t0), max(cur[1], t1)))
    return sorted(by_name.values())


def segment_index(segments: Sequence[Tuple[int, int]], t: int) -> int:
    """Index of the last segment starting at or before `t` (-1 if none):
    which round a span belongs to."""
    idx = -1
    for i, (t0, _) in enumerate(segments):
        if t0 <= t:
            idx = i
        else:
            break
    return idx


def align_spans(spans: Sequence[dict], offset_ns: int) -> List[dict]:
    """Shift a peer's spans onto the collector's timeline
    (`t_local = t_peer - offset_ns`, see `estimate_clock_offset`)."""
    out = []
    for s in spans:
        s = dict(s)
        s["t0"] = int(s["t0"]) - offset_ns
        s["t1"] = int(s["t1"]) - offset_ns
        out.append(s)
    return out
