"""Minimal Prometheus-text-format metrics registry (stdlib only).

The /metrics plane of the serving surface (tools/serve.py) and anything
else that wants scrapeable counters: no client library ships in the
container, and the text exposition format is simple enough to emit
directly (https://prometheus.io/docs/instrumenting/exposition_formats/).

Supported instrument types: Counter (monotonic), Gauge (set), Histogram
(cumulative buckets + _sum/_count). All are label-aware — a label-set is a
frozen sorted tuple of (key, value) pairs — and thread-safe under one
registry lock (instrument updates are a dict update + float add; the lock
is never held across I/O).

`REGISTRY` is the process default; `get_or_create` makes module-level
instrument declaration idempotent (serve restarts its service object
without restarting the process in tests).
"""
from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..utils.threads import make_lock

DEFAULT_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                           1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers bare, floats compact."""
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return f"{v:.10g}"


def _escape(v: str) -> str:
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels)
    return "{" + inner + "}"


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self._lock = make_lock(f"metrics.{name}")

    def _key(self, labels: dict) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def render(self) -> List[str]:
        raise NotImplementedError


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name: str, help_text: str):
        super().__init__(name, help_text)
        self._values: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def declare(self, **labels) -> None:
        """Pre-register a label set at 0 so the series renders before its
        first increment (scrapers see the full per-edge matrix up front)."""
        key = self._key(labels)
        with self._lock:
            self._values.setdefault(key, 0.0)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label set — the headline number for a labeled
        counter (e.g. sheds across all (class, reason) pairs)."""
        with self._lock:
            return sum(self._values.values())

    def values(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Snapshot of every (label-set, value) pair."""
        with self._lock:
            return dict(self._values)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{_label_str(k)} {_fmt(v)}" for k, v in items]


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name: str, help_text: str):
        super().__init__(name, help_text)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(self._key(labels))

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [f"{self.name}{_label_str(k)} {_fmt(v)}" for k, v in items]


class Histogram(_Instrument):
    kind = "histogram"

    # horizon after which a retained exemplar is considered stale and any
    # fresh observation replaces it (the "per bucket window" semantics:
    # within a window the MAX-latency observation's trace id is kept)
    DEFAULT_EXEMPLAR_WINDOW_S = 60.0

    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                 exemplar_window_s: Optional[float] = None):
        super().__init__(name, help_text)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        # label key -> (per-bucket counts, sum, count)
        self._series: Dict[Tuple, list] = {}
        # label key -> bucket index -> [value, trace_id, t] — the trace id
        # of the worst (max-value) observation in the current window, so a
        # p99 spike on a dashboard links straight to the request trace
        # that caused it (docs/OBSERVABILITY.md exemplar semantics). The
        # index len(buckets) is the +Inf overflow bucket.
        self.exemplar_window_s = (self.DEFAULT_EXEMPLAR_WINDOW_S
                                  if exemplar_window_s is None
                                  else float(exemplar_window_s))
        self._exemplars: Dict[Tuple, Dict[int, list]] = {}

    def observe(self, value: float, exemplar: Optional[str] = None,
                now: Optional[float] = None, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = [[0] * len(self.buckets), 0.0, 0]
                self._series[key] = s
            counts, _, _ = s
            # per-bucket (non-cumulative) storage; render() cumulates
            idx = len(self.buckets)          # +Inf overflow by default
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    idx = i
                    break
            s[1] += float(value)
            s[2] += 1
            if exemplar is not None:
                now = time.monotonic() if now is None else now
                ex = self._exemplars.setdefault(key, {})
                cur = ex.get(idx)
                # retain the max-latency observation of the window; a
                # stale (rolled-over) exemplar loses to ANY fresh one
                if cur is None or value >= cur[0] \
                        or now - cur[2] > self.exemplar_window_s:
                    ex[idx] = [float(value), str(exemplar), now]

    def exemplars(self, now: Optional[float] = None,
                  **labels) -> Dict[str, dict]:
        """Current (unexpired) exemplars for one label set:
        `{le: {"value", "trace_id", "age_s"}}` with `le` the bucket's
        upper bound as a string ("+Inf" for the overflow bucket). The
        /healthz-facing view; /metrics renders the same data as
        `# EXEMPLAR` comment lines."""
        now = time.monotonic() if now is None else now
        out: Dict[str, dict] = {}
        with self._lock:
            ex = self._exemplars.get(self._key(labels), {})
            items = [(i, list(v)) for i, v in ex.items()]
        for i, (value, trace_id, t) in sorted(items):
            if now - t > self.exemplar_window_s:
                continue
            le = ("+Inf" if i >= len(self.buckets)
                  else _fmt(self.buckets[i]))
            out[le] = {"value": round(value, 6), "trace_id": trace_id,
                       "age_s": round(max(0.0, now - t), 3)}
        return out

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(self._key(labels))
            return s[2] if s else 0

    def snapshot(self, **labels) -> Tuple[List[int], int]:
        """Copy of (per-bucket counts, total observation count) for one
        label set — the raw material for WINDOWED percentiles: diff two
        snapshots and feed the delta to `percentile_from_counts` (the
        brownout governor's p95-over-the-last-interval read)."""
        with self._lock:
            s = self._series.get(self._key(labels))
            if s is None:
                return [0] * len(self.buckets), 0
            return list(s[0]), s[2]

    def percentile(self, q: float, **labels) -> Optional[float]:
        """All-time nearest-bucket-upper-bound percentile (None when the
        series has no observations)."""
        counts, n = self.snapshot(**labels)
        return percentile_from_counts(self.buckets, counts, n, q)

    def render(self) -> List[str]:
        # ONE lock acquisition captures both the bucket counts and the
        # exemplar table: a concurrent observe() between two separate
        # acquisitions could roll an exemplar over mid-render, making
        # the rendered counts and `# EXEMPLAR` lines disagree (dropped
        # or duplicated lines under a racing scrape). Formatting — the
        # slow part — happens outside the lock on the copies.
        now = time.monotonic()
        with self._lock:
            items = sorted((k, (list(s[0]), s[1], s[2]))
                           for k, s in self._series.items())
            exemplars = {k: [(i, list(v))
                             for i, v in sorted(self._exemplars
                                                .get(k, {}).items())]
                         for k, _ in items}
        lines = []
        for key, (counts, total, n) in items:
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                lk = key + (("le", _fmt(bound)),)
                lines.append(f"{self.name}_bucket{_label_str(lk)} {cum}")
            lk = key + (("le", "+Inf"),)
            lines.append(f"{self.name}_bucket{_label_str(lk)} {n}")
            lines.append(f"{self.name}_sum{_label_str(key)} {_fmt(total)}")
            lines.append(f"{self.name}_count{_label_str(key)} {n}")
            # exemplars as COMMENT lines: the exposition stays valid
            # Prometheus text format 0.0.4 (every parser skips '#' lines
            # that are not HELP/TYPE), while the p99-spike -> trace-id
            # link is still one grep away (OpenMetrics-shaped payload)
            for idx, (value, trace_id, t) in exemplars.get(key, ()):
                if now - t > self.exemplar_window_s:
                    continue
                le = ("+Inf" if idx >= len(self.buckets)
                      else _fmt(self.buckets[idx]))
                lk = key + (("le", le),)
                lines.append(
                    f"# EXEMPLAR {self.name}_bucket{_label_str(lk)} "
                    f'{{trace_id="{_escape(trace_id)}"}} '
                    f"{_fmt(value)}")
        return lines

    def _key(self, labels: dict):
        if "le" in labels:
            raise ValueError("'le' is reserved for histogram buckets")
        return super()._key(labels)


class Registry:
    """Named instrument collection rendering to Prometheus text format."""

    def __init__(self):
        self._lock = make_lock("metrics.registry")
        self._instruments: Dict[str, _Instrument] = {}

    def register(self, inst: _Instrument) -> _Instrument:
        with self._lock:
            cur = self._instruments.get(inst.name)
            if cur is not None:
                raise ValueError(f"metric already registered: {inst.name}")
            self._instruments[inst.name] = inst
        return inst

    def get_or_create(self, cls, name: str, help_text: str, **kwargs):
        """Idempotent declaration: the existing instrument when the name is
        taken (must be the same type), else a fresh registration."""
        with self._lock:
            cur = self._instruments.get(name)
            if cur is not None:
                if not isinstance(cur, cls):
                    raise ValueError(
                        f"metric {name} already registered as {cur.kind}")
                return cur
            inst = cls(name, help_text, **kwargs)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help_text: str) -> Counter:
        return self.get_or_create(Counter, name, help_text)

    def gauge(self, name: str, help_text: str) -> Gauge:
        return self.get_or_create(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self.get_or_create(Histogram, name, help_text,
                                  buckets=buckets)

    def render(self, extra: Iterable[str] = ()) -> str:
        """The full exposition document (trailing newline included, as the
        format requires). `extra` lines (already formatted) append at the
        end — e.g. the monitoring-snapshot gauges."""
        with self._lock:
            insts = [self._instruments[k]
                     for k in sorted(self._instruments)]
        out: List[str] = []
        for inst in insts:
            out.append(f"# HELP {inst.name} {inst.help}")
            out.append(f"# TYPE {inst.name} {inst.kind}")
            out.extend(inst.render())
        out.extend(extra)
        return "\n".join(out) + "\n"


REGISTRY = Registry()


def percentile_from_counts(buckets: Sequence[float], counts: Sequence[int],
                           n: int, q: float) -> Optional[float]:
    """Nearest-bucket-upper-bound percentile from a (possibly differenced)
    histogram window: the smallest bucket bound whose cumulative count
    covers rank q. `n` may exceed sum(counts) — observations above the
    last finite bucket live only in the total — in which case a rank
    falling into that overflow returns +inf (honestly 'worse than every
    bound', which is exactly what an overload watermark wants to see).
    Returns None for an empty window."""
    if n <= 0:
        return None
    rank = q / 100.0 * n
    cum = 0
    for bound, c in zip(buckets, counts):
        cum += c
        if cum >= rank:
            return float(bound)
    return float("inf")


_EXEMPLAR_RE = None


def parse_exemplars(text: str, family: str) -> List[dict]:
    """The client side of the `# EXEMPLAR` exposition contract: parse a
    rendered /metrics document back into `{le, trace_id, value}` rows for
    one histogram family — how the benchkit serve recipe lifts the
    p99-bucket -> trace-id links off a live server into its trajectory
    record (value is the observation in the instrument's native unit,
    seconds for latency histograms)."""
    global _EXEMPLAR_RE  # pylint: disable=global-statement
    import re
    if _EXEMPLAR_RE is None:
        _EXEMPLAR_RE = re.compile(
            r'^# EXEMPLAR (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'\{(?P<labels>[^}]*)\} '
            r'\{trace_id="(?P<trace_id>[^"]*)"\} '
            r'(?P<value>[-+0-9.eEinf]+)$')
    out: List[dict] = []
    for line in text.splitlines():
        m = _EXEMPLAR_RE.match(line)
        if m is None or m.group("name") != f"{family}_bucket":
            continue
        le = None
        for pair in m.group("labels").split(","):
            if pair.startswith('le="'):
                le = pair[4:-1]
        if le is None:
            continue
        out.append({"le": le, "trace_id": m.group("trace_id"),
                    "value": float(m.group("value"))})
    return out


def render_monitoring_snapshot(snapshot: dict,
                               prefix: str = "pipeedge_monitor") -> List[str]:
    """Monitoring's `snapshot()` matrix (key -> scope -> metric -> value)
    as gauge lines — the bridge that lets /metrics expose every monitoring
    key without reaching into the per-key getter matrix one call at a time
    (monitoring.snapshot() is the one synchronized read)."""
    lines = []
    names = set()
    rows = []
    for key in sorted(snapshot):
        scopes = snapshot[key]
        for scope in ("instant", "window", "global"):
            for metric, value in sorted(scopes.get(scope, {}).items()):
                name = f"{prefix}_{metric}"
                names.add(name)
                rows.append((name, key, scope, value))
    for name in sorted(names):
        lines.append(f"# HELP {name} monitoring snapshot metric")
        lines.append(f"# TYPE {name} gauge")
        for n, key, scope, value in rows:
            if n == name:
                lines.append(
                    f'{name}{{key="{key}",scope="{scope}"}} '
                    f"{_fmt(float(value))}")
    return lines
