"""Flight recorder: an always-on bounded ring of request-tagged events
that can explain any slow request AFTER the fact.

The span plane (telemetry/__init__.py) answers "why was the fleet slow"
when a `--trace-spans` run was armed ahead of time; production incidents
do not schedule themselves. This module keeps a cheap, always-on,
fixed-size per-rank ring of request-tagged events (admits, sheds,
deadline expiries, brownout transitions, failover lifecycle, feed/ack
progress) and, when something goes wrong, dumps a **postmortem bundle**:
the event ring, a slice of the span ring scoped to the offending request
(when span recording is on), and whatever context the caller attaches
(admission/brownout snapshots, microbatch-ledger state).

Triggers (docs/OBSERVABILITY.md):
- `deadline` — a request expired mid-flight (HTTP 504, tools/serve.py)
- `shed`     — admission refused a request (503; cooldown-limited so a
               shed storm writes one bundle, not thousands)
- `failover` — a degraded window opened / a rank died (runtime.py,
               tools/serve.py POST /degraded)
- `slo`      — the brownout ladder crossed its SLO-breach rung
- `gray`     — the peer-health plane quarantined a gray-failing rank
               (pipeedge_tpu/health/, docs/FAULT_TOLERANCE.md)
- `poison`   — the NaN/Inf activation guard tripped at a stage boundary
               (PIPEEDGE_NAN_GUARD=1, pipeedge_tpu/health/guard.py)
- `manual`   — POST /debug/dump (never cooldown-limited)

Dumps are JSON files under `PIPEEDGE_POSTMORTEM_DIR` (default
`postmortems/`), written atomically (tmp + rename) OUTSIDE the ring lock,
counted on `pipeedge_postmortems_written_total{trigger}` (matrix
pre-declared — pipelint PL501) and surfaced on /healthz (`flight` block:
written total + last bundle path). `tools/trace_report.py --request`
reads a bundle directly: its `spans` slice is the same span-dict shape a
merged trace decodes to.

Module-level surface mirrors the span plane's (`note()` / `maybe_dump()`
route to a lazily-created process singleton), so probes cost one global
read when nothing ever dumps.
"""
from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from ..utils.threads import make_lock
from . import metrics as prom
from . import recorder as span_recorder

logger = logging.getLogger(__name__)

ENV_POSTMORTEM_DIR = "PIPEEDGE_POSTMORTEM_DIR"
DEFAULT_POSTMORTEM_DIR = "postmortems"
DEFAULT_CAPACITY = 4096
DEFAULT_COOLDOWN_S = 5.0

TRIGGERS = ("deadline", "shed", "failover", "slo", "slo_burn", "gray",
            "poison", "manual")

_POSTMORTEMS = prom.REGISTRY.counter(
    "pipeedge_postmortems_written_total",
    "postmortem bundles written by the flight recorder, by trigger")
for _t in TRIGGERS:
    _POSTMORTEMS.declare(trigger=_t)


def rid_tree_member(span_rid: Optional[str], rid: str) -> bool:
    """True when `span_rid` belongs to `rid`'s derivation tree: the rid
    itself or any dot-suffixed descendant (`rid.t2`, `rid.hedge.t1`,
    `rid.fo1`, `rid.replay` — the router/executor derivation grammar,
    docs/OBSERVABILITY.md). One logical request resolves as one tree."""
    if not isinstance(span_rid, str):
        return False
    return span_rid == rid or span_rid.startswith(rid + ".")


def trace_slice(spans: Sequence[dict], rid: Optional[str]) -> List[dict]:
    """The bundle's span slice: every span in `rid`'s derivation tree
    (retry/hedge/failover-replay children included), plus the spans
    sharing a microbatch id with one of them (the wire/ledger hops
    recorded before the trace context reached them). `rid=None` keeps
    the whole list (a fleet-wide postmortem wants everything)."""
    if rid is None:
        return list(spans)
    mine = [s for s in spans if rid_tree_member(s.get("rid"), rid)]
    mbs = {s.get("mb") for s in mine if s.get("mb") is not None}
    out = list(mine)
    if mbs:
        out += [s for s in spans
                if not rid_tree_member(s.get("rid"), rid)
                and s.get("mb") in mbs]
    out.sort(key=lambda s: (int(s.get("t0", 0)), str(s.get("cat", "")),
                            str(s.get("name", ""))))
    return out


class FlightRecorder:
    """Fixed-size drop-oldest ring of `(t_ns, kind, rid, detail)` events.

    `note()` is the hot-path entry: one short lock + one deque append —
    always on, never blocking on I/O (the same discipline as
    SpanRecorder.record). `dump()` snapshots under the lock and writes
    the bundle file OUTSIDE it."""

    def __init__(self, rank: int = 0, capacity: int = DEFAULT_CAPACITY,
                 out_dir: Optional[str] = None,
                 cooldown_s: float = DEFAULT_COOLDOWN_S):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.rank = int(rank)
        self.out_dir = (out_dir if out_dir is not None
                        else os.getenv(ENV_POSTMORTEM_DIR,
                                       DEFAULT_POSTMORTEM_DIR))
        self.cooldown_s = float(cooldown_s)
        self.dropped = 0
        self._ring: deque = deque(maxlen=int(capacity))
        self._lock = make_lock("telemetry.flight")
        self._seq = 0
        self._last_path: Optional[str] = None
        # per-trigger stamp of the last bundle (the cooldown basis) and
        # events suppressed by it since (honesty counter in the bundle)
        self._last_dump: Dict[str, float] = {}
        self._suppressed: Dict[str, int] = {}

    # -- hot path -------------------------------------------------------

    def note(self, kind: str, rid: Optional[str] = None, **detail) -> None:
        """Append one event (request-tagged when `rid` is given). Detail
        values must be JSON-serializable."""
        evt = (time.monotonic_ns(), str(kind),
               None if rid is None else str(rid), detail or None)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(evt)

    # -- introspection --------------------------------------------------

    def events(self, rid: Optional[str] = None) -> List[dict]:
        """Ring snapshot (oldest first), optionally request-filtered."""
        with self._lock:
            rows = list(self._ring)
        out = []
        for t, kind, evt_rid, detail in rows:
            if rid is not None and evt_rid != rid:
                continue
            d = {"t_ns": t, "kind": kind, "rid": evt_rid}
            if detail:
                d.update(detail)
            out.append(d)
        return out

    def last_path(self) -> Optional[str]:
        with self._lock:
            return self._last_path

    def written_total(self) -> int:
        return int(_POSTMORTEMS.total())

    # -- postmortem bundles ---------------------------------------------

    def would_dump(self, trigger: str) -> bool:
        """Whether `maybe_dump(trigger)` would fire right now (cooldown
        check only, no state change). Callers with an EXPENSIVE context
        to assemble gate on this first — a shed storm must not pay a
        snapshot per suppressed dump. Racy by design: losing the race
        just builds one context that gets suppressed."""
        if trigger == "manual":
            return True
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(trigger)
            return last is None or now - last >= self.cooldown_s

    def maybe_dump(self, trigger: str, rid: Optional[str] = None,
                   context: Optional[dict] = None) -> Optional[str]:
        """Dump a bundle unless `trigger` fired within its cooldown
        (manual dumps are never suppressed). Returns the bundle path, or
        None when suppressed. Never raises: a postmortem failing to
        write must not take the serving path down with it."""
        if trigger not in TRIGGERS:
            raise ValueError(f"unknown trigger {trigger!r} "
                             f"(expected one of {TRIGGERS})")
        now = time.monotonic()
        with self._lock:
            last = self._last_dump.get(trigger)
            if trigger != "manual" and last is not None \
                    and now - last < self.cooldown_s:
                self._suppressed[trigger] = \
                    self._suppressed.get(trigger, 0) + 1
                return None
            self._last_dump[trigger] = now
        try:
            return self._dump(trigger, rid, context)
        except Exception:  # noqa: BLE001 — the contract: a postmortem
            # failing to write (disk full, unserializable context value)
            # must never take the serving path down with it; dumps run
            # inside 504/shed handlers
            logger.warning("flight recorder: postmortem dump failed",
                           exc_info=True)
            return None

    def _dump(self, trigger: str, rid: Optional[str],
              context: Optional[dict]) -> str:
        with self._lock:
            rows = list(self._ring)
            seq = self._seq
            self._seq += 1
            suppressed = dict(self._suppressed)
        rec = span_recorder()
        spans = trace_slice(rec.snapshot(), rid) if rec is not None else []
        events = []
        for t, kind, evt_rid, detail in rows:
            d = {"t_ns": t, "kind": kind, "rid": evt_rid}
            if detail:
                d.update(detail)
            events.append(d)
        bundle = {
            "bundle": "pipeedge-postmortem",
            "trigger": trigger,
            "rid": rid,
            "rank": self.rank,
            "seq": seq,
            "t_mono_ns": time.monotonic_ns(),
            "events": events,
            "events_dropped": self.dropped,
            "suppressed_dumps": suppressed,
            "spans": spans,
            "context": context or {},
        }
        os.makedirs(self.out_dir, exist_ok=True)
        name = f"postmortem-r{self.rank}-{seq:04d}-{trigger}.json"
        path = os.path.join(self.out_dir, name)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf8") as f:
            # default=str: an odd value in an event detail or context
            # (numpy scalar, exception object) degrades to its repr
            # instead of losing the whole bundle
            json.dump(bundle, f, separators=(",", ":"), sort_keys=True,
                      default=str)
        os.replace(tmp, path)
        _POSTMORTEMS.inc(trigger=trigger)
        with self._lock:
            self._last_path = path
        return path


_recorder: Optional[FlightRecorder] = None
_recorder_lock = make_lock("telemetry.flight_singleton")


def configure(rank: int = 0, capacity: int = DEFAULT_CAPACITY,
              out_dir: Optional[str] = None,
              cooldown_s: float = DEFAULT_COOLDOWN_S) -> FlightRecorder:
    """(Re)build the process singleton with explicit settings — what
    tools/serve.py's --postmortem-dir and runtime.py's per-rank setup
    call. Probes that ran before configure() keep their events only in
    the replaced recorder (fresh ring, same instrumentation)."""
    global _recorder  # pylint: disable=global-statement
    with _recorder_lock:
        _recorder = FlightRecorder(rank=rank, capacity=capacity,
                                   out_dir=out_dir, cooldown_s=cooldown_s)
        return _recorder


def recorder() -> FlightRecorder:
    """The process singleton (lazily created — the recorder is ALWAYS on;
    only dumps are conditional)."""
    global _recorder  # pylint: disable=global-statement
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
            rec = _recorder
    return rec


def note(kind: str, rid: Optional[str] = None, **detail) -> None:
    recorder().note(kind, rid=rid, **detail)


def maybe_dump(trigger: str, rid: Optional[str] = None,
               context: Optional[dict] = None) -> Optional[str]:
    return recorder().maybe_dump(trigger, rid=rid, context=context)
