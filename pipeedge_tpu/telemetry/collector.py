"""Fleet collector + SLO burn-rate engine (docs/OBSERVABILITY.md).

PR 17 made serving a routed fleet; this module makes the fleet
observable as ONE system:

- **FleetCollector**: the router-side scraper. Periodically pulls
  `/metrics` from every registered replica (and any prefill workers
  they report) into a bounded in-memory time-series ring, and
  aggregates the rings into the `GET /fleet` body: per-class goodput /
  shed / queue-depth, per-replica health with windowed deltas, and the
  union of latency exemplars re-rendered through the existing
  `parse_exemplars` exposition contract (`round-trip: parse_exemplars(
  fleet["exemplars_text"], family)` yields the same rows). Scraping is
  plain text-format parsing — the collector deliberately consumes the
  same surface any external Prometheus would, so it cannot grow a
  private side channel.
- **BurnRateEngine**: multi-window error-budget burn rates from the
  per-class outcome counters (the SRE multiwindow/multi-burn-rate
  discipline, scaled to serving windows). burn = (bad fraction over
  the window) / (1 - objective); 1.0 means the error budget is being
  consumed exactly at the sustainable rate. Exported as the
  pre-declared `pipeedge_slo_burn_rate{class,window}` gauge matrix
  (PL501) and edge-triggered into the flight recorder's `slo_burn`
  postmortem trigger when the fast window breaches — ROADMAP item 4's
  price signal.
- **debug_spans_payload / parse_prom_text**: the per-process
  `GET /debug/spans` ring-drain body (span rows + a peer monotonic
  stamp for the clock-offset estimator) and the minimal Prometheus
  text parser the scrape path rides.

Everything is injectable (fetch_fn, targets_fn, now=) so the whole
plane unit-tests without sockets; tools/serve.py wires the real HTTP.
"""
from __future__ import annotations

import os
import re
import threading
import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import recorder as _recorder_fn
from . import metrics as prom
from ..utils.threads import make_lock

REQUEST_CLASSES = ("interactive", "batch", "best_effort")
BURN_WINDOWS = ("short", "long")

# the families /fleet aggregates, by their exposition names
CLASS_FAMILY = "pipeedge_requests_by_class_total"
LATENCY_FAMILY = "pipeedge_serve_request_latency_seconds"
QUEUE_FAMILY = "pipeedge_admission_queue_depth"
BROWNOUT_FAMILY = "pipeedge_brownout_level"

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})? '
    r'(?P<value>[-+0-9.eEinfa]+)$')
_LABEL_RE = re.compile(r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>[^"]*)"')


def parse_prom_text(text: str,
                    families: Optional[Sequence[str]] = None
                    ) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Prometheus text format 0.0.4 -> {family: [(labels, value)]}.
    Histogram child series (`_bucket`/`_sum`/`_count`) key under their
    child name; `families` (when given) filters to names of interest.
    Unparseable lines are skipped — a scrape must never throw on one
    odd line."""
    want = set(families) if families is not None else None
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        if want is not None and name not in want:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {lm.group("k"): lm.group("v")
                  for lm in _LABEL_RE.finditer(m.group("labels") or "")}
        out.setdefault(name, []).append((labels, value))
    return out


def render_exemplar_lines(family: str,
                          rows: Sequence[dict]) -> List[str]:
    """`{le, trace_id, value}` rows -> `# EXEMPLAR` exposition lines in
    the exact shape `metrics.parse_exemplars` parses back — the /fleet
    union keeps the contract the per-replica /metrics established."""
    lines = []
    for row in rows:
        lines.append(
            f'# EXEMPLAR {family}_bucket{{le="{row["le"]}"}} '
            f'{{trace_id="{row["trace_id"]}"}} '
            f'{prom._fmt(float(row["value"]))}')
    return lines


def debug_spans_payload(drain: bool = True) -> dict:
    """The per-process GET /debug/spans body: span rows (drained from
    the ring by default — a federating trace_report wants each span
    exactly once), plus monotonic stamps bracketing the read so the
    caller can feed `estimate_clock_offset` one (t0, t1, t2, t3)
    quadruple per fetch."""
    t_in = time.monotonic_ns()
    rec = _recorder_fn()
    if rec is None:
        spans: List[dict] = []
        rank = 0
        dropped = 0
    else:
        spans = rec.drain() if drain else rec.snapshot()
        rank = rec.rank
        dropped = rec.dropped
    return {"pid": os.getpid(), "rank": rank, "enabled": rec is not None,
            "dropped": dropped, "drained": bool(drain),
            "t_recv_ns": t_in, "t_send_ns": time.monotonic_ns(),
            "spans": spans}


class BurnRateEngine:
    """Error-budget burn rates over a short (fast, paging) and a long
    (slow, confirmation) window, per request class.

    `update()` takes CUMULATIVE per-class (good, total) counts; the
    engine keeps a bounded sample ring and differences against the
    sample closest to each window's start. Gauges are pre-declared for
    the full class x window matrix (PL501). `on_breach(cls, burn)`
    fires EDGE-TRIGGERED when a class's fast-window burn first exceeds
    `threshold` (re-arming once it recovers) — one postmortem bundle
    per overload episode, not one per tick."""

    def __init__(self, objective: float = 0.99,
                 fast_window_s: float = 30.0,
                 slow_window_s: float = 300.0,
                 threshold: float = 10.0,
                 classes: Sequence[str] = REQUEST_CLASSES,
                 registry: Optional[prom.Registry] = None,
                 on_breach: Optional[Callable[[str, float], None]] = None):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1): {objective}")
        self.objective = float(objective)
        self.budget = 1.0 - self.objective
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.threshold = float(threshold)
        self.classes = tuple(classes)
        self.on_breach = on_breach
        self._lock = make_lock("telemetry.burn")
        # (t, {cls: (good, total)}) oldest-first; bounded by slow window
        self._samples: deque = deque()
        self._breached: set = set()
        reg = registry if registry is not None else prom.REGISTRY
        self.gauge = reg.gauge(
            "pipeedge_slo_burn_rate",
            "error-budget burn rate by request class and window "
            "(1.0 = consuming budget exactly at the sustainable rate; "
            "fast-window breach > threshold triggers an slo_burn "
            "postmortem bundle)")
        for cls in self.classes:
            for window in BURN_WINDOWS:
                # zeroing IS the declaration for a gauge: the full
                # class x window matrix renders from the first scrape
                self.gauge.set(0.0, **{"class": cls, "window": window})

    @staticmethod
    def counts_from_families(
            families: Dict[str, List[Tuple[Dict[str, str], float]]],
            classes: Sequence[str] = REQUEST_CLASSES
    ) -> Dict[str, Tuple[float, float]]:
        """Parsed /metrics families -> {cls: (good, total)} cumulative,
        from the per-class outcome counter (outcome == ok is good)."""
        out = {cls: [0.0, 0.0] for cls in classes}
        for labels, value in families.get(CLASS_FAMILY, ()):
            cls = labels.get("class")
            if cls not in out:
                continue
            out[cls][1] += value
            if labels.get("outcome") == "ok":
                out[cls][0] += value
        return {cls: (g, t) for cls, (g, t) in out.items()}

    @staticmethod
    def counts_from_counter(counter,
                            classes: Sequence[str] = REQUEST_CLASSES
                            ) -> Dict[str, Tuple[float, float]]:
        """A live {class, outcome} Counter instrument (the replica-local
        path — no scrape hop) -> {cls: (good, total)} cumulative."""
        out = {cls: [0.0, 0.0] for cls in classes}
        for key, value in counter.values().items():
            labels = dict(key)
            cls = labels.get("class")
            if cls not in out:
                continue
            out[cls][1] += value
            if labels.get("outcome") == "ok":
                out[cls][0] += value
        return {cls: (g, t) for cls, (g, t) in out.items()}

    def _baseline(self, now: float, window_s: float) -> Optional[tuple]:
        """Newest sample at or before the window start (falling back to
        the oldest sample when history is shorter than the window)."""
        base = None
        for t, counts in self._samples:
            if t <= now - window_s:
                base = (t, counts)
            else:
                break
        if base is None and self._samples:
            base = self._samples[0]
        return base

    def update(self, counts: Dict[str, Tuple[float, float]],
               now: Optional[float] = None) -> Dict[str, Dict[str, float]]:
        """Feed one cumulative sample; returns {cls: {window: burn}}
        and updates the gauge matrix. Fires `on_breach` outside the
        lock for classes newly over threshold on the fast window."""
        now = time.monotonic() if now is None else float(now)
        fired: List[Tuple[str, float]] = []
        burns: Dict[str, Dict[str, float]] = {}
        with self._lock:
            self._samples.append((now, dict(counts)))
            # keep one sample older than the slow window as its baseline
            while len(self._samples) >= 2 \
                    and self._samples[1][0] <= now - self.slow_window_s:
                self._samples.popleft()
            for window, window_s in (("short", self.fast_window_s),
                                     ("long", self.slow_window_s)):
                base = self._baseline(now, window_s)
                for cls in self.classes:
                    good, total = counts.get(cls, (0.0, 0.0))
                    bg, bt = (base[1].get(cls, (0.0, 0.0))
                              if base else (0.0, 0.0))
                    d_total = total - bt
                    d_bad = d_total - (good - bg)
                    burn = ((d_bad / d_total) / self.budget
                            if d_total > 0 else 0.0)
                    burns.setdefault(cls, {})[window] = burn
            over = {cls for cls in self.classes
                    if burns[cls]["short"] > self.threshold}
            fired = [(cls, burns[cls]["short"])
                     for cls in sorted(over - self._breached)]
            self._breached = over
        for cls, per_window in burns.items():
            for window, burn in per_window.items():
                self.gauge.set(round(burn, 6),
                               **{"class": cls, "window": window})
        if self.on_breach is not None:
            for cls, burn in fired:
                self.on_breach(cls, burn)
        return burns


def http_fetch_text(url: str, timeout: float) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


class FleetCollector:
    """The router's scrape loop + aggregation surface.

    `targets_fn()` returns the CURRENT {name: base_url} scrape set
    (replicas come and go — membership is re-read every tick), and
    each target's parsed /metrics lands in a per-target bounded ring
    (`history` samples). `fleet_snapshot()` is the GET /fleet body."""

    def __init__(self, targets_fn: Callable[[], Dict[str, str]],
                 interval_s: float = 1.0,
                 history: int = 120,
                 timeout_s: float = 2.0,
                 fetch_fn: Optional[Callable[[str, float], str]] = None,
                 burn: Optional[BurnRateEngine] = None,
                 classes: Sequence[str] = REQUEST_CLASSES):
        self.targets_fn = targets_fn
        self.interval_s = float(interval_s)
        self.history = int(history)
        self.timeout_s = float(timeout_s)
        self.fetch = fetch_fn or http_fetch_text
        self.burn = burn
        self.classes = tuple(classes)
        self._lock = make_lock("telemetry.collector")
        self._rings: Dict[str, deque] = {}
        self._urls: Dict[str, str] = {}
        self._scrapes = 0
        self._errors = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.m_scrapes = prom.REGISTRY.counter(
            "pipeedge_fleet_scrapes_total",
            "fleet collector scrape attempts, by result")
        for res in ("ok", "error"):
            self.m_scrapes.declare(result=res)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="fleet-collector")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:        # noqa: BLE001 — scrape must not die
                self._errors += 1

    # -- scraping ---------------------------------------------------------

    def scrape_once(self, now: Optional[float] = None) -> int:
        """Scrape every current target once; returns how many answered."""
        now = time.monotonic() if now is None else float(now)
        targets = dict(self.targets_fn())
        ok = 0
        for name, url in targets.items():
            sample = {"t": now, "ok": False, "families": {},
                      "exemplars": []}
            try:
                text = self.fetch(f"{url}/metrics", self.timeout_s)
                sample["families"] = parse_prom_text(
                    text, families=(CLASS_FAMILY, QUEUE_FAMILY,
                                    BROWNOUT_FAMILY))
                sample["exemplars"] = prom.parse_exemplars(
                    text, LATENCY_FAMILY)
                sample["ok"] = True
                ok += 1
                self.m_scrapes.inc(result="ok")
            except (OSError, ValueError):
                self._errors += 1
                self.m_scrapes.inc(result="error")
            with self._lock:
                ring = self._rings.get(name)
                if ring is None:
                    ring = deque(maxlen=self.history)
                    self._rings[name] = ring
                ring.append(sample)
                self._urls[name] = url
                self._scrapes += 1
        if self.burn is not None:
            self.burn.update(self._fleet_counts(), now=now)
        return ok

    def _fleet_counts(self) -> Dict[str, Tuple[float, float]]:
        """Latest cumulative per-class (good, total) summed across all
        targets' most recent good sample."""
        totals = {cls: [0.0, 0.0] for cls in self.classes}
        with self._lock:
            rings = {n: list(r) for n, r in self._rings.items()}
        for samples in rings.values():
            latest = next((s for s in reversed(samples) if s["ok"]), None)
            if latest is None:
                continue
            counts = BurnRateEngine.counts_from_families(
                latest["families"], classes=self.classes)
            for cls, (g, t) in counts.items():
                totals[cls][0] += g
                totals[cls][1] += t
        return {cls: (g, t) for cls, (g, t) in totals.items()}

    # -- aggregation ------------------------------------------------------

    def fleet_snapshot(self, now: Optional[float] = None) -> dict:
        """The GET /fleet body: per-class fleet aggregates, per-replica
        health + windowed deltas, the exemplar union (round-trippable
        through `parse_exemplars`), and the burn-rate matrix."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            rings = {n: list(r) for n, r in self._rings.items()}
            urls = dict(self._urls)
            scrapes, errors = self._scrapes, self._errors
        classes = {cls: {"goodput_rps": 0.0, "shed_rps": 0.0,
                         "requests_total": 0.0, "ok_total": 0.0,
                         "window_attainment": None}
                   for cls in self.classes}
        replicas = {}
        exemplar_union: Dict[str, dict] = {}
        queue_depth = 0.0
        brownout_level = 0
        cls_window: Dict[str, List[float]] = {
            cls: [0.0, 0.0, 0.0] for cls in self.classes}  # dok, dtot, dshed
        for name, samples in rings.items():
            latest = next((s for s in reversed(samples) if s["ok"]), None)
            rec = {"url": urls.get(name),
                   "ok": bool(samples and samples[-1]["ok"]),
                   "samples": len(samples),
                   "age_s": (round(now - samples[-1]["t"], 3)
                             if samples else None)}
            if latest is None:
                rec["requests"] = {}
                replicas[name] = rec
                continue
            counts = BurnRateEngine.counts_from_families(
                latest["families"], classes=self.classes)
            for cls, (g, t) in counts.items():
                classes[cls]["ok_total"] += g
                classes[cls]["requests_total"] += t
            for labels, value in latest["families"].get(QUEUE_FAMILY, ()):
                queue_depth += value
            # the fleet's brownout rung is the MAX across targets: one
            # replica shedding work is enough to order autoscale
            # scale-down behind brownout (serving/autoscale.py)
            for labels, value in latest["families"].get(
                    BROWNOUT_FAMILY, ()):
                brownout_level = max(brownout_level, int(value))
            # windowed deltas: latest good sample vs the oldest good one
            oldest = next((s for s in samples if s["ok"]), None)
            window_s = max(1e-9, latest["t"] - oldest["t"]) \
                if oldest is not latest else None
            rec["requests"] = {cls: round(t, 1)
                               for cls, (_, t) in counts.items()}
            if window_s is not None:
                base = BurnRateEngine.counts_from_families(
                    oldest["families"], classes=self.classes)
                goodput = {}
                for cls in self.classes:
                    dg = counts[cls][0] - base[cls][0]
                    dt = counts[cls][1] - base[cls][1]
                    goodput[cls] = round(dg / window_s, 3)
                    w = cls_window[cls]
                    w[0] += dg
                    w[1] += dt
                    w[2] += (dt - dg)
                    classes[cls]["goodput_rps"] += dg / window_s
                    classes[cls]["shed_rps"] += (dt - dg) / window_s
                rec["window_s"] = round(window_s, 3)
                rec["goodput_rps"] = goodput
            for row in latest["exemplars"]:
                cur = exemplar_union.get(row["le"])
                if cur is None or row["value"] > cur["value"]:
                    exemplar_union[row["le"]] = dict(row)
            replicas[name] = rec
        for cls in self.classes:
            dok, dtot, _ = cls_window[cls]
            classes[cls]["window_attainment"] = \
                round(dok / dtot, 4) if dtot > 0 else None
            classes[cls]["goodput_rps"] = round(
                classes[cls]["goodput_rps"], 3)
            classes[cls]["shed_rps"] = round(classes[cls]["shed_rps"], 3)
        union_rows = [exemplar_union[le]
                      for le in sorted(exemplar_union,
                                       key=lambda s: float(
                                           s.replace("+Inf", "inf")))]
        out = {
            "interval_s": self.interval_s,
            "history": self.history,
            "scrapes": scrapes,
            "scrape_errors": errors,
            "targets": urls,
            "replicas": replicas,
            "classes": classes,
            "queue_depth": queue_depth,
            "brownout_level": brownout_level,
            "latency_family": LATENCY_FAMILY,
            "exemplars": union_rows,
            "exemplars_text": "\n".join(render_exemplar_lines(
                LATENCY_FAMILY, union_rows)),
        }
        if self.burn is not None:
            out["slo"] = {
                "objective": self.burn.objective,
                "threshold": self.burn.threshold,
                "windows_s": {"short": self.burn.fast_window_s,
                              "long": self.burn.slow_window_s},
                "burn_rate": self.burn.update(self._fleet_counts(),
                                              now=now),
            }
        return out
