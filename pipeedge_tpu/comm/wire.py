"""Host-side quantized wire codec for DCN transports.

Two generations of the format coexist (the receiver distinguishes them by
the header tensor alone, so a fleet never needs version coordination):

v1 — host-encoded: a scalar int32 bitwidth header, then per payload tensor
either the raw array (bit=0) or a [packed_uint32, scale, shift, shape]
quadruple. The bitwidth travels ON the wire — the reference ships it as the
5th element of every encoded tensor
(/root/reference/src/pipeedge/quantization/basic_op.py:143) — so the
consumer can decode even when the producer's adaptive policy changes the
bitwidth mid-run. Packing runs in the native C++ codec when built
(host-side, off the accelerator; bit-identical to the XLA ops —
ops/native_quant.py), else via the XLA ops.

v2 — device-encoded (the overlapped int8 wire path): the header is a 1-D
int32 vector [WIRE_V2_MAGIC, version, bit, flags, n_payload] followed by
the same per-tensor [packed, scale, shift, shape] quads (raw arrays when
bit=0). The difference is WHERE the work happens: `wire_encode_device`
quantizes inside XLA on the producing device (ops/quant.py, so the pack
fuses with the stage's last matmuls) and starts an ASYNC device->host copy
of only the packed words + scale/shift — at int8 a 4x smaller D2H readback
than the raw fp32 activations v1 pulls back before encoding. The returned
`PendingWire` completes the copies on `finalize()`, letting the caller
dispatch the next microbatch's compute while this one's readback drains
(comm/dcn.py's dispatch/readback stage split). `wire_decode` dequantizes
v2 frames back ON the receiving device (jitted decode) instead of through
the host codec. Packing layout and math are bit-identical across v1/v2/
native (ops/native_quant.py contract), so any producer pairs with any
consumer.

Consumers: the DCN runtime driver (runtime.py) and the DCN decode mode
(tools/generate.py --edge-bits).
"""
from __future__ import annotations

import os
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry

# v2 header magic: v1's header is a 0-d int32 whose value is a bitwidth
# (>= 0), so a 1-D header opening with a negative sentinel is unambiguous.
WIRE_V2_MAGIC = -2
WIRE_V2_VERSION = 2
_V2_HEADER_LEN = 5
# flags bit 0: payload was encoded on-device (XLA ops); informational —
# the packing layout is identical either way.
FLAG_ON_DEVICE = 1
# flags bit 1: the frame's tensor list ends with a [algo, crc] uint32
# checksum over every body tensor's bytes (frame integrity,
# docs/FAULT_TOLERANCE.md gray failures). Decoders without the bit see a
# plain v2 frame — old frames still decode, new frames degrade to
# unchecked on old decoders (the flag is advisory, like FLAG_ON_DEVICE).
FLAG_CRC = 2

ENV_WIRE_CRC = "PIPEEDGE_WIRE_CRC"   # 1 = checksum every v2 frame

# Checksum algorithm ids (travel IN the checksum tensor, so a fleet with
# mixed wheels still verifies): CRC32C (Castagnoli) when a native wheel
# is importable — the satellite's named algorithm — else zlib's CRC32
# (ISO-HDLC), which is always available at C speed. A verifier that
# lacks the frame's algorithm skips verification rather than raising a
# false corruption.
CRC_ALGO_CRC32C = 0
CRC_ALGO_CRC32 = 1
try:                               # pragma: no cover - env-dependent
    import crc32c as _crc32c_mod   # type: ignore
except ImportError:
    _crc32c_mod = None


def crc_enabled() -> bool:
    """Whether v2 frames should carry an integrity checksum (env
    PIPEEDGE_WIRE_CRC; runtime --wire-crc sets it for the process)."""
    return os.getenv(ENV_WIRE_CRC, "0") == "1"


class WireCorruptError(ValueError):
    """A v2 frame's checksum did not match its payload bytes — the frame
    was corrupted in flight. Consumers recover by requesting one bounded
    resend over the control channel (comm/dcn.py `request_resend`)."""

    def __init__(self, expected: int, got: int):
        self.expected = int(expected)
        self.got = int(got)
        super().__init__(
            f"wire frame failed integrity check (checksum "
            f"{got:#010x} != expected {expected:#010x})")


def _checksum_fn(algo: int):
    if algo == CRC_ALGO_CRC32C and _crc32c_mod is not None:
        return _crc32c_mod.crc32c
    if algo == CRC_ALGO_CRC32:
        return zlib.crc32
    return None


def frame_checksum(tensors: Sequence,
                   algo: Optional[int] = None) -> Tuple[int, int]:
    """`(algo, crc)` over every tensor's raw bytes, in list order. The
    default algorithm is CRC32C when the native wheel is present, zlib
    CRC32 otherwise; the id rides the frame so the verifier always knows
    what to recompute."""
    if algo is None:
        algo = (CRC_ALGO_CRC32C if _crc32c_mod is not None
                else CRC_ALGO_CRC32)
    fn = _checksum_fn(algo)
    if fn is None:
        raise ValueError(f"checksum algorithm {algo} unavailable")
    crc = 0
    for t in tensors:
        a = np.ascontiguousarray(np.asarray(t))
        crc = fn(a.reshape(-1).view(np.uint8).data, crc)
    return algo, crc & 0xFFFFFFFF


def locate_crc_header(tensors: Sequence, scan: int = 3) -> Optional[int]:
    """Index of the CRC-flagged v2 header within a frame's tensor list,
    or None. The header may not be first: failover frames prepend the
    microbatch-id tensor (which the checksum deliberately excludes — it
    is host-attached after `finalize()`). What the transport reader uses
    to verify frames in flight (comm/dcn.py)."""
    for i, t in enumerate(tensors[:scan]):
        a = np.asarray(t)
        if _is_v2_header(a) and int(a[3]) & FLAG_CRC:
            return i
    return None


def verify_frame(body: Sequence, crc_tensor) -> Sequence:
    """Verify a v2 frame's trailing `[algo, crc]` tensor against `body`
    (the tensor list between header and checksum); returns `body`.
    Raises `WireCorruptError` on mismatch. An unknown algorithm (a newer
    producer) degrades to unverified — never a false corruption."""
    vals = np.asarray(crc_tensor, np.uint32).reshape(-1)
    algo, expected = int(vals[0]), int(vals[1])
    if _checksum_fn(algo) is None:  # pragma: no cover - future algos
        return body
    _, got = frame_checksum(body, algo=algo)
    if got != expected:
        raise WireCorruptError(expected, got)
    return body


def native_wire_codec(bit: int):
    """The native host-side codec when usable for this bitwidth, else None.
    PIPEEDGE_NATIVE_QUANT=0 disables it."""
    if bit == 0 or bit > 16 or os.getenv("PIPEEDGE_NATIVE_QUANT", "1") != "1":
        return None
    from ..ops import native_quant
    return native_quant if native_quant.available() else None


def wire_encode(out, bit: int) -> List[np.ndarray]:
    """Stage output (tensor or tuple) -> v1 wire tensor list (host encode)."""
    with telemetry.span("quant", f"encode{bit}"):
        return _wire_encode_timed(out, bit)


def _wire_encode_timed(out, bit: int) -> List[np.ndarray]:
    import jax.numpy as jnp

    from ..ops import quant as quant_ops
    tensors = out if isinstance(out, tuple) else (out,)
    wire = [np.asarray(bit, np.int32)]
    if bit == 0:
        return wire + [np.asarray(t) for t in tensors]
    native = native_wire_codec(bit)
    for t in tensors:
        if native is not None:
            arr = np.asarray(t, np.float32)
            packed, scale, shift = native.encode_outerdim(arr, bit)
            wire += [packed, scale, shift, np.asarray(arr.shape, np.int64)]
        else:
            enc = quant_ops.tensor_encode_outerdim(jnp.asarray(t), bit)
            wire += [np.asarray(enc.data), np.asarray(enc.scale),
                     np.asarray(enc.shift), np.asarray(enc.shape, np.int64)]
    return wire


class PendingWire:
    """A v2 wire frame whose device->host copies are still in flight.

    `parts` mixes host arrays (header, shapes) and device arrays (packed
    payload, scale, shift) whose `copy_to_host_async()` has been kicked
    off. `finalize()` materializes everything as numpy (blocking only on
    the already-started copies) — call it on the readback thread, after
    dispatching the NEXT microbatch's compute.

    With `crc=True` the finalized frame gains the integrity trailer: the
    header copy's FLAG_CRC bit is set and a `[algo, crc]` uint32 tensor
    over every body tensor's bytes is appended. The flag lives on the
    FINALIZED frame only — a colocated (local-tier) hand-off ships
    `parts` as-is, device buffers and all, and an in-process reference
    hand-off has no wire to corrupt (and no host bytes to checksum)."""

    __slots__ = ("parts", "crc")

    def __init__(self, parts: List, crc: bool = False):
        self.parts = parts
        self.crc = bool(crc)

    def finalize(self) -> List[np.ndarray]:
        out = [np.asarray(p) for p in self.parts]
        if self.crc:
            header = out[0].copy()
            header[3] |= FLAG_CRC
            out[0] = header
            algo, crc = frame_checksum(out[1:])
            out.append(np.asarray([algo, crc], np.uint32))
        return out


def _start_host_copy(arr) -> None:
    copy = getattr(arr, "copy_to_host_async", None)
    if copy is not None:
        try:
            copy()
        except (RuntimeError, NotImplementedError):  # backend quirk: the
            pass  # later np.asarray() still works, just synchronously


def wire_encode_device(out, bit: int,
                       crc: Optional[bool] = None) -> PendingWire:
    """Stage output (tensor or tuple) -> pending v2 wire frame.

    Quantizes ON the producing device (jitted `tensor_encode_outerdim`,
    cached per bitwidth) and starts the async readback of only the wire
    payload — packed words + per-item scale/shift at bit>0, the raw
    arrays at bit=0. Never blocks (so the telemetry span covers host
    dispatch only; the device time lands in the readback span).

    `crc` arms the integrity trailer (default: env PIPEEDGE_WIRE_CRC);
    the checksum itself is computed at `finalize()`, when host bytes
    exist — local-tier hand-offs never pay (or carry) it."""
    with telemetry.span("quant", f"encode_device{bit}"):
        return _wire_encode_device_timed(
            out, bit, crc_enabled() if crc is None else bool(crc))


def _wire_encode_device_timed(out, bit: int, crc: bool) -> PendingWire:
    import jax.numpy as jnp

    from ..ops import fused_quant
    tensors = out if isinstance(out, tuple) else (out,)
    header = np.asarray([WIRE_V2_MAGIC, WIRE_V2_VERSION, bit, FLAG_ON_DEVICE,
                         len(tensors)], np.int32)
    parts: List = [header]
    if bit == 0:
        for t in tensors:
            t = jnp.asarray(t)
            _start_host_copy(t)
            parts.append(t)
        return PendingWire(parts, crc=crc)
    for t in tensors:
        # fused Pallas encode when enabled (ops/fused_quant.py) — the
        # packing layout is bit-identical to the XLA/native codecs, so
        # any consumer generation still decodes this frame
        enc = fused_quant.encode_outerdim(jnp.asarray(t), bit)
        for a in (enc.data, enc.scale, enc.shift):
            _start_host_copy(a)
        parts += [enc.data, enc.scale, enc.shift,
                  np.asarray(enc.shape, np.int64)]
    return PendingWire(parts, crc=crc)


def _is_v2_header(header: np.ndarray) -> bool:
    return (header.ndim == 1 and header.size >= _V2_HEADER_LEN
            and header.dtype.kind == 'i' and int(header[0]) == WIRE_V2_MAGIC)


def _wire_decode_v2(header, tensors, dtype):
    """Decode a v2 body ON the receiving device (jitted dequantize; the
    fused-dequant prologue when enabled)."""
    import jax.numpy as jnp

    from ..ops import fused_quant
    from ..ops import quant as quant_ops
    bit = int(header[2])
    n_payload = int(header[4])
    if bit == 0:
        if len(tensors) != n_payload:
            raise ValueError(
                f"malformed v2 wire frame: {len(tensors)} tensors after the "
                f"header (expected {n_payload} raw payloads)")
        out = tuple(jnp.asarray(t) for t in tensors)
    else:
        if len(tensors) != 4 * n_payload:
            raise ValueError(
                f"malformed v2 wire frame: {len(tensors)} tensors after the "
                f"header (expected {4 * n_payload}: packed/scale/shift/shape "
                f"per payload)")
        out = []
        for i in range(0, len(tensors), 4):
            data, scale, shift, shape = tensors[i:i + 4]
            enc = quant_ops.QuantizedTensor(
                data=jnp.asarray(data), scale=jnp.asarray(scale),
                shift=jnp.asarray(shift),
                shape=tuple(int(s) for s in shape), bit=bit)
            out.append(fused_quant.decode_outerdim(enc).astype(dtype))
        out = tuple(out)
    return out[0] if len(out) == 1 else out


def wire_decode(tensors: List[np.ndarray], dtype):
    """Inverse of `wire_encode`/`wire_encode_device` (version and bitwidth
    read from the wire header); returns the stage payload (tensor/tuple).
    v2 frames dequantize on the receiving device; v1 frames through the
    native host codec when available. A v2 frame carrying the FLAG_CRC
    trailer is verified FIRST — a corrupted frame raises
    `WireCorruptError` before any garbage reaches a device."""
    with telemetry.span("quant", "decode"):
        return _wire_decode_timed(tensors, dtype)


def _wire_decode_timed(tensors: List[np.ndarray], dtype):
    import jax.numpy as jnp

    from ..ops import quant as quant_ops
    header = np.asarray(tensors[0])
    if _is_v2_header(header):
        body = tensors[1:]
        if int(header[3]) & FLAG_CRC:
            if not body:
                raise ValueError("malformed v2 wire frame: FLAG_CRC set "
                                 "but no checksum tensor")
            body = verify_frame(body[:-1], body[-1])
        return _wire_decode_v2(header, body, dtype)
    bit = int(header)
    tensors = tensors[1:]
    if bit == 0:
        out = tuple(jnp.asarray(t) for t in tensors)
    else:
        if len(tensors) % 4:
            raise ValueError(
                f"malformed quantized wire frame: {len(tensors)} tensors "
                "after the bitwidth header (expected a multiple of 4: "
                "packed/scale/shift/shape per payload)")
        native = native_wire_codec(bit)
        out = []
        for i in range(0, len(tensors), 4):
            data, scale, shift, shape = tensors[i:i + 4]
            if native is not None:
                dec = native.decode_outerdim(data, scale, shift,
                                             tuple(int(s) for s in shape),
                                             bit)
                out.append(jnp.asarray(dec, dtype=dtype))
            else:
                enc = quant_ops.QuantizedTensor(
                    data=jnp.asarray(data), scale=jnp.asarray(scale),
                    shift=jnp.asarray(shift),
                    shape=tuple(int(s) for s in shape), bit=bit)
                out.append(quant_ops.tensor_decode_outerdim(enc).astype(dtype))
        out = tuple(out)
    return out[0] if len(out) == 1 else out


# -- wire byte accounting (the bench/test counters) ---------------------

def frame_wire_bytes(tensors: Sequence) -> int:
    """Total bytes of a wire frame's tensor list — everything that rides
    the socket payload sections (header tensor, packed data, scale/shift,
    shapes). Matches what the transport recv/send monitor hooks sum."""
    return sum(int(t.nbytes) for t in tensors)


def frame_payload_bytes(tensors: Sequence) -> int:
    """Activation-payload bytes of a wire frame: the bytes that REPLACE the
    raw activations (packed words at bit>0, the raw arrays at bit=0),
    excluding the fixed metadata (header, scale/shift, shape vectors).

    This is the apples-to-apples compression counter: fp32 payload bytes /
    int8 payload bytes == 32/bit exactly (metadata is O(batch) and reported
    separately via `frame_wire_bytes`)."""
    header = np.asarray(tensors[0])
    body = list(tensors[1:])
    if _is_v2_header(header):
        bit = int(header[2])
        if int(header[3]) & FLAG_CRC and body:
            body = body[:-1]    # the integrity trailer is metadata
    else:
        bit = int(header)
    if bit == 0:
        return sum(int(t.nbytes) for t in body)
    # quantized: quads of [data, scale, shift, shape]
    return sum(int(body[i].nbytes) for i in range(0, len(body), 4))
