"""Host-side quantized wire codec for DCN transports.

Stage payloads cross process boundaries as a tensor list: a scalar int32
bitwidth header, then per payload tensor either the raw array (bit=0) or a
[packed_uint32, scale, shift, shape] quadruple. The bitwidth travels ON the
wire — the reference ships it as the 5th element of every encoded tensor
(/root/reference/src/pipeedge/quantization/basic_op.py:143) — so the
consumer can decode even when the producer's adaptive policy changes the
bitwidth mid-run. Packing runs in the native C++ codec when built
(host-side, off the accelerator; bit-identical to the XLA ops —
ops/native_quant.py), else via the XLA ops.

Consumers: the DCN runtime driver (runtime.py) and the DCN decode mode
(tools/generate.py --edge-bits).
"""
from __future__ import annotations

import os
from typing import List

import numpy as np


def native_wire_codec(bit: int):
    """The native host-side codec when usable for this bitwidth, else None.
    PIPEEDGE_NATIVE_QUANT=0 disables it."""
    if bit == 0 or bit > 16 or os.getenv("PIPEEDGE_NATIVE_QUANT", "1") != "1":
        return None
    from ..ops import native_quant
    return native_quant if native_quant.available() else None


def wire_encode(out, bit: int) -> List[np.ndarray]:
    """Stage output (tensor or tuple) -> wire tensor list."""
    import jax.numpy as jnp

    from ..ops import quant as quant_ops
    tensors = out if isinstance(out, tuple) else (out,)
    wire = [np.asarray(bit, np.int32)]
    if bit == 0:
        return wire + [np.asarray(t) for t in tensors]
    native = native_wire_codec(bit)
    for t in tensors:
        if native is not None:
            arr = np.asarray(t, np.float32)
            packed, scale, shift = native.encode_outerdim(arr, bit)
            wire += [packed, scale, shift, np.asarray(arr.shape, np.int64)]
        else:
            enc = quant_ops.tensor_encode_outerdim(jnp.asarray(t), bit)
            wire += [np.asarray(enc.data), np.asarray(enc.scale),
                     np.asarray(enc.shift), np.asarray(enc.shape, np.int64)]
    return wire


def wire_decode(tensors: List[np.ndarray], dtype):
    """Inverse of `wire_encode` (bitwidth read from the wire header);
    returns the stage payload (tensor/tuple)."""
    import jax.numpy as jnp

    from ..ops import quant as quant_ops
    bit = int(tensors[0])
    tensors = tensors[1:]
    if bit == 0:
        out = tuple(jnp.asarray(t) for t in tensors)
    else:
        if len(tensors) % 4:
            raise ValueError(
                f"malformed quantized wire frame: {len(tensors)} tensors "
                "after the bitwidth header (expected a multiple of 4: "
                "packed/scale/shift/shape per payload)")
        native = native_wire_codec(bit)
        out = []
        for i in range(0, len(tensors), 4):
            data, scale, shift, shape = tensors[i:i + 4]
            if native is not None:
                dec = native.decode_outerdim(data, scale, shift,
                                             tuple(int(s) for s in shape),
                                             bit)
                out.append(jnp.asarray(dec, dtype=dtype))
            else:
                enc = quant_ops.QuantizedTensor(
                    data=jnp.asarray(data), scale=jnp.asarray(scale),
                    shift=jnp.asarray(shift),
                    shape=tuple(int(s) for s in shape), bit=bit)
                out.append(quant_ops.tensor_decode_outerdim(enc).astype(dtype))
        out = tuple(out)
    return out[0] if len(out) == 1 else out
