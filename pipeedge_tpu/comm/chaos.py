"""Deterministic fault injection for the DCN transport (chaos harness).

The fault-tolerance layer (liveness plane, mid-run failover, replay —
docs/FAULT_TOLERANCE.md) is only trustworthy if its failure modes can be
reproduced on demand. This module injects faults at exact, countable
points in a rank's send stream, so a chaos run is bit-for-bit repeatable:
"kill rank 1 at microbatch 3" means the same thing on every run.

Faults are configured per PROCESS through the `DCN_CHAOS` env var — the
launcher (tests, `tools/chaos_dcn.py`) targets a rank by setting the
variable only in that rank's environment. Grammar (`;`-separated actions):

    kill@K          exit the process (os._exit, status 137) immediately
                    before its K-th tensor-frame send (1-based)
    hang@K          SIGSTOP the whole process before its K-th send —
                    sockets stay open, heartbeats stop: the hung-rank
                    case only the liveness plane can catch
    drop@K          silently swallow the K-th tensor-frame send
    delay@K:MS      sleep MS milliseconds before every tensor-frame send
                    from the K-th on (slow-link / straggler simulation)
    restart@K:MS    kill@K, then RE-EXEC the same command line MS
                    milliseconds later with DCN_EPOCH incremented and the
                    chaos spec cleared — the transient-crash-and-recover
                    case the elastic membership plane (JOIN handshake,
                    docs/FAULT_TOLERANCE.md healing) re-admits
    flap@K:MS       drop every open connection (data, command, accepted)
                    before the K-th send and stay silent for MS ms, then
                    resume — a network blip. Survivable without failover
                    when every rank's DCN_RECONNECT_GRACE exceeds MS;
                    with grace 0 the fleet treats it as a death (and,
                    because the flapped rank keeps its epoch, its
                    post-fence frames are dropped as stale)
    slow@K:MS       persistent gray degradation: MS ms added to every
                    send from the K-th on, FOREVER — the throttled-TPU /
                    degrading-NIC straggler the peer-health plane
                    (docs/FAULT_TOLERANCE.md gray failures) must detect.
                    `slow@K-J:MS` bounds it to sends K..J inclusive (the
                    "chaos clears" case probation readmission needs)
    jitter@K:MS     like slow, but the per-send delay is uniform random
                    in [0, MS] — deterministic per process via
                    DCN_CHAOS_SEED (default 0). `jitter@K-J:MS` bounds it
    corrupt@K       flip one bit in the K-th send's largest payload
                    tensor AFTER any frame checksum was computed
                    (comm/dcn.py applies it below the integrity layer),
                    so PIPEEDGE_WIRE_CRC verification sees genuine wire
                    corruption; without CRC the garbage propagates —
                    what the NaN guard exists to catch

Counting is over `send_tensors` calls on the wrapped context (command and
heartbeat frames are not counted — they are the recovery machinery under
test). For a pipeline stage, one send == one microbatch, so `@K` indexes
microbatches directly.
"""
from __future__ import annotations

import logging
import os
import signal
import socket as socket_mod
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils.threads import make_lock

ENV_CHAOS = "DCN_CHAOS"
ENV_CHAOS_SEED = "DCN_CHAOS_SEED"   # jitter determinism (default 0)

logger = logging.getLogger(__name__)


@dataclass
class ChaosAction:
    kind: str            # kill | hang | drop | delay | restart | flap |
    # slow | jitter | corrupt
    at_send: int         # 1-based send index the action arms at
    delay_ms: float = 0.0
    until_send: Optional[int] = None   # slow/jitter: last affected send
    # (inclusive); None = the degradation persists forever
    fired: bool = False  # slow/jitter: arming logged (harnesses stamp
    # the fault instant off that one log line, like kill/hang/drop do)


@dataclass
class ChaosSpec:
    actions: List[ChaosAction] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str) -> "ChaosSpec":
        actions = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                kind, _, where = part.partition("@")
                kind = kind.strip().lower()
                if kind in ("delay", "restart", "flap"):
                    at, _, ms = where.partition(":")
                    actions.append(ChaosAction(kind, int(at),
                                               delay_ms=float(ms or 0)))
                elif kind in ("slow", "jitter"):
                    at, _, ms = where.partition(":")
                    at, _, until = at.partition("-")
                    actions.append(ChaosAction(
                        kind, int(at), delay_ms=float(ms or 0),
                        until_send=int(until) if until else None))
                elif kind in ("kill", "hang", "drop", "corrupt"):
                    actions.append(ChaosAction(kind, int(where)))
                else:
                    raise ValueError(f"unknown chaos action {kind!r}")
            except ValueError as exc:
                raise ValueError(
                    f"bad {ENV_CHAOS} clause {part!r}: {exc} (grammar: "
                    "kill@K | hang@K | drop@K | delay@K:MS | "
                    "restart@K:MS | flap@K:MS | slow@K[-J]:MS | "
                    "jitter@K[-J]:MS | corrupt@K)") from None
        return cls(actions)


class _ChaosSender:
    """Wraps a context's `send_tensors`, applying the spec's actions at
    their exact send indices. Thread-safe: a stage's send thread and the
    data rank's feed thread may share one context."""

    def __init__(self, ctx, spec: ChaosSpec):
        self._ctx = ctx
        self._inner = ctx.send_tensors
        self._spec = spec
        self._lock = make_lock("chaos.sender")
        self._count = 0
        # jitter determinism: one seeded stream per process (the spec is
        # per-process, so replaying the same seed replays the delays)
        import random
        self._rng = random.Random(int(os.getenv(ENV_CHAOS_SEED, "0")))

    def __call__(self, dst, tensors, channel=0, trace=None):
        with self._lock:
            self._count += 1
            n = self._count
        for act in self._spec.actions:
            if act.kind == "delay" and n >= act.at_send:
                time.sleep(act.delay_ms / 1e3)
            elif act.kind in ("slow", "jitter") and n >= act.at_send \
                    and (act.until_send is None or n <= act.until_send):
                if not act.fired:
                    # one arming line at the FAULT instant (the
                    # per-send sleeps are silent): what chaos_dcn.py
                    # stamps fault-to-quarantine latency against
                    act.fired = True
                    logger.error("chaos: %s arming at send %d "
                                 "(%.0f ms/send%s)", act.kind, n,
                                 act.delay_ms,
                                 "" if act.until_send is None
                                 else f" through send {act.until_send}")
                ms = (act.delay_ms if act.kind == "slow"
                      else self._rng.uniform(0.0, act.delay_ms))
                time.sleep(ms / 1e3)
            elif n == act.at_send:
                if act.kind == "corrupt":
                    # one-shot flag the transport consumes BELOW its
                    # integrity layer (dcn._send_tensors_once): the bit
                    # flips after any checksum was computed and after the
                    # resend cache captured the clean frame — genuine
                    # wire corruption, recoverable by a resend
                    logger.error("chaos: corrupting send %d (one bit "
                                 "flip)", n)
                    self._ctx._corrupt_next_send = True
                if act.kind == "kill":
                    logger.error("chaos: killing this process before "
                                 "send %d", n)
                    os._exit(137)
                if act.kind == "restart":
                    _restart(n, act.delay_ms)
                if act.kind == "hang":
                    logger.error("chaos: SIGSTOPping this process before "
                                 "send %d", n)
                    os.kill(os.getpid(), signal.SIGSTOP)
                if act.kind == "drop":
                    logger.warning("chaos: dropping send %d", n)
                    return
                if act.kind == "flap":
                    _flap(self._ctx, n, act.delay_ms)
        return self._inner(dst, tensors, channel=channel, trace=trace)


def _restart(n: int, delay_ms: float) -> None:
    """kill@K followed by a delayed re-exec of the SAME command line: the
    replacement process starts `delay_ms` later with DCN_EPOCH incremented
    (a genuinely new incarnation the JOIN handshake can admit) and the
    chaos spec cleared (the restarted rank must not crash again). The
    relauncher is a detached child so it survives this process's exit;
    stdout/stderr are inherited, so a harness reading this rank's pipe
    also sees the new incarnation's lines."""
    import subprocess
    import sys

    from . import dcn

    epoch = int(os.getenv(dcn.ENV_EPOCH, "0")) + 1
    env = dict(os.environ)
    env.pop(ENV_CHAOS, None)
    env[dcn.ENV_EPOCH] = str(epoch)
    argv = [sys.executable] + list(sys.argv)
    logger.error("chaos: killing this process before send %d; re-exec "
                 "as epoch %d in %.0f ms", n, epoch, delay_ms)
    subprocess.Popen(
        [sys.executable, "-c",
         "import subprocess, sys, time; time.sleep(float(sys.argv[1])); "
         "sys.exit(subprocess.call(sys.argv[2:]))",
         str(delay_ms / 1e3)] + argv,
        env=env, start_new_session=True)
    os._exit(137)


def _flap(ctx, n: int, delay_ms: float) -> None:
    """Drop every open connection on `ctx` (peers see the break; this
    rank's readers see their sockets die), stay silent for `delay_ms`,
    then return — the pending send redials. The listener stays bound, so
    peers inside a reconnect-grace window revive the rank on redial."""
    logger.error("chaos: flapping before send %d (all connections "
                 "dropped for %.0f ms)", n, delay_ms)
    with ctx._conns_lock:
        conns = (list(ctx._conns.values()) + list(ctx._cmd_conns.values())
                 + list(ctx._accepted))
        ctx._conns.clear()
        ctx._cmd_conns.clear()
        ctx._accepted.clear()
    for c in conns:
        try:
            c.shutdown(socket_mod.SHUT_RDWR)
        except OSError:
            pass
        try:
            c.close()
        except OSError:
            pass
    time.sleep(delay_ms / 1e3)
    logger.warning("chaos: flap over; connections will redial")


def maybe_install(ctx) -> Optional[ChaosSpec]:
    """Install the `DCN_CHAOS` spec (if any) onto `ctx` by wrapping its
    `send_tensors`. Returns the parsed spec, or None when the env var is
    unset. Call once, after the context is constructed."""
    raw = os.getenv(ENV_CHAOS)
    if not raw:
        return None
    spec = ChaosSpec.parse(raw)
    ctx.send_tensors = _ChaosSender(ctx, spec)
    logger.warning("chaos: installed %s", raw)
    return spec
