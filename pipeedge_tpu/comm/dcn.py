"""Cross-host (DCN) tensor transport: framed TCP P2P + pipeline stages.

The second transport, for spans XLA collectives don't cover. Within a TPU
slice the SPMD pipeline's `ppermute` edges ride ICI (parallel/spmd.py);
across independent hosts/slices that are NOT joined into one JAX process
group (no `jax.distributed`), activations must travel host-side — the role
the reference's gloo P2P backend plays (reference comm/p2p/__init__.py).

Capability parity with the reference's wire layer, redesigned for numpy/JAX:

- framing: per message a fixed header, then per tensor a dtype code + shape
  + raw payload (reference p2p:96-121 sends dtype/shapelen, shape, payload as
  separate tagged messages; one length-prefixed frame per tensor suffices on
  a stream socket and avoids the tag multiplexing entirely).
- dtype enum: `_DTYPES` (reference TORCH_TYPES, p2p:24-38) including
  bfloat16 via ml_dtypes — the dtype JAX TPU programs actually exchange.
- command channel: CMD frames carry (cmd, tensors) to every peer — the
  reference's `cmd_broadcast` on tag 10 (p2p:72-85). Delivery is dispatched
  to a handler callback from the receiving connection's reader thread.
- pipeline stage: `DcnPipelineStage` wires recv -> work -> send with bounded
  hand-off queues, preserving the reference's end-to-end backpressure
  semantics (ConditionQueue maxsize=1, p2p:88-93, 252-257): at most one
  microbatch buffered per hop, TCP flow control propagating stalls upstream.

There is no pickle fallback: payloads are always ndarrays (the reference
needs pickling for its schedule broadcast, util.py:28-46; here schedules are
encoded as int arrays by the caller, runtime.py's CMD_SCHED tensor format).

Elastic membership (docs/FAULT_TOLERANCE.md rank lifecycle): every HELLO
carries the sender's incarnation epoch (env DCN_EPOCH); a confirmed death
fences the dead incarnation so zombie frames are dropped at the reader;
and a restarted peer with a higher epoch re-admits itself through the
`_MSG_JOIN` handshake (`announce_join` / `register_peer_rejoin_handler`),
coming back as live spare capacity instead of staying dead forever.
"""
from __future__ import annotations

import logging
import os
import queue
import socket
import struct
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import CMD_STOP, DistContext
from . import wire as wire_codec
from .. import telemetry
from ..telemetry import metrics as prom
from ..utils.threads import make_lock

try:  # bfloat16 on the wire (JAX's native TPU dtype)
    import ml_dtypes
    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BFLOAT16 = None
try:  # sub-byte quantized payloads (stored 1 byte/value in memory,
    _INT4 = np.dtype(ml_dtypes.int4)     # nibble-packed 2/byte on the wire)
    _UINT4 = np.dtype(ml_dtypes.uint4)
except (NameError, AttributeError, TypeError):  # pragma: no cover
    _INT4 = _UINT4 = None

logger = logging.getLogger(__name__)

# dtype enum (reference TORCH_TYPES, p2p/__init__.py:24-38)
_DTYPES: List[Optional[np.dtype]] = [np.dtype(d) for d in (
    'float16', 'float32', 'float64', 'uint8', 'int8', 'int16', 'int32',
    'int64', 'bool', 'complex64', 'complex128', 'uint16', 'uint32',
    'uint64')] + [_BFLOAT16, _INT4, _UINT4]
# 4-bit codes travel nibble-packed: ceil(n/2) wire bytes for n values
# (their in-memory representation burns a full byte per value)
_NIBBLE_CODES = frozenset(
    i for i, d in enumerate(_DTYPES) if d is not None and d in (_INT4, _UINT4))

_MSG_TENSORS = 1
_MSG_CMD = 2
_MSG_HELLO = 3
# per-edge bitwidth negotiation on the control channel (aux = bitwidth):
# answered directly by the receiving reader thread, no app wiring needed
_MSG_NEG = 4
_MSG_NEG_ACK = 5
# liveness plane (aux = sender rank): periodic no-payload frames on the
# dedicated command connections. A closed socket already raises on its
# reader; heartbeats additionally catch a HUNG peer — process frozen,
# sockets still open — which no amount of stream-error handling can see.
_MSG_HEARTBEAT = 6
# telemetry collection (aux = probe flag): a `_MSG_SPANS` request is
# answered inline by the receiving reader thread with a `_MSG_SPANS_ACK`
# carrying [t_rx, t_tx] receiver timestamps + the receiver's span ring as a
# uint8 JSON blob (empty when span recording is off). The same exchange
# doubles as the NTP-style clock probe `collect_spans` aligns ranks with.
_MSG_SPANS = 7
_MSG_SPANS_ACK = 8
# elastic membership plane (aux = joiner's epoch): a restarted (or late)
# peer asks to be re-admitted over the command channel. The receiver
# un-deads the rank (cancels pending death timers, resets the heartbeat
# watch) when the epoch is NEWER than every incarnation it has fenced,
# and replies _MSG_JOIN_ACK (aux = receiver's epoch; -1 = refused).
_MSG_JOIN = 9
_MSG_JOIN_ACK = 10
# tiered-transport negotiation (aux = path-tier code): a producing rank
# asks the consuming rank which transport tier its edge should ride —
# answered inline by the receiving reader thread like `_MSG_NEG`. The
# receiver grants the COLOCATED tier only when the proposer's context is
# registered in this very process (the hand-off is a direct queue put of
# device buffers, so both ends must share an address space), else the
# zero-copy socket tier when its receive pool is enabled, else legacy v2.
_MSG_PATH = 11
_MSG_PATH_ACK = 12
# request-scoped tracing (docs/OBSERVABILITY.md): a `_MSG_TENSORS` frame
# whose FIRST tensor is a uint8 JSON trace-context blob (telemetry.
# TraceContext.to_wire). The reader strips the blob and delivers the
# remaining tensors exactly like a plain data frame, with the decoded
# context as queue metadata — so stage workers' dispatch/readback/emit
# spans and per-edge transfer spans inherit the request id fleet-wide.
# Wire-v2 compatible by construction: plain `_MSG_TENSORS` frames stay
# byte-identical (absent = untraced), and an undecodable/truncated blob
# degrades to untraced (counted), never to a dead reader.
_MSG_TENSORS_TRACED = 13
# heartbeat RTT echo (aux = the echoed beat sequence number): a beat that
# carries a sequence-number payload is answered inline by the receiving
# reader with this ack, so the beat sender can measure the command-plane
# round trip per peer — the latency signal the gray-failure detector
# (pipeedge_tpu/health/) consumes; beats without the payload (older
# peers) simply go unanswered and keep their pure-liveness meaning.
_MSG_HEARTBEAT_ACK = 14
# frame-integrity recovery (aux = the corrupt frame's per-edge sequence
# number, -1 = latest; payload = [channel int32]): the receiving READER
# verifies CRC-flagged frames in flight and, on a checksum mismatch,
# drops the frame and asks the producer to re-send it BY SEQUENCE
# NUMBER — with PIPEEDGE_WIRE_CRC armed, data-frame headers carry a
# per-(dst, channel) seq in the aux field, and the producer keeps the
# last RESEND_CACHE_DEPTH clean frames per edge (pipelined sends mean
# "the last frame" may already be a LATER one; the seq address makes the
# replay exact). Each cached frame replays at most max(1, send_retries)
# times — the bounded redial+resend the integrity satellite reuses
# DCN_SEND_RETRIES for. A cache miss (producer restarted, cap hit,
# frame aged out) means the frame is lost and the round's normal
# timeout/failover semantics apply.
_MSG_RESEND = 15
_SPANS_PROBE = 1    # aux: timestamps only (clock probe)
_SPANS_REQUEST = 0  # aux: timestamps + span ring
_SPANS_DIGEST = 2   # aux: timestamps + cumulative duration digest — the
# per-round rebalance collection (kilobytes of (cat,name,stage) rollups;
# durations only, so no clock alignment and no full trace required)

# wire bitwidths a context accepts by default for its inbound quantized
# edges (ops/quant.py SUPPORTED_BITS, restatable per context so a peer
# without e.g. the sub-byte decode path can cap its producers)
DEFAULT_EDGE_BITS = (0, 1, 2, 3, 4, 5, 6, 8, 16, 32)

# Liveness / transient-fault knobs (env defaults; constructor args and the
# runtime CLI override). Interval 0 disables the heartbeat plane entirely.
ENV_HEARTBEAT_INTERVAL = "DCN_HEARTBEAT_INTERVAL"   # seconds between beats
ENV_HEARTBEAT_MISS = "DCN_HEARTBEAT_MISS"           # missed-beat threshold
ENV_RECONNECT_GRACE = "DCN_RECONNECT_GRACE"         # seconds a dropped peer
# may reconnect before its death is confirmed (0 = declare immediately)
ENV_SEND_RETRIES = "DCN_SEND_RETRIES"               # redial+resend attempts
ENV_EPOCH = "DCN_EPOCH"                             # this rank's incarnation
# number (0 = first launch). A restarted rank MUST come up with a higher
# epoch than the incarnation that died, or its JOIN is refused and its
# frames stay fenced (comm/chaos.py `restart@K:MS` re-execs with it
# incremented; orchestrators do the same).
DEFAULT_HEARTBEAT_MISS = 3

# -- tiered inter-stage transport (docs/DCN_WIRE.md selection matrix) ----
# Per edge, the producer negotiates the cheapest path the consumer can
# serve (`negotiate_edge_bits` idiom, `_MSG_PATH` on the control channel):
#
#   local      colocated ranks (same process): device buffers hand off
#              through the consumer context's bounded recv queue directly —
#              no serialize, no D2H/H2D round trip, no socket. The wire
#              protocol's framing (src, epoch, channel) rides as queue
#              metadata; epoch fencing, liveness signs, and the monitor
#              hooks behave exactly like the socket reader's.
#   zerocopy   remote edges: scatter-gather `sendmsg` writes (no flattening
#              copy — the pre-existing send path) paired with POOLED
#              receive buffers: payloads land via `recv_into` in reusable
#              buffers and surface as ndarray views, eliminating the
#              per-tensor bytes() copy. Buffers recycle only when no
#              consumer still references them (refcount ownership), so a
#              retained array — the failover ledger, a replay — can never
#              observe a recycled buffer.
#   socket_v2  the legacy copy-on-receive socket path (fallback, and the
#              A/B baseline: DCN_RECV_POOL=0).
PATH_SOCKET_V2 = "socket_v2"
PATH_ZEROCOPY = "zerocopy"
PATH_LOCAL = "local"
PATH_CODES = {PATH_SOCKET_V2: 0, PATH_ZEROCOPY: 1, PATH_LOCAL: 2}
_PATH_BY_CODE = {v: k for k, v in PATH_CODES.items()}
ENV_RECV_POOL = "DCN_RECV_POOL"          # 0 disables pooled recv buffers
ENV_LOCAL_HANDOFF = "DCN_LOCAL_HANDOFF"  # 0 disables the colocated tier

# process-local context registry, keyed by listen address: how a sender
# discovers that a destination rank's context lives in THIS process (and
# its frames can skip the socket entirely). Registered in init(),
# unregistered in shutdown().
_LOCAL_CONTEXTS: Dict[Tuple[str, int], "DistDcnContext"] = {}
_LOCAL_LOCK = make_lock("dcn.local_registry")


class _RecvBufferPool:
    """Reusable receive buffers for the zero-copy socket tier.

    `acquire(n)` hands out a bytearray of at least `n` bytes; payloads are
    `recv_into`'d and surfaced as `np.frombuffer` views, so the buffer
    stays referenced for exactly as long as any consumer holds the array.
    Recycling is refcount-driven: a buffer is reused only when the pool
    itself is its sole owner — ownership hand-off without a release
    protocol, and a retained array (the ledger holding a result, a replay
    in flight) silently promotes its buffer out of rotation instead of
    ever being overwritten. One pool per reader thread: no locking.
    """

    # 3 == pool list + loop variable + getrefcount argument: no array
    # view (or any other consumer) references the buffer
    _FREE_REFCOUNT = 3

    def __init__(self, max_buffers: int = 16):
        self._bufs: List[bytearray] = []
        self._max = max_buffers

    def acquire(self, n: int) -> bytearray:
        for buf in self._bufs:
            if len(buf) >= n \
                    and sys.getrefcount(buf) == self._FREE_REFCOUNT:
                return buf
        buf = bytearray(max(n, 4096))
        # retained buffers (refcount > free) rotate out: drop the oldest
        # still-held entry first — its consumer keeps it alive, and the
        # pool can never reuse it while held — so free (just too-small)
        # buffers survive for smaller frames; only a fully-free pool
        # evicts a reusable one
        if len(self._bufs) >= self._max:
            idx = 0
            for old in self._bufs:   # same refcount shape as the scan above
                if sys.getrefcount(old) != self._FREE_REFCOUNT:
                    break            # held: evict this one
                idx += 1
            self._bufs.pop(idx if idx < len(self._bufs) else 0)
        self._bufs.append(buf)
        return buf


def _recv_pool_enabled() -> bool:
    return os.getenv(ENV_RECV_POOL, "1") != "0" \
        and hasattr(sys, "getrefcount")


def _local_handoff_enabled() -> bool:
    return os.getenv(ENV_LOCAL_HANDOFF, "1") != "0"


def _put_on_device(tensors: List, device) -> List:
    """Move the device arrays in a colocated hand-off onto the consumer's
    device (`utils/jax_compat.py` has no shim to add here: `device_put`
    between colocated devices is the ICI/DMA transfer — it never routes
    through the host; within one mesh the SPMD pipeline's
    `collective_permute` edges in parallel/spmd.py cover the same hop).
    Host ndarrays pass through untouched — the consumer's first jit
    places them. No-jax builds (socket-only users) degrade to a no-op."""
    try:
        import jax
    except ImportError:  # pragma: no cover - jax ships with this tree
        return tensors
    out = []
    for t in tensors:
        if isinstance(t, jax.Array) and device is not None \
                and getattr(t, "sharding", None) is not None \
                and t.sharding.device_set != {device}:
            t = jax.device_put(t, device)
        out.append(t)
    return out


# /metrics plane: exceeded-silence events the liveness watcher saw (the
# healthz/metrics "is the fleet flapping" signal; docs/OBSERVABILITY.md)
_HEARTBEAT_MISSES = prom.REGISTRY.counter(
    "pipeedge_heartbeat_miss_total",
    "peers whose heartbeat silence exceeded interval*miss (per event)")
# epoch fencing: frames a reader dropped because they were sent by an
# incarnation that has since been fenced (declared dead, or superseded by
# a newer incarnation's admission) — the "stale zombie frame" signal
_STALE_FRAMES = prom.REGISTRY.counter(
    "pipeedge_stale_frames_dropped_total",
    "frames dropped at the reader because their sender incarnation was "
    "fenced (dead or superseded), by sender rank")
# membership plane: admissions this context granted to rejoining peers
_PEER_REJOINS = prom.REGISTRY.counter(
    "pipeedge_peer_rejoins_total",
    "JOIN admissions granted to restarted/rejoining peers, by rank")
# request tracing: data frames that arrived carrying a trace context, per
# producing peer (the per-edge trace counter the request-tracing plane
# reports), and blobs that failed to decode (tolerated as untraced)
_TRACED_FRAMES = prom.REGISTRY.counter(
    "pipeedge_traced_frames_total",
    "data frames received with a trace-context field, by producing peer")
_TRACE_INVALID = prom.REGISTRY.counter(
    "pipeedge_trace_ctx_invalid_total",
    "trace-context blobs that failed to decode (frame delivered untraced)")
# gray-failure signal: bounded redial+resend attempts the transport paid
# per destination (DCN_SEND_RETRIES) — a link that needs retries is
# degrading even when every retry eventually succeeds
_SEND_RETRIES_TOTAL = prom.REGISTRY.counter(
    "pipeedge_send_retries_total",
    "data-send redial+resend attempts (DCN_SEND_RETRIES), by peer rank")
# frame integrity (PIPEEDGE_WIRE_CRC): frames whose checksum failed at
# the receiving reader — each one triggers a bounded seq-addressed
# resend request. Public: the runtime's belt-and-braces decode handlers
# count on the same family.
FRAMES_CORRUPT = prom.REGISTRY.counter(
    "pipeedge_frames_corrupt_total",
    "wire frames that failed the integrity checksum on receive, by "
    "producing peer")


def _env_number(name: str, default, cast):
    val = os.getenv(name)
    if not val:
        return default
    try:
        return cast(val)
    except ValueError:
        raise ValueError(f"{name}={val!r} is not a number") from None

# msg_type, aux (cmd / sender rank), channel, n_tensors. The channel byte
# demultiplexes logically-distinct streams on the same rank pair (e.g. a
# colocated data rank's raw-input feed vs the last stage's results) — the
# role the reference's tag offsets play (p2p:12-21).
_HEADER = struct.Struct('!BiBH')
_TENSOR_HEADER = struct.Struct('!BB')  # dtype code, ndim
_DIM = struct.Struct('!q')

CHANNEL_DATA = 0     # inter-stage activations
CHANNEL_RESULTS = 1  # last stage -> data rank
CHANNEL_BIDS = 3     # reverse-auction bid replies -> auctioneer
# Round-parity offset for multi-round (re-schedule) runs: round r uses
# channel + CHANNEL_ROUND_PARITY*(r%2), so a frame the data rank streams for
# round r+1 can never be pulled by a stage from round r that is still
# tearing down (its recv loop polls only the old-parity channel; per-channel
# queues keep the traffic apart). Parity-2 suffices because a worker fully
# stops round r's stage before it begins round r+1.
CHANNEL_ROUND_PARITY = 8


def base_channel(channel: int) -> int:
    """Strip the round-parity offset: the logical stream kind
    (DATA/RESULTS/FEED) of a possibly parity-shifted channel byte."""
    return channel % CHANNEL_ROUND_PARITY


CHANNEL_FEED = 2     # data rank -> head stage (raw inputs). A separate
# channel so feed traffic is distinguishable from pipeline-edge traffic:
# the reference injects inputs *locally* (enqueue_tensor, p2p:442-450), so
# its per-rank 'send' telemetry never contains feed bytes — keeping the
# adaptive-quant policies' sensor clean. Monitoring hooks can filter on it.


def parse_rank_addrs(dcn_addrs: Optional[str], world_size: int,
                     base_port: int) -> List[Tuple[str, int]]:
    """Parse `--dcn-addrs 'h:p,h:p,...'` (one per rank) or default to
    localhost at base_port+rank (the reference's MASTER_ADDR/PORT analogue,
    runtime.py:599). Shared by every DCN CLI."""
    if dcn_addrs:
        parts = dcn_addrs.split(',')
        if len(parts) != world_size:
            raise RuntimeError("--dcn-addrs must list one host:port per rank")
        out = []
        for p in parts:
            host, port = p.rsplit(':', 1)
            out.append((host, int(port)))
        return out
    return [("127.0.0.1", base_port + i) for i in range(world_size)]


def _dtype_code(dtype: np.dtype) -> int:
    for i, d in enumerate(_DTYPES):
        if d is not None and d == dtype:
            return i
    raise TypeError(f"unsupported wire dtype: {dtype}")


def _socket_buf_bytes() -> int:
    """Requested SO_SNDBUF/SO_RCVBUF size. Default 4 MiB: inter-stage
    activation frames are megabytes, and deeper kernel buffers keep the
    sender's `sendmsg` from stalling on the default (often ~200 KiB)
    window while the stage could be computing. DCN_SOCKET_BUF overrides;
    0 keeps the kernel default."""
    env = os.getenv("DCN_SOCKET_BUF")
    if env:
        try:
            return int(env)
        except ValueError:
            raise ValueError(
                f"DCN_SOCKET_BUF={env!r} is not a byte count") from None
    return 4 << 20


def _tune_socket(sock: socket.socket) -> None:
    """Apply the transport socket options: TCP_NODELAY (frames are whole
    messages; never wait on Nagle) and enlarged send/recv buffers."""
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    buf = _socket_buf_bytes()
    if buf > 0:
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                sock.setsockopt(socket.SOL_SOCKET, opt, buf)
            except OSError:  # pragma: no cover - kernel policy caps apply
                pass


def _pack_nibbles(t: np.ndarray) -> np.ndarray:
    """4-bit array -> wire bytes, two values per byte (value i in byte
    i//2, low nibble first). int4 is stored as its two's-complement low
    nibble; the receiver sign-extends."""
    vals = t.reshape(-1).astype(np.int8).view(np.uint8) & np.uint8(0xF)
    if vals.size % 2:
        vals = np.concatenate([vals, np.zeros(1, np.uint8)])
    return (vals[0::2] | (vals[1::2] << np.uint8(4))).astype(np.uint8)


def _unpack_nibbles(payload: bytes, n: int, dtype: np.dtype) -> np.ndarray:
    """Inverse of `_pack_nibbles` for `n` values of 4-bit `dtype`."""
    b = np.frombuffer(payload, np.uint8)
    nib = np.empty(b.size * 2, np.uint8)
    nib[0::2] = b & np.uint8(0xF)
    nib[1::2] = b >> np.uint8(4)
    nib = nib[:n]
    if dtype == _INT4:  # sign-extend the two's-complement nibble
        return (((nib.astype(np.int8)) ^ 8) - 8).astype(dtype)
    return nib.astype(dtype)


def _recv_into_exact(sock: socket.socket, view: memoryview) -> None:
    """Fill `view` completely from the socket (raises on peer close)."""
    got, n = 0, view.nbytes
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    # returns the bytearray itself (struct.unpack and np.frombuffer both
    # take any buffer): no bytes() flattening copy
    buf = bytearray(n)
    _recv_into_exact(sock, memoryview(buf))
    return buf


# Linux caps sendmsg at UIO_MAXIOV (1024) iovecs; frames with many tensors
# (2 + ndim buffers each) must be sent in chunks or sendmsg fails EMSGSIZE.
_MAX_IOVECS = 1000


def _sendmsg_all(sock: socket.socket, parts: List) -> None:
    """Scatter-gather send of every buffer in `parts` (no flattening copy)."""
    bufs = [memoryview(p) for p in parts if len(p)]
    while bufs:
        try:
            sent = sock.sendmsg(bufs[:_MAX_IOVECS])
        except InterruptedError:
            continue
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if sent:
            bufs[0] = bufs[0][sent:]


def _send_frame(sock: socket.socket, msg_type: int, aux: int,
                tensors: Sequence[np.ndarray], channel: int = 0) -> None:
    parts = [_HEADER.pack(msg_type, aux, channel, len(tensors))]
    for t in tensors:
        t = np.asarray(t)
        if not t.flags.c_contiguous:  # ascontiguousarray would promote 0-d to 1-d
            t = np.ascontiguousarray(t)
        code = _dtype_code(t.dtype)
        parts.append(_TENSOR_HEADER.pack(code, t.ndim))
        for d in t.shape:
            parts.append(_DIM.pack(d))
        if code in _NIBBLE_CODES:
            parts.append(_pack_nibbles(t))
        else:
            # raw bytes view of the payload: zero-copy into sendmsg
            parts.append(t.reshape(-1).view(np.uint8))
    _sendmsg_all(sock, parts)


def _recv_header(sock: socket.socket) -> Tuple[int, int, int, int]:
    return _HEADER.unpack(_recv_exact(sock, _HEADER.size))


def _recv_body(sock: socket.socket, n: int,
               pool: Optional[_RecvBufferPool] = None) -> List[np.ndarray]:
    tensors = []
    for _ in range(n):
        code, ndim = _TENSOR_HEADER.unpack(
            _recv_exact(sock, _TENSOR_HEADER.size))
        dtype = _DTYPES[code]
        if dtype is None:
            raise TypeError("peer sent an ml_dtypes wire dtype (bfloat16/"
                            "int4/uint4) this build cannot represent")
        shape = tuple(_DIM.unpack(_recv_exact(sock, _DIM.size))[0]
                      for _ in range(ndim))
        n_values = int(np.prod(shape, dtype=np.int64))
        if code in _NIBBLE_CODES:
            payload = _recv_exact(sock, (n_values + 1) // 2)
            tensors.append(_unpack_nibbles(payload, n_values,
                                           dtype).reshape(shape))
            continue
        nbytes = dtype.itemsize * n_values
        if pool is not None and nbytes > 0:
            # zero-copy tier: the payload lands directly in a pooled
            # buffer and the array is a VIEW over it — no intermediate
            # allocation or copy. The view's refcount is what keeps the
            # buffer out of rotation (see _RecvBufferPool).
            buf = pool.acquire(nbytes)
            _recv_into_exact(sock, memoryview(buf)[:nbytes])
            tensors.append(np.frombuffer(buf, dtype=dtype,
                                         count=n_values).reshape(shape))
        else:
            payload = _recv_exact(sock, nbytes)
            tensors.append(np.frombuffer(payload, dtype=dtype).reshape(shape))
    return tensors


def _recv_frame(sock: socket.socket) -> Tuple[int, int, int, List[np.ndarray]]:
    msg_type, aux, channel, n = _recv_header(sock)
    return msg_type, aux, channel, _recv_body(sock, n)


def _flip_one_bit(tensors: Sequence) -> List:
    """Chaos corrupt@K: return `tensors` with one bit flipped in a COPY
    of the largest tensor (the activation payload — never the header,
    microbatch id, or checksum, which are all small). The caller's
    arrays are untouched."""
    tensors = list(tensors)
    sizes = [int(np.asarray(t).nbytes) for t in tensors]
    if not sizes or max(sizes) == 0:
        return tensors
    idx = sizes.index(max(sizes))
    victim = np.asarray(tensors[idx]).copy()
    flat = victim.reshape(-1).view(np.uint8)
    flat[flat.size // 2] ^= np.uint8(1)
    tensors[idx] = victim
    return tensors


class DistDcnContext(DistContext):
    """Point-to-point tensor transport between ranks over TCP (DCN).

    The reference's `DistP2pContext` (p2p:41-70) minus the process group:
    every rank runs a listener; links are dialed lazily on first send and
    identified by a HELLO frame. `send_tensors`/`recv_tensors` move ndarray
    lists rank-to-rank; `cmd_broadcast` fans a command frame to all peers,
    dispatched to `cmd_handler` on the receiver (reference tag-10 channel).
    """

    RECV_QUEUE_DEPTH = 1   # reference ConditionQueue maxsize=1 backpressure
    CONNECT_TIMEOUT = 60.0  # total dial deadline incl. refused-retry backoff
    # clean frames cached per (dst, channel) for integrity resends —
    # deeper than the default stage pipelining depth (2), so the frame a
    # consumer flags corrupt is still addressable by seq even after the
    # producer pipelined a few more sends on that edge
    RESEND_CACHE_DEPTH = 4

    def __init__(self, world_size: int, rank: int,
                 rank_addrs: Sequence[Tuple[str, int]],
                 cmd_handler: Optional[Callable] = None,
                 edge_bits_supported: Optional[Sequence[int]] = None,
                 reconnect_grace: Optional[float] = None,
                 send_retries: Optional[int] = None,
                 epoch: Optional[int] = None,
                 accept_joins: bool = True):
        super().__init__(world_size=world_size, rank=rank)
        assert len(rank_addrs) == world_size
        self._rank_addrs = list(rank_addrs)
        self._cmd_handler = cmd_handler
        # wire bitwidths this context accepts on its inbound quantized
        # edges; producers cap their proposals via negotiate_edge_bits
        self._edge_bits = tuple(sorted(set(
            edge_bits_supported if edge_bits_supported is not None
            else DEFAULT_EDGE_BITS)))
        # bitwidth-negotiation replies, keyed by the answering peer
        self._neg_replies: Dict[int, "queue.Queue"] = {}
        self._neg_lock = make_lock("dcn.neg")
        # span-collection replies, keyed by the answering peer (one
        # in-flight collect_spans per peer, like negotiation)
        self._span_replies: Dict[int, "queue.Queue"] = {}
        self._span_lock = make_lock("dcn.span")
        # tiered transport (docs/DCN_WIRE.md): negotiated path per
        # DESTINATION rank (producer side; only PATH_LOCAL changes this
        # context's send behavior), path-negotiation reply queues, the
        # env-resolved tier capabilities, and the device colocated
        # hand-offs should land on (set_local_device)
        self._edge_path: Dict[int, str] = {}
        self._path_replies: Dict[int, "queue.Queue"] = {}
        self._recv_pool_on = _recv_pool_enabled()
        self._local_on = _local_handoff_enabled()
        self._local_device = None
        # env override so small test fleets / fast-failing deployments don't
        # wait the full minute for a peer that will never come up
        env_timeout = os.getenv("DCN_CONNECT_TIMEOUT")
        if env_timeout:
            try:
                self.CONNECT_TIMEOUT = float(env_timeout)
            except ValueError:
                raise ValueError(
                    f"DCN_CONNECT_TIMEOUT={env_timeout!r} is not a number "
                    "(seconds)") from None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._reader_threads: List[threading.Thread] = []
        self._conns: Dict[int, socket.socket] = {}       # outgoing data, by dst
        # outgoing COMMAND connections, separate from the data sockets: a
        # data send blocked on backpressure holds its conn lock for as long
        # as the receiver stalls, and an abort command (CMD_STOP after a
        # peer death) must never queue behind it
        self._cmd_conns: Dict[int, socket.socket] = {}
        # per-destination locks (created upfront: world size is known), so a
        # slow dial to one peer never stalls traffic to the others
        self._conn_locks = [make_lock(f"dcn.conn[{i}]")
                            for i in range(world_size)]
        self._cmd_conn_locks = [make_lock(f"dcn.cmd_conn[{i}]")
                                for i in range(world_size)]
        self._conns_lock = make_lock("dcn.conns")        # dict/list mutation
        self._accepted: List[socket.socket] = []         # incoming
        self._recv_queues: Dict[Tuple[int, int], "queue.Queue"] = {}
        self._recv_lock = make_lock("dcn.recv")
        self._stop = threading.Event()
        # peer-death detection (beyond the reference, whose RPC backpressure
        # "breaks down if the previous stage fails to send data afterward",
        # rpc/__init__.py:83-86): ranks whose connection dropped outside a
        # clean shutdown, and an optional notification callback
        self._dead: set = set()
        self._dead_lock = make_lock("dcn.dead")
        self._peer_death_handler: Optional[Callable[[int], None]] = None
        # elastic membership (docs/FAULT_TOLERANCE.md rank lifecycle):
        # this rank's incarnation number — travels in every HELLO so the
        # receiver can fence frames from a dead incarnation
        self.epoch = int(epoch if epoch is not None
                         else _env_number(ENV_EPOCH, 0, int))
        # /metrics hygiene (pipelint PL501): membership is known here, so
        # the per-peer label matrices render from the first scrape — a
        # scraper watching a peer's series sees 0, not series-absent
        for r in range(world_size):
            if r != rank:
                _HEARTBEAT_MISSES.declare(peer=str(r))
                _STALE_FRAMES.declare(peer=str(r))
                _PEER_REJOINS.declare(peer=str(r))
                _TRACED_FRAMES.declare(peer=str(r))
                _SEND_RETRIES_TOTAL.declare(peer=str(r))
                FRAMES_CORRUPT.declare(peer=str(r))
        # admission policy: with accept_joins=False every _MSG_JOIN is
        # refused (the runtime's --on-peer-rejoin ignore), so a confirmed
        # death stays terminal exactly as before this plane existed
        self.accept_joins = bool(accept_joins)
        # highest epoch each peer ever HELLO'd/JOINed with (under _dead_lock)
        self._peer_epoch: Dict[int, int] = {}
        # fence floor per peer: frames from incarnations with epoch below
        # this are stale and dropped at the reader. Raised to dead_epoch+1
        # when a death is confirmed, and to the admitted epoch on JOIN.
        self._min_epoch: Dict[int, int] = {}
        self._peer_rejoin_handler: Optional[
            Callable[[int, int], None]] = None
        # instance-level stale counter so tests and the runtime can assert
        # "the fenced frame never reached the ledger" without scraping
        self.stale_frames_dropped = 0
        # peers whose listener answered at least once (dialed out or dialed
        # us): a later connection-REFUSED from one of these is a death
        # signal, not a still-starting listener (_ensure_conn fast path)
        self._ever_connected: set = set()
        # transient-fault policy: a dropped connection opens a grace window
        # (seconds) before the death is confirmed — a RESTARTING rank that
        # rebinds its listener and HELLOs again within it is revived, a dead
        # one is not. 0 preserves the declare-immediately behavior.
        self._reconnect_grace = (reconnect_grace if reconnect_grace is not None
                                 else _env_number(ENV_RECONNECT_GRACE, 0.0,
                                                  float))
        # bounded redial+resend attempts for a data send that hits a broken
        # pipe (transient network fault / peer restart); 0 = fail fast
        self.send_retries = (send_retries if send_retries is not None
                             else _env_number(ENV_SEND_RETRIES, 0, int))
        # monotonic stamp of the last life sign per peer (any inbound frame,
        # or a successful outbound dial): what a grace window checks against
        self._alive_at: Dict[int, float] = {}
        # ranks inside an open grace window, mapped to their pending timer
        self._pending_death: Dict[int, threading.Timer] = {}
        # liveness plane state (start_heartbeat)
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        self._hb_interval = 0.0
        self._hb_miss = DEFAULT_HEARTBEAT_MISS
        self._hb_peers: Tuple[int, ...] = ()
        self._hb_last_rx: Dict[int, float] = {}
        self._hb_lock = make_lock("dcn.hb")
        self._hb_hook: Optional[Callable[[int], None]] = None
        # per-peer redial backoff for the beat loop — instance state (not
        # loop-local) so a rejoin admission can clear it and the plane
        # starts beating the restored rank immediately
        self._hb_dial_backoff: Dict[int, float] = {}
        # heartbeat RTT measurement (all under _hb_lock): beat sequence
        # counter, in-flight probes (dst, seq) -> send stamp, and per-peer
        # bounded RTT sample windows (ms). Beats carry the seq as a
        # payload; the peer's reader echoes it back (_MSG_HEARTBEAT_ACK).
        self._hb_seq = 0
        self._hb_rtt_pending: Dict[Tuple[int, int], float] = {}
        self._hb_rtt: Dict[int, deque] = {}
        self._hb_rtt_hook: Optional[Callable[[int, float], None]] = None
        # gray-failure accounting + frame-integrity recovery (under
        # _retry_lock): per-destination redial+resend counts, and — when
        # PIPEEDGE_WIRE_CRC arms frame checksums — a per-(dst, channel)
        # frame sequence counter (travels in the data-frame aux field)
        # plus a bounded cache of the last clean frames per edge, each
        # entry [seq, msg_type, tensors, replays]. Deeper than the stage
        # pipelining depth (default 2) so a corrupt frame's seq is still
        # cached by the time the consumer's resend request arrives.
        self._retry_lock = make_lock("dcn.retry")
        self._send_retry_counts: Dict[int, int] = {}
        self._frame_seq: Dict[Tuple[int, int], int] = {}
        self._last_frames: Dict[Tuple[int, int], deque] = {}
        self._wire_crc = wire_codec.crc_enabled()
        # chaos hook (comm/chaos.py corrupt@K): one-shot bit flip applied
        # BELOW the integrity layer, on a copy, so the resend cache and
        # any checksum stay clean — simulated wire corruption
        self._corrupt_next_send = False
        # send/recv measurement hooks (reference p2p:132-152): pre fires just
        # before the payload moves, post just after, so (post - pre) is the
        # actual wire transfer time — excluding idle waits for data to exist.
        self._send_pre_hook: Optional[Callable[[int, int], None]] = None
        self._send_post_hook: Optional[
            Callable[[int, int, Sequence[np.ndarray]], None]] = None
        self._recv_pre_hook: Optional[Callable[[int, int], None]] = None
        self._recv_post_hook: Optional[
            Callable[[int, int, Sequence[np.ndarray]], None]] = None

    def register_send_hooks(self, pre: Optional[Callable] = None,
                            post: Optional[Callable] = None) -> None:
        """Measure data sends: `pre(dst, channel)` before the frame hits the
        socket, `post(dst, channel, tensors)` after the write completes
        (reference register_send_pre/post_hook, p2p:132-142). Command and
        HELLO frames are not measured."""
        self._send_pre_hook = pre
        self._send_post_hook = post

    def register_recv_hooks(self, pre: Optional[Callable] = None,
                            post: Optional[Callable] = None) -> None:
        """Measure data receipt: `pre(src, channel)` after a frame header
        arrives (payload incoming), `post(src, channel, tensors)` once the
        payload is fully read — so the interval is transfer time, not idle
        time (reference recv hooks run around the tensor payload reads,
        p2p:236-244).

        After `pre` fires, `post` is ALWAYS called — with `tensors=None` if
        the transfer aborted mid-payload (peer death) — so hooks that pair
        start/stop measurements never leak a started measurement."""
        self._recv_pre_hook = pre
        self._recv_post_hook = post

    def register_peer_death_handler(self, handler: Callable[[int], None]) \
            -> None:
        """`handler(rank)` fires (once per rank, from the observing thread)
        when a connection to/from `rank` drops while the context is live —
        i.e. not during `shutdown()`. A dropped connection during a clean
        stop is NOT a death; callers that race stop against detection should
        gate on their own stop flag inside the handler."""
        self._peer_death_handler = handler

    def _mark_dead(self, rank: int, reason: str = "connection lost") -> None:
        if rank < 0 or self._stop.is_set():
            return
        if self._reconnect_grace > 0:
            # open a grace window instead of declaring death: a RESTARTING
            # peer (rebinds + HELLOs within the window) is revived by
            # _confirm_dead finding a newer life sign
            with self._dead_lock:
                if rank in self._dead or rank in self._pending_death:
                    return
                timer = threading.Timer(
                    self._reconnect_grace, self._confirm_dead,
                    args=(rank, time.monotonic(), reason))
                timer.daemon = True
                self._pending_death[rank] = timer
            logger.warning("rank %d: peer rank %d %s; reconnect grace %.1fs",
                           self._rank, rank, reason, self._reconnect_grace)
            timer.start()
            return
        self._declare_dead(rank, reason)

    def _confirm_dead(self, rank: int, marked_at: float, reason: str) -> None:
        """Grace expiry: the peer is dead unless it showed a life sign
        (inbound frame / fresh HELLO / successful dial / JOIN admission)
        after the mark."""
        with self._dead_lock:
            self._pending_death.pop(rank, None)
        self._declare_dead(rank, reason + " (grace expired)",
                           not_after=marked_at)

    def _declare_dead(self, rank: int, reason: str,
                      not_after: Optional[float] = None) -> None:
        if self._stop.is_set():
            return
        with self._dead_lock:
            # revive check INSIDE the same critical section that declares:
            # a JOIN admission (which stamps _alive_at under this lock)
            # racing a grace-expiry timer must never be overridden by the
            # timer fencing the just-admitted incarnation
            if not_after is not None \
                    and self._alive_at.get(rank, 0.0) > not_after:
                revived = True
            elif rank in self._dead:
                return
            else:
                revived = False
                self._dead.add(rank)
                # fence the dead incarnation: anything it (or a zombie
                # copy of it) still manages to push onto a half-open
                # socket is stale. A restart must come back with a HIGHER
                # epoch to be heard.
                dead_epoch = self._peer_epoch.get(rank, 0)
                self._min_epoch[rank] = max(self._min_epoch.get(rank, 0),
                                            dead_epoch + 1)
        if revived:
            logger.info("rank %d: peer rank %d reconnected within grace",
                        self._rank, rank)
            return
        # a dead peer's negotiated path is void: whatever replaces it
        # (failover target, restarted incarnation) must renegotiate
        self._edge_path.pop(rank, None)
        logger.warning("rank %d: peer rank %d %s (peer death?)",
                       self._rank, rank, reason)
        if self._peer_death_handler is not None:
            self._peer_death_handler(rank)

    def _alive_sign(self, rank: int) -> None:
        """Record a life sign from `rank` (called from reader threads and
        successful dials); what an open grace window is checked against."""
        with self._dead_lock:
            self._alive_at[rank] = time.monotonic()

    def dead_ranks(self) -> frozenset:
        """Ranks this context has confirmed dead (post-grace) and not
        since re-admitted via the JOIN handshake."""
        with self._dead_lock:
            return frozenset(self._dead)

    def min_epoch_of(self, rank: int) -> int:
        """The fence floor for `rank`: frames from incarnations with a
        lower epoch are stale (dropped at the reader). 0 = never fenced."""
        with self._dead_lock:
            return self._min_epoch.get(rank, 0)

    # -- elastic membership (rejoin) -----------------------------------

    def register_peer_rejoin_handler(
            self, handler: Optional[Callable[[int, int], None]]) -> None:
        """`handler(rank, epoch)` fires (off-thread) when a peer passes
        the JOIN admission handshake — the signal the runtime uses to pull
        the rank out of its terminal dead set and plan a heal."""
        self._peer_rejoin_handler = handler

    def _admit_peer(self, src: int, epoch: int) -> bool:
        """Process a _MSG_JOIN from `src` claiming incarnation `epoch`:
        admit (un-dead, reset liveness watch, drop stale conns) when the
        epoch is not below the fence floor, refuse otherwise. Returns
        whether the peer was admitted."""
        if not self.accept_joins or src < 0 or src == self._rank:
            return False
        with self._dead_lock:
            if epoch < self._min_epoch.get(src, 0):
                return False    # a zombie of a fenced incarnation
            was_dead = src in self._dead
            self._dead.discard(src)
            timer = self._pending_death.pop(src, None)
            self._alive_at[src] = time.monotonic()
            self._peer_epoch[src] = max(self._peer_epoch.get(src, 0), epoch)
            # supersede every older incarnation: even if the old one was
            # never CONFIRMED dead (fast restart inside grace), its frames
            # must not interleave with the new incarnation's
            self._min_epoch[src] = max(self._min_epoch.get(src, 0), epoch)
        if timer is not None:
            timer.cancel()
        # the old incarnation's outgoing sockets are gone; drop them so
        # the next send/beat redials the restarted listener. Its
        # negotiated transport path is equally stale (a restarted rank
        # is a NEW process: a colocated grant would now dangle).
        self._edge_path.pop(src, None)
        with self._conns_lock:
            self._conns.pop(src, None)
            self._cmd_conns.pop(src, None)
        # heartbeat hygiene: restart the watch from the peer's FIRST new
        # beat (watching-starts-at-first-beat rule), and clear the dial
        # backoff so this rank resumes beating it immediately — a second
        # death of the same rank must be detected like the first
        with self._hb_lock:
            self._hb_last_rx.pop(src, None)
        self._hb_dial_backoff.pop(src, None)
        _PEER_REJOINS.inc(peer=str(src))
        logger.warning("rank %d: peer rank %d rejoined (epoch %d%s)",
                       self._rank, src, epoch,
                       ", was confirmed dead" if was_dead else "")
        if self._peer_rejoin_handler is not None:
            # off-thread like _mark_dead: the handler may broadcast
            # commands, and this reader must keep serving frames
            threading.Thread(target=self._peer_rejoin_handler,
                             args=(src, epoch), daemon=True).start()
        return True

    def _cmd_channel_send(self, dst: int, msg_type: int, aux: int,
                          tensors: Sequence[np.ndarray] = (),
                          timeout: Optional[float] = None) -> None:
        """One frame to `dst` over the dedicated command connection,
        invalidating the cached conn on failure so the next send redials
        — the shared core of every point-to-point control-channel path
        (negotiation, span replies, JOIN, CMD sends)."""
        with self._cmd_conn_locks[dst]:
            conn = self._ensure_conn(dst, timeout=timeout,
                                     conns=self._cmd_conns)
            try:
                _send_frame(conn, msg_type, aux, tensors)
            except OSError:
                with self._conns_lock:
                    if self._cmd_conns.get(dst) is conn:
                        del self._cmd_conns[dst]
                raise

    def _try_cmd_send(self, dst: int, msg_type: int, aux: int,
                      tensors: Sequence[np.ndarray] = (),
                      lock_timeout: float = 0.5,
                      dial_timeout: float = 2.0) -> bool:
        """Best-effort, BOUNDED command-channel send for reader-thread
        replies (heartbeat-RTT echoes, resend requests): a busy conn
        lock (e.g. a broadcast blocked mid-send to the same peer) or a
        failed dial just drops the reply — one lost probe/request, never
        a wedged reader. Returns whether the frame went out."""
        lock = self._cmd_conn_locks[dst]
        if not lock.acquire(timeout=lock_timeout):
            return False
        try:
            conn = self._ensure_conn(dst, timeout=dial_timeout,
                                     conns=self._cmd_conns)
            try:
                _send_frame(conn, msg_type, aux, tensors)  # pipelint: disable=PL102
                return True
            except OSError:
                with self._conns_lock:
                    if self._cmd_conns.get(dst) is conn:
                        del self._cmd_conns[dst]
                return False
        except OSError:
            return False
        finally:
            lock.release()

    def announce_join(self, peers: Optional[Sequence[int]] = None,
                      timeout: float = 5.0) -> List[int]:
        """Ask every peer (default: the whole fleet) to re-admit this rank
        at its current epoch — what a restarted rank calls after init().
        Best-effort per peer (a peer that is itself down just misses the
        announcement); returns the list of peers the JOIN reached."""
        reached = []
        for dst in (peers if peers is not None else range(self._world_size)):
            if dst == self._rank:
                continue
            try:
                self._cmd_channel_send(dst, _MSG_JOIN, self.epoch,
                                       timeout=timeout)
                reached.append(dst)
            except OSError as exc:
                logger.warning("rank %d: JOIN announcement to rank %d "
                               "failed: %s", self._rank, dst, exc)
        return reached

    def cmd_send(self, dst: int, cmd: int,
                 tensors: Sequence[np.ndarray] = (),
                 timeout: Optional[float] = None) -> None:
        """Send a command frame to ONE peer over the command connection —
        the point-to-point complement of `cmd_broadcast` (an admission ACK
        must reach exactly the rejoiner, not the fleet). Raises OSError
        when `dst` is unreachable."""
        self._cmd_channel_send(dst, _MSG_CMD, cmd, tensors,
                               timeout=timeout)

    # -- liveness plane ------------------------------------------------

    def register_heartbeat_hook(self, hook: Optional[Callable[[int], None]]) \
            -> None:
        """`hook(src)` fires on the reader thread for every heartbeat frame
        received — the feed for monitoring's heartbeat windows."""
        self._hb_hook = hook

    def register_heartbeat_rtt_hook(
            self, hook: Optional[Callable[[int, float], None]]) -> None:
        """`hook(src, rtt_ms)` fires on the reader thread for every
        heartbeat probe that comes home — the per-sample feed for
        monitoring's RTT windows (the aggregate view is
        `heartbeat_rtt_stats`)."""
        self._hb_rtt_hook = hook

    def heartbeat_rtt_stats(self) -> Dict[int, Dict[str, float]]:
        """Per-peer heartbeat round-trip statistics over the bounded
        sample window: `{peer: {"n", "p50_ms", "p99_ms"}}` (nearest-rank
        percentiles; peers with no completed probe are absent). The
        latency signal the gray-failure scorer and the
        `pipeedge_heartbeat_rtt_ms` gauges read — beats prove liveness,
        these prove the link is still FAST."""
        with self._hb_lock:
            samples = {p: sorted(dq) for p, dq in self._hb_rtt.items()
                       if dq}
        out: Dict[int, Dict[str, float]] = {}
        for peer, vals in samples.items():
            def pct(q):
                idx = max(0, min(len(vals) - 1,
                                 int(round(q / 100.0 * (len(vals) - 1)))))
                return round(vals[idx], 3)
            out[peer] = {"n": len(vals), "p50_ms": pct(50),
                         "p99_ms": pct(99)}
        return out

    def send_retry_counts(self) -> Dict[int, int]:
        """Cumulative redial+resend attempts per destination (the
        DCN_SEND_RETRIES loop) — the gray-failure scorer differences two
        snapshots for a per-window count."""
        with self._retry_lock:
            return dict(self._send_retry_counts)

    # -- frame-integrity recovery (PIPEEDGE_WIRE_CRC) -------------------

    def request_resend(self, src: int, channel: int, seq: int = -1,
                       timeout: float = 5.0) -> None:
        """Ask `src` to replay data frame `seq` (its per-edge sequence
        number, carried in the data-frame aux when PIPEEDGE_WIRE_CRC is
        armed; -1 = the latest cached frame) on `channel` — the consumer
        half of the integrity-recovery path. The reader loop calls this
        automatically on a checksum mismatch; it stays public for
        belt-and-braces consumers (runtime.py's decode handlers).
        Best-effort: the replayed frame arrives as a normal data frame
        on the same recv queue; a cache miss or replay-cap hit on the
        producer means the frame is lost and the round's
        timeout/failover semantics apply. Raises OSError when `src` is
        unreachable."""
        self._cmd_channel_send(src, _MSG_RESEND, int(seq),
                               (np.asarray(channel, np.int32),),
                               timeout=timeout)

    def _resend_last(self, dst: int, channel: int, seq: int = -1) -> bool:
        """Producer half: replay cached frame `seq` (-1 = latest) for
        (dst, channel), at most max(1, send_retries) times per frame.
        Runs on the reader thread: the data-conn lock acquire AND the
        replay send itself are bounded (a backpressured consumer that
        stopped draining its socket forfeits the replay rather than
        wedging this reader)."""
        with self._retry_lock:
            dq = self._last_frames.get((dst, channel))
            entry = None
            if dq:
                if seq < 0:
                    entry = dq[-1]
                else:
                    for e in dq:
                        if e[0] == seq:
                            entry = e
                            break
            if entry is None:
                logger.warning("rank %d: resend request from rank %d "
                               "(channel %d, seq %d) missed the cache "
                               "(PIPEEDGE_WIRE_CRC off, restarted, or "
                               "aged past RESEND_CACHE_DEPTH=%d)",
                               self._rank, dst, channel, seq,
                               self.RESEND_CACHE_DEPTH)
                return False
            cap = max(1, self.send_retries)
            if entry[3] >= cap:
                logger.warning("rank %d: resend cap (%d) hit for rank %d "
                               "channel %d seq %d; frame stays lost",
                               self._rank, cap, dst, channel, entry[0])
                return False
            entry[3] += 1
            frame_seq, msg_type, tensors = entry[0], entry[1], entry[2]
        lock = self._conn_locks[dst]
        if not lock.acquire(timeout=5.0):
            logger.warning("rank %d: resend to rank %d skipped (data "
                           "conn busy)", self._rank, dst)
            return False
        try:
            conn = self._ensure_conn(dst, timeout=5.0)
            conn.settimeout(10.0)
            try:
                # deliberate send under the per-dst conn lock: the same
                # frame-serializer discipline as _send_tensors_once; the
                # socket timeout bounds it (see docstring)
                _send_frame(conn, msg_type, frame_seq, tensors, channel)  # pipelint: disable=PL102
            except OSError:
                with self._conns_lock:
                    if self._conns.get(dst) is conn:
                        del self._conns[dst]
                try:
                    conn.close()
                except OSError:
                    pass
                raise
            finally:
                try:
                    conn.settimeout(None)
                except OSError:
                    pass
        finally:
            lock.release()
        logger.warning("rank %d: replayed frame seq %d to rank %d on "
                       "channel %d (integrity recovery)", self._rank,
                       frame_seq, dst, channel)
        return True

    def start_heartbeat(self, peers: Optional[Sequence[int]] = None,
                        interval: Optional[float] = None,
                        miss_threshold: Optional[int] = None) -> None:
        """Start the liveness plane: every `interval` seconds beat each peer
        over the command connections, and declare any peer dead whose own
        beats stop for `interval * miss_threshold` seconds. A beat-silent
        peer with an OPEN socket is exactly the hung-rank case the stream
        errors cannot catch. Defaults: env DCN_HEARTBEAT_INTERVAL (0 =
        disabled, the default) and DCN_HEARTBEAT_MISS (3). Watching starts
        at a peer's FIRST received beat, so ranks coming up at different
        times are never declared dead by a launch skew."""
        interval = (interval if interval is not None
                    else _env_number(ENV_HEARTBEAT_INTERVAL, 0.0, float))
        if interval <= 0 or self._hb_thread is not None:
            return
        self._hb_interval = float(interval)
        self._hb_miss = int(miss_threshold if miss_threshold is not None
                            else _env_number(ENV_HEARTBEAT_MISS,
                                             DEFAULT_HEARTBEAT_MISS, int))
        self._hb_peers = tuple(p for p in (peers if peers is not None
                                           else range(self._world_size))
                               if p != self._rank)
        self._hb_stop = threading.Event()
        self._hb_dial_backoff = {}
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True,
            name=f"dcn-heartbeat-{self._rank}")
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        """Stop beating and watching (the context stays usable)."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None

    def _heartbeat_loop(self) -> None:
        interval = self._hb_interval
        # a peer that failed to dial is not re-dialed every cycle: serial
        # blocking dials to (say) a SYN-blackholed host would stretch THIS
        # rank's own beat period past other ranks' silence thresholds and
        # get healthy ranks declared dead. One attempt per miss-window.
        dial_backoff = self._hb_dial_backoff
        while not self._stop.is_set() and not self._hb_stop.is_set():
            for dst in self._hb_peers:
                if dst in self._dead or self._hb_stop.is_set():
                    continue
                if self._cmd_conns.get(dst) is None \
                        and time.monotonic() < dial_backoff.get(dst, 0.0):
                    continue
                # bounded lock acquire: a broadcast stuck dialing THIS
                # peer must not stall the beats to every other peer
                lock = self._cmd_conn_locks[dst]
                if not lock.acquire(timeout=min(2.0, interval)):
                    continue
                try:
                    # short per-beat dial budget: a peer that is not up yet
                    # just misses this beat, it does not stall the plane
                    conn = self._ensure_conn(
                        dst, timeout=min(0.5, interval),
                        conns=self._cmd_conns)
                    # sequence-numbered beat: the peer's reader echoes the
                    # seq (_MSG_HEARTBEAT_ACK), turning liveness beats
                    # into RTT probes. Stamp BEFORE the send so kernel
                    # buffering counts toward the measured round trip;
                    # prune probes older than the miss window (a lost ack
                    # must not leak its stamp forever).
                    with self._hb_lock:
                        self._hb_seq += 1
                        seq = self._hb_seq
                        self._hb_rtt_pending[(dst, seq)] = time.monotonic()
                        horizon = (time.monotonic()
                                   - interval * max(1, self._hb_miss))
                        for key in [k for k, t
                                    in self._hb_rtt_pending.items()
                                    if t < horizon]:
                            del self._hb_rtt_pending[key]
                    _send_frame(conn, _MSG_HEARTBEAT, self._rank,
                                (np.asarray(seq, np.int64),))
                    dial_backoff.pop(dst, None)
                except OSError:
                    dial_backoff[dst] = (time.monotonic()
                                         + interval * self._hb_miss)
                    with self._conns_lock:
                        self._cmd_conns.pop(dst, None)
                finally:
                    lock.release()
            now = time.monotonic()
            with self._hb_lock:
                rx = dict(self._hb_last_rx)
            with self._dead_lock:
                alive = dict(self._alive_at)
                # peers in an open grace window are already being handled:
                # re-flagging them every tick would spam death threads and
                # inflate the miss counter (one event, not one per tick)
                dead = set(self._dead) | set(self._pending_death)
            # ANY inbound frame counts as life, not only beats: a rank
            # whose beat thread is starved while it streams data is busy,
            # not hung. Size interval*miss above the worst single-threaded
            # stall a rank can take (model build / jit compile) — see
            # docs/FAULT_TOLERANCE.md.
            silent = [(p, now - max(last, alive.get(p, 0.0)))
                      for p, last in rx.items()
                      if now - max(last, alive.get(p, 0.0))
                      > interval * self._hb_miss and p not in dead]
            for peer, gap in silent:
                # dispatch off-thread: the death handler may block (grace
                # waits, command broadcasts) and beats must keep flowing
                _HEARTBEAT_MISSES.inc(peer=str(peer))
                threading.Thread(
                    target=self._mark_dead,
                    args=(peer, f"missed {self._hb_miss} heartbeats "
                                f"(silent {gap:.1f}s, interval "
                                f"{interval}s)"),
                    daemon=True).start()
            self._hb_stop.wait(interval)

    # -- lifecycle -----------------------------------------------------

    def init(self) -> None:
        # fresh session state so the context is genuinely reusable
        # (base-class contract, comm/__init__.py): the previous session's
        # threads are all joined by shutdown() and hold the old event
        self._stop = threading.Event()
        self._reader_threads = []
        self._recv_queues = {}
        self._neg_replies = {}
        self._span_replies = {}
        self._path_replies = {}
        self._edge_path = {}
        self._dead = set()
        self._alive_at = {}
        self._pending_death = {}
        self._hb_last_rx = {}
        self._hb_dial_backoff = {}
        self._hb_rtt_pending = {}
        self._hb_rtt = {}
        self._send_retry_counts = {}
        self._frame_seq = {}
        self._last_frames = {}
        self._peer_epoch = {}
        self._min_epoch = {}
        self.stale_frames_dropped = 0
        # forget which peers were ever up: a relaunched fleet's listeners
        # get the full rendezvous budget again, not the fast-refusal path
        self._ever_connected = set()
        host, port = self._rank_addrs[self._rank]
        self._listener = socket.create_server((host, port), backlog=8,
                                              reuse_port=False)
        self._listener.settimeout(0.2)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"dcn-accept-{self._rank}")
        self._accept_thread.start()
        # colocated-tier discovery: contexts in one process find each
        # other by listen address (a rank's address is unique fleet-wide)
        with _LOCAL_LOCK:
            _LOCAL_CONTEXTS[tuple(self._rank_addrs[self._rank])] = self
        super().init()

    def shutdown(self) -> None:
        self._stop.set()
        key = tuple(self._rank_addrs[self._rank])
        with _LOCAL_LOCK:
            if _LOCAL_CONTEXTS.get(key) is self:
                del _LOCAL_CONTEXTS[key]
        self.stop_heartbeat()
        with self._dead_lock:
            timers = list(self._pending_death.values())
            self._pending_death.clear()
        for t in timers:
            t.cancel()
        if self._accept_thread is not None:
            self._accept_thread.join()
        with self._conns_lock:
            conns = (list(self._conns.values())
                     + list(self._cmd_conns.values()) + self._accepted)
            self._conns.clear()
            self._cmd_conns.clear()
            self._accepted.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)  # unblock readers immediately
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._listener is not None:
            self._listener.close()
        for t in self._reader_threads:
            t.join(timeout=5)
        super().shutdown()

    # -- incoming ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            _tune_socket(conn)
            with self._conns_lock:
                self._accepted.append(conn)
            t = threading.Thread(target=self._reader_loop, args=(conn,),
                                 daemon=True,
                                 name=f"dcn-reader-{self._rank}")
            t.start()
            self._reader_threads.append(t)

    def _queue_for(self, src: int, channel: int) -> "queue.Queue":
        with self._recv_lock:
            q = self._recv_queues.get((src, channel))
            if q is None:
                q = queue.Queue(maxsize=self.RECV_QUEUE_DEPTH)
                self._recv_queues[(src, channel)] = q
            return q

    def _reader_loop(self, conn: socket.socket) -> None:
        src = -1
        conn_epoch = 0
        warned_stale = False
        # zero-copy tier: one receive-buffer pool per connection (reader
        # threads never share buffers, so the pool needs no lock)
        pool = _RecvBufferPool() if self._recv_pool_on else None
        try:
            msg_type, src, _, hello = _recv_frame(conn)
            if msg_type != _MSG_HELLO:
                logger.error("peer spoke before HELLO; dropping connection")
                return
            # the HELLO's payload carries the sender's incarnation number
            # (absent = 0, the pre-epoch wire layout): every frame on THIS
            # connection belongs to that incarnation
            conn_epoch = int(np.asarray(hello[0]).reshape(-1)[0]) \
                if hello else 0
            with self._dead_lock:
                self._peer_epoch[src] = max(self._peer_epoch.get(src, 0),
                                            conn_epoch)
            with self._conns_lock:
                self._ever_connected.add(src)
            self._alive_sign(src)
            while not self._stop.is_set():
                msg_type, aux, channel, n_tensors = _recv_header(conn)
                # traced data frame: identical to _MSG_TENSORS except the
                # leading uint8 trace-context blob (stripped after the
                # body read, below) — normalize the type here so every
                # data-frame branch (hooks, spans, fences, queues) stays
                # one code path
                traced = msg_type == _MSG_TENSORS_TRACED
                if traced:
                    msg_type = _MSG_TENSORS
                # epoch fence: a frame from an incarnation that has since
                # been fenced (confirmed dead, or superseded by a newer
                # JOIN) must never reach queues, handlers, or the ledger.
                # The payload is still drained (stream framing), then
                # dropped — with no hooks, no life sign, no beat credit:
                # a zombie must not keep its own death window open.
                with self._dead_lock:
                    stale = conn_epoch < self._min_epoch.get(src, 0)
                if stale:
                    _recv_body(conn, n_tensors, pool)
                    self.stale_frames_dropped += 1
                    _STALE_FRAMES.inc(peer=str(src))
                    # one WARNING per connection, debug thereafter: a
                    # zombie that keeps streaming would otherwise flood
                    # the logs for the rest of the run (the counter
                    # carries the ongoing signal)
                    log = logger.debug if warned_stale else logger.warning
                    warned_stale = True
                    log("rank %d: dropping stale frame(s) (type %d) from "
                        "rank %d epoch %d (fence %d)", self._rank,
                        msg_type, src, conn_epoch, self.min_epoch_of(src))
                    continue
                # "any inbound frame counts as life" — EXCEPT the
                # heartbeat-RTT echo: an ack proves only that the peer's
                # reader thread can write a socket (it is generated in
                # response to OUR probe). Crediting it would keep a
                # partially-hung peer — beat loop wedged, reader alive —
                # alive forever, defeating beat-silence detection.
                if msg_type != _MSG_HEARTBEAT_ACK:
                    self._alive_sign(src)
                hooked = (msg_type == _MSG_TENSORS
                          and self._recv_pre_hook is not None)
                if hooked:
                    self._recv_pre_hook(src, channel)
                # wire-recv span: header seen -> payload fully read, i.e.
                # actual transfer time, not idle time (zero-cost when span
                # recording is off)
                t_rx0 = (time.monotonic_ns()
                         if msg_type == _MSG_TENSORS and telemetry.enabled()
                         else 0)
                try:
                    tensors = _recv_body(conn, n_tensors, pool)
                except Exception:
                    # abort notification: a paired measurement started by the
                    # pre hook must be discarded, or this (recyclable) thread
                    # ident leaks a dangling iteration context
                    if hooked and self._recv_post_hook is not None:
                        self._recv_post_hook(src, channel, None)
                    raise
                tctx = None
                if traced:
                    # strip the leading trace-context blob; decode failure
                    # (truncated/garbage) degrades to untraced — the
                    # payload tensors are intact either way
                    tctx = telemetry.TraceContext.from_wire(tensors[0]) \
                        if tensors else None
                    tensors = tensors[1:]
                    if tctx is None:
                        _TRACE_INVALID.inc()
                    else:
                        _TRACED_FRAMES.inc(peer=str(src))
                if t_rx0:
                    telemetry.record("wire", f"recv<-r{src}", t_rx0,
                                     time.monotonic_ns(),
                                     rid=tctx.rid if tctx else None)
                if msg_type == _MSG_TENSORS and self._recv_post_hook is not None:
                    self._recv_post_hook(src, channel, tensors)
                if msg_type == _MSG_TENSORS and self._wire_crc and tensors:
                    # frame integrity: verify CRC-flagged frames HERE,
                    # where src, channel AND the producer's frame seq
                    # (aux) are all known — a corrupt frame is dropped
                    # (never enqueued, so consumers only ever see clean
                    # frames) and its EXACT seq is requested back. The
                    # request rides the bounded try-send: recovery must
                    # never wedge this reader.
                    idx = wire_codec.locate_crc_header(tensors)
                    if idx is not None:
                        try:
                            wire_codec.verify_frame(tensors[idx + 1:-1],
                                                    tensors[-1])
                        except wire_codec.WireCorruptError as exc:
                            FRAMES_CORRUPT.inc(peer=str(src))
                            logger.error(
                                "rank %d: corrupt frame from rank %d "
                                "(channel %d, seq %d): %s; requesting "
                                "resend", self._rank, src, channel, aux,
                                exc)
                            self._try_cmd_send(
                                src, _MSG_RESEND, aux,
                                (np.asarray(channel, np.int32),))
                            continue
                if msg_type == _MSG_TENSORS:
                    # blocks when the consumer is behind: TCP backpressure
                    # propagates the stall to the sender (reference
                    # p2p:252-257 semantics); re-check _stop so shutdown
                    # can't leave this thread parked on a full queue forever.
                    # Items carry the sending incarnation's epoch so
                    # `recv_tensors_meta` consumers (the failover ledger)
                    # can key their dedupe on it.
                    q = self._queue_for(src, channel)
                    while not self._stop.is_set():
                        try:
                            q.put((conn_epoch, tensors, tctx), timeout=0.2)
                            break
                        except queue.Full:
                            continue
                elif msg_type == _MSG_CMD:
                    if self._cmd_handler is not None:
                        self._cmd_handler(aux, tuple(tensors))
                elif msg_type == _MSG_NEG:
                    # answer the bitwidth proposal inline: transport-level
                    # handshake, no app handler required
                    try:
                        self._send_neg(src, _MSG_NEG_ACK,
                                       self._accept_edge_bit(aux))
                    except OSError as exc:
                        logger.warning("rank %d: bitwidth-handshake reply to "
                                       "rank %d failed: %s", self._rank, src,
                                       exc)
                elif msg_type == _MSG_NEG_ACK:
                    self._neg_queue(src).put(aux)
                elif msg_type == _MSG_PATH:
                    # transport-tier proposal: answered inline like the
                    # bitwidth handshake (no app wiring)
                    try:
                        self._send_neg(src, _MSG_PATH_ACK,
                                       self._accept_edge_path(src, aux))
                    except OSError as exc:
                        logger.warning("rank %d: path-handshake reply to "
                                       "rank %d failed: %s", self._rank,
                                       src, exc)
                elif msg_type == _MSG_PATH_ACK:
                    self._path_queue(src).put(aux)
                elif msg_type == _MSG_SPANS:
                    # answer inline (transport-level, like _MSG_NEG): the
                    # requester's clock probe needs t_rx stamped NOW
                    try:
                        self._reply_spans(src, aux, time.monotonic_ns())
                    except OSError as exc:
                        logger.warning("rank %d: span-collection reply to "
                                       "rank %d failed: %s", self._rank,
                                       src, exc)
                elif msg_type == _MSG_SPANS_ACK:
                    self._span_queue(src).put((aux, tensors))
                elif msg_type == _MSG_HEARTBEAT:
                    with self._hb_lock:
                        self._hb_last_rx[aux] = time.monotonic()
                    if self._hb_hook is not None:
                        self._hb_hook(aux)
                    if tensors:
                        # sequence-numbered beat: echo the seq so the
                        # sender measures this command plane's RTT.
                        # BOUNDED send (lock + dial budgets): a busy cmd
                        # conn or unreachable peer just loses this one
                        # probe — it must never wedge this reader (a
                        # wedged reader stops crediting the peer's DATA
                        # frames as life signs and falsely kills it).
                        seq = int(np.asarray(tensors[0]).reshape(-1)[0])
                        if not self._try_cmd_send(src, _MSG_HEARTBEAT_ACK,
                                                  seq):
                            logger.debug("rank %d: heartbeat-RTT echo to "
                                         "rank %d skipped", self._rank,
                                         src)
                elif msg_type == _MSG_HEARTBEAT_ACK:
                    # our own probe coming home (aux = echoed seq)
                    now = time.monotonic()
                    rtt_ms = None
                    with self._hb_lock:
                        t0 = self._hb_rtt_pending.pop((src, aux), None)
                        if t0 is not None:
                            rtt_ms = (now - t0) * 1e3
                            dq = self._hb_rtt.get(src)
                            if dq is None:
                                dq = self._hb_rtt[src] = deque(maxlen=512)
                            dq.append(rtt_ms)
                    if rtt_ms is not None \
                            and self._hb_rtt_hook is not None:
                        self._hb_rtt_hook(src, rtt_ms)
                elif msg_type == _MSG_RESEND:
                    # frame-integrity recovery: replay the cached clean
                    # frame for (requester, channel=payload, seq=aux) —
                    # bounded, best-effort (see _MSG_RESEND's comment)
                    ch = (int(np.asarray(tensors[0]).reshape(-1)[0])
                          if tensors else 0)
                    try:
                        self._resend_last(src, ch, aux)
                    except OSError as exc:
                        logger.warning("rank %d: resend to rank %d "
                                       "(channel %d, seq %d) failed: %s",
                                       self._rank, src, ch, aux, exc)
                elif msg_type == _MSG_JOIN:
                    # admission handshake (aux = joiner's claimed epoch):
                    # a JOIN always rides a NEW connection from the new
                    # incarnation, so its epoch should match conn_epoch —
                    # trust the HELLO (what fencing keys on) when they
                    # disagree
                    admitted = self._admit_peer(src, conn_epoch)
                    try:
                        self._send_neg(src, _MSG_JOIN_ACK,
                                       self.epoch if admitted else -1)
                    except OSError as exc:
                        logger.warning("rank %d: JOIN ack to rank %d "
                                       "failed: %s", self._rank, src, exc)
                elif msg_type == _MSG_JOIN_ACK:
                    if aux < 0:
                        logger.error("rank %d: rank %d REFUSED this "
                                     "rank's JOIN (epoch %d is fenced "
                                     "there)", self._rank, src, self.epoch)
                    else:
                        with self._dead_lock:
                            self._peer_epoch[src] = max(
                                self._peer_epoch.get(src, 0), aux)
                else:
                    logger.error("unknown frame type %d from rank %d",
                                 msg_type, src)
        except (ConnectionError, OSError) as exc:
            if not self._stop.is_set():
                # a FENCED incarnation's connection dropping is not news:
                # the zombie finally exiting must not re-kill a rank whose
                # new incarnation has since been admitted
                with self._dead_lock:
                    fenced = (src >= 0
                              and conn_epoch < self._min_epoch.get(src, 0))
                if fenced:
                    logger.info("fenced connection from rank %d (epoch %d) "
                                "dropped: %s", src, conn_epoch, exc)
                else:
                    logger.warning("connection from rank %d dropped: %s",
                                   src, exc)
                    self._mark_dead(src)
        finally:
            conn.close()

    # -- outgoing ------------------------------------------------------

    def _ensure_conn(self, dst: int, timeout: Optional[float] = None,
                     conns: Optional[Dict[int, socket.socket]] = None) \
            -> socket.socket:
        """Dial `dst` lazily into `conns` (default: the data-conn map);
        caller must hold the matching per-dst lock. Retries refused
        connections until the deadline (CONNECT_TIMEOUT default) so
        simultaneously-launched ranks can dial peers whose listeners aren't
        up yet (the role of the reference's process-group rendezvous,
        p2p:62).

        Fast peer-death path: once a peer has EVER been dialed
        successfully, fresh connection-REFUSED errors mean its listener is
        gone (the process died — restarts rebind within ~1 s), so the
        retry loop gives up after a short grace instead of burning the
        full startup budget. This is what bounds fleet abort latency when
        a rank dies before data flows (test_peer_death_aborts_fleet)."""
        if conns is None:
            conns = self._conns
        conn = conns.get(dst)
        if conn is not None:
            return conn
        host, port = self._rank_addrs[dst]
        deadline = time.monotonic() + (self.CONNECT_TIMEOUT
                                       if timeout is None else timeout)
        was_up = dst in self._ever_connected
        refused_since = None
        while True:
            try:
                # per-attempt timeout clamped to the remaining budget, so a
                # SYN-blackholed peer can't overrun the caller's deadline
                attempt = min(5.0, max(0.1, deadline - time.monotonic()))
                conn = socket.create_connection((host, port), timeout=attempt)
                break
            except OSError as exc:
                if self._stop.is_set() or time.monotonic() >= deadline:
                    raise
                if was_up and isinstance(exc, ConnectionRefusedError):
                    now = time.monotonic()
                    refused_since = refused_since or now
                    if now - refused_since > 2.0:
                        raise   # listener stayed gone: the peer is dead
                else:
                    refused_since = None
                time.sleep(0.2)
        conn.settimeout(None)
        _tune_socket(conn)
        # HELLO carries this incarnation's epoch so the receiver can fence
        # stale frames per connection (readers without the payload read 0)
        _send_frame(conn, _MSG_HELLO, self._rank,
                    (np.asarray(self.epoch, np.int64),))
        with self._conns_lock:
            conns[dst] = conn
            self._ever_connected.add(dst)
        self._alive_sign(dst)   # a successful dial revives a grace window
        return conn

    def send_tensors(self, dst: int, tensors: Sequence[np.ndarray],
                     channel: int = CHANNEL_DATA,
                     trace: Optional["telemetry.TraceContext"] = None) \
            -> None:
        """Send a tensor list to `dst` (reference _send_tensor, p2p:96-108).

        `trace` (a telemetry.TraceContext) rides the frame as an optional
        leading uint8 blob (`_MSG_TENSORS_TRACED`): the consumer's stage
        and wire spans inherit its request id. None sends the plain (and
        byte-identical to pre-tracing) `_MSG_TENSORS` frame — untraced
        runs pay zero wire bytes for the feature.

        With `send_retries` > 0 (env DCN_SEND_RETRIES), a broken connection
        is redialed and the WHOLE frame resent, with exponential backoff —
        transient network faults and in-grace peer restarts heal instead of
        killing the edge. The receiver discards a torn partial frame with
        its dropped connection, so a resend can duplicate a frame but never
        corrupt one; consumers that must be exactly-once dedupe at the
        application layer (runtime.py's microbatch-id ledger).

        When `negotiate_edge_path` agreed the COLOCATED tier for `dst`,
        the frame skips the socket entirely: tensors (host or device
        arrays) hand off through the in-process peer's recv queue with
        the framing as metadata. A peer that left the process meanwhile
        (clean shutdown) degrades back to the socket path."""
        if self._edge_path.get(dst) == PATH_LOCAL:
            peer = self._local_peer(dst)
            if peer is not None:
                try:
                    self._deliver_local(peer, dst, tensors, channel,
                                        trace=trace)
                    return
                except (ConnectionError, OSError):
                    self._mark_dead(dst)
                    raise
            # grant went stale (peer context gone): socket truth resumes
            self._edge_path.pop(dst, None)
        attempts = 1 + max(0, self.send_retries)
        for attempt in range(attempts):
            try:
                self._send_tensors_once(dst, tensors, channel, trace=trace)
                return
            except OSError as exc:
                if attempt + 1 >= attempts or self._stop.is_set():
                    # notify AFTER releasing the conn lock: the death
                    # handler may broadcast commands, which needs these
                    # locks (deadlock otherwise)
                    self._mark_dead(dst)
                    raise
                # gray-failure signal: a link that needs redials is
                # degrading even when every retry eventually succeeds
                with self._retry_lock:
                    self._send_retry_counts[dst] = \
                        self._send_retry_counts.get(dst, 0) + 1
                _SEND_RETRIES_TOTAL.inc(peer=str(dst))
                backoff = min(2.0, 0.2 * (2 ** attempt))
                logger.warning(
                    "rank %d: send to rank %d failed (%s); retry %d/%d "
                    "in %.1fs", self._rank, dst, exc, attempt + 1,
                    attempts - 1, backoff)
                time.sleep(backoff)

    def _send_tensors_once(self, dst: int, tensors: Sequence[np.ndarray],
                           channel: int,
                           trace: Optional["telemetry.TraceContext"] = None
                           ) -> None:
        # wire frame vs hook payload kept separate: the recv side strips
        # the blob BEFORE its hooks fire, so the send hooks must count
        # the same (payload-only) tensors or the per-edge send/recv byte
        # accounting would permanently diverge on traced edges
        msg_type = _MSG_TENSORS
        wire_tensors = tensors
        if trace is not None:
            wire_tensors = [trace.to_wire()] + list(tensors)
            msg_type = _MSG_TENSORS_TRACED
        # chaos corrupt@K: flip one bit in a COPY, below the integrity
        # layer — the resend cache (and any frame checksum, computed by
        # the caller's PendingWire.finalize) keeps the clean bytes, so a
        # consumer-requested resend genuinely recovers the frame
        frame_tensors = wire_tensors
        if self._corrupt_next_send:
            self._corrupt_next_send = False
            frame_tensors = _flip_one_bit(wire_tensors)
        # frame integrity: with PIPEEDGE_WIRE_CRC armed, CRC-FLAGGED
        # frames carry a per-(dst, channel) sequence number in the aux
        # field instead of the (reader-unused) sender rank, so a
        # consumer can address a corrupt frame's resend EXACTLY —
        # pipelined sends mean "the last frame" may already be a later
        # one. Unflagged frames (raw feed microbatches, v1) are neither
        # stamped nor cached: the receiver can never verify them, so
        # caching would only pin dead copies of large inputs per edge.
        aux = self._rank
        seq = None
        if self._wire_crc \
                and wire_codec.locate_crc_header(wire_tensors) is not None:
            with self._retry_lock:
                seq = self._frame_seq.get((dst, channel), 0) + 1
                self._frame_seq[(dst, channel)] = seq
            aux = seq
        with self._conn_locks[dst]:
            conn = self._ensure_conn(dst)
            if self._send_pre_hook is not None:
                self._send_pre_hook(dst, channel)
            t_tx0 = time.monotonic_ns() if telemetry.enabled() else 0
            try:
                _send_frame(conn, msg_type, aux, frame_tensors,
                            channel)
            except Exception as exc:
                if self._send_pre_hook is not None \
                        and self._send_post_hook is not None:
                    self._send_post_hook(dst, channel, None)  # abort
                if isinstance(exc, OSError):
                    # broken pipe / reset: the peer is gone; drop the
                    # conn so state stays clean
                    with self._conns_lock:
                        if self._conns.get(dst) is conn:
                            del self._conns[dst]
                raise
            if t_tx0:
                telemetry.record("wire", f"send->r{dst}", t_tx0,
                                 time.monotonic_ns(),
                                 rid=trace.rid if trace else None)
            if self._send_post_hook is not None:
                self._send_post_hook(dst, channel, tensors)
        if seq is not None:
            # frame-integrity resend cache: the last RESEND_CACHE_DEPTH
            # CLEAN CRC-flagged frames per edge-channel, seq-addressed
            # (memory is bounded at a few in-flight microbatches per
            # edge), each with its own replay count
            with self._retry_lock:
                dq = self._last_frames.get((dst, channel))
                if dq is None:
                    dq = self._last_frames[(dst, channel)] = deque(
                        maxlen=self.RESEND_CACHE_DEPTH)
                dq.append([seq, msg_type, wire_tensors, 0])

    def recv_tensors(self, src: int, timeout: Optional[float] = None,
                     channel: int = CHANNEL_DATA) -> List[np.ndarray]:
        """Receive the next tensor list from `src` (p2p:111-121). Raises
        queue.Empty on timeout, ConnectionError if `src`'s connection died
        and no frames remain (already-delivered frames drain first)."""
        return self.recv_tensors_meta(src, timeout=timeout,
                                      channel=channel)[0]

    def recv_tensors_meta(self, src: int, timeout: Optional[float] = None,
                          channel: int = CHANNEL_DATA) \
            -> Tuple[List[np.ndarray], int]:
        """`recv_tensors` plus the sending incarnation's epoch:
        `(tensors, epoch)`. What the failover ledger keys its epoch-aware
        dedupe on (stale incarnations are already fenced at the reader;
        the epoch here is forensic + belt-and-braces)."""
        tensors, epoch, _ = self.recv_tensors_traced(src, timeout=timeout,
                                                     channel=channel)
        return tensors, epoch

    def recv_tensors_traced(self, src: int,
                            timeout: Optional[float] = None,
                            channel: int = CHANNEL_DATA) \
            -> Tuple[List[np.ndarray], int,
                     Optional["telemetry.TraceContext"]]:
        """`recv_tensors_meta` plus the frame's trace context
        `(tensors, epoch, trace)` — None for a plain (untraced) frame or
        an undecodable blob. What the DCN stage workers pull so their
        spans inherit the producing request's id."""
        q = self._queue_for(src, channel)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                epoch, tensors, tctx = q.get(
                    timeout=0.2 if deadline is None
                    else max(0.0, min(0.2, deadline - time.monotonic())))
                return tensors, epoch, tctx
            except queue.Empty:
                with self._dead_lock:
                    dead = src in self._dead
                if dead and q.empty():
                    raise ConnectionError(
                        f"rank {src} died (connection lost)") from None
                if deadline is not None and time.monotonic() >= deadline:
                    raise

    def cmd_broadcast(self, cmd: int, tensors: Sequence[np.ndarray] = (),
                      best_effort: Optional[bool] = None,
                      exclude: Optional[Sequence[int]] = None) -> None:
        """Send a command frame to every other rank (p2p:72-85).

        Delivery policy: commands the fleet can survive missing (CMD_STOP —
        receivers also have their own timeouts) are best-effort with a short
        dial deadline, so one dead rank never stalls the broadcast. Every
        other command (CMD_SCHED especially) retries dialing each peer until
        the full CONNECT_TIMEOUT: a worker whose listener comes up seconds
        after the data rank broadcasts must still receive the schedule — the
        delivery guarantee the reference gets for free from its
        init_process_group rendezvous (p2p:62).

        Peers in `exclude` and peers this context has CONFIRMED dead are
        skipped outright (never counted as failures): a failover CMD_SCHED
        must reach every survivor without stalling on — or aborting over —
        the rank whose death triggered it."""
        if best_effort is None:
            best_effort = cmd == CMD_STOP
        skip = set(exclude or ())
        with self._dead_lock:
            skip |= self._dead
        # One deadline shared across the whole broadcast: several dead peers
        # cost at most ~CONNECT_TIMEOUT total, not CONNECT_TIMEOUT each
        # (already-connected and live peers dial in milliseconds regardless
        # of their position in the loop).
        deadline = time.monotonic() + (5.0 if best_effort
                                       else self.CONNECT_TIMEOUT)
        failures = []
        for dst in range(self._world_size):
            if dst == self._rank:
                continue
            if dst in skip:
                logger.debug("cmd_broadcast: skipping rank %d (dead/"
                             "excluded)", dst)
                continue
            try:
                # dedicated command connections: never blocked behind a
                # backpressured data send to the same peer
                with self._cmd_conn_locks[dst]:
                    remaining = max(1.0, deadline - time.monotonic())
                    conn = self._ensure_conn(dst, timeout=remaining,
                                             conns=self._cmd_conns)
                    try:
                        _send_frame(conn, _MSG_CMD, cmd, tensors)
                    except OSError:
                        # the CACHED connection went stale (the peer
                        # flapped or restarted inside its grace window):
                        # one fresh redial before declaring the peer
                        # unreachable — it is alive, only the old socket
                        # is dead
                        with self._conns_lock:
                            if self._cmd_conns.get(dst) is conn:
                                del self._cmd_conns[dst]
                        remaining = max(1.0, deadline - time.monotonic())
                        conn = self._ensure_conn(dst, timeout=remaining,
                                                 conns=self._cmd_conns)
                        _send_frame(conn, _MSG_CMD, cmd, tensors)
            except OSError as exc:
                # keep delivering to the remaining reachable peers either
                # way; drop the broken conn so a later broadcast redials
                with self._conns_lock:
                    self._cmd_conns.pop(dst, None)
                failures.append((dst, exc))
                logger.warning("cmd_broadcast: rank %d unreachable (%s); "
                               "skipping", dst, exc)
        if failures and not best_effort:
            raise ConnectionError(
                f"cmd_broadcast(cmd={cmd}): undeliverable to rank(s) "
                + ", ".join(f"{d} ({e})" for d, e in failures))

    # -- per-edge bitwidth negotiation ---------------------------------

    def _neg_queue(self, peer: int) -> "queue.Queue":
        with self._neg_lock:
            q = self._neg_replies.get(peer)
            if q is None:
                q = queue.Queue()
                self._neg_replies[peer] = q
            return q

    def _accept_edge_bit(self, proposed: int) -> int:
        """Receiver policy: the proposal when supported, else the widest
        supported bitwidth below it (0 = uncompressed, always legal)."""
        if proposed in self._edge_bits:
            return proposed
        lower = [b for b in self._edge_bits if 0 < b < proposed]
        return max(lower) if lower else 0

    def _send_neg(self, dst: int, msg_type: int, bit: int) -> None:
        # rides the dedicated command connections: a proposal must never
        # queue behind a backpressured data send to the same peer
        self._cmd_channel_send(dst, msg_type, bit)

    def negotiate_edge_bits(self, dst: int, proposed: int,
                            timeout: Optional[float] = 30.0) -> int:
        """Agree an edge bitwidth with the consuming rank over the control
        channel: propose `proposed`, get back what `dst` accepts (its
        `edge_bits_supported` policy — the proposal itself, or the widest
        supported bitwidth below it, or 0 for uncompressed). Run once per
        edge before streaming; the per-frame wire header still carries the
        actual bitwidth, so adaptive policies may later move WITHIN the
        agreed capability. Raises queue.Empty on timeout and OSError when
        `dst` is unreachable. One in-flight negotiation per peer."""
        q = self._neg_queue(dst)
        while True:  # drop stale replies from an abandoned negotiation
            try:
                q.get_nowait()
            except queue.Empty:
                break
        self._send_neg(dst, _MSG_NEG, int(proposed))
        return int(q.get(timeout=timeout))

    # -- tiered transport (colocated / zero-copy / legacy v2) ----------

    def _path_queue(self, peer: int) -> "queue.Queue":
        with self._neg_lock:
            q = self._path_replies.get(peer)
            if q is None:
                q = queue.Queue()
                self._path_replies[peer] = q
            return q

    def _local_peer(self, rank: int) -> Optional["DistDcnContext"]:
        """The live context serving `rank` IN THIS PROCESS, or None. The
        registry is keyed by listen address, so the check is also proof
        both ends share an address space — the colocated tier's only
        requirement."""
        if not 0 <= rank < self._world_size:
            return None
        with _LOCAL_LOCK:
            peer = _LOCAL_CONTEXTS.get(tuple(self._rank_addrs[rank]))
        if peer is None or peer._rank != rank or peer._stop.is_set():
            return None
        return peer

    def _accept_edge_path(self, src: int, proposed_code: int) -> int:
        """Receiver policy for a `_MSG_PATH` proposal: the colocated tier
        when the proposer's context is registered in this process (and
        both sides enable it), else zero-copy when this context pools its
        receive buffers, else legacy v2."""
        if proposed_code >= PATH_CODES[PATH_LOCAL] and self._local_on \
                and self._local_peer(src) is not None:
            return PATH_CODES[PATH_LOCAL]
        if self._recv_pool_on:
            return PATH_CODES[PATH_ZEROCOPY]
        return PATH_CODES[PATH_SOCKET_V2]

    def negotiate_edge_path(self, dst: int,
                            timeout: Optional[float] = 30.0) -> str:
        """Agree this edge's transport tier with the consuming rank over
        the control channel (the `negotiate_edge_bits` idiom): propose the
        cheapest tier this side supports, get back what `dst` serves.
        PATH_LOCAL switches `send_tensors(dst, ...)` to the in-process
        device-buffer hand-off; the socket tiers are receiver-local
        behavior and the answer is informational (telemetry records it
        either way). Run once per edge before streaming — the runtime
        renegotiates at every round build, so failover targets and
        restarted incarnations never ride a stale grant. Raises
        queue.Empty on timeout and OSError when `dst` is unreachable."""
        proposed = (PATH_CODES[PATH_LOCAL]
                    if self._local_on and self._local_peer(dst) is not None
                    else PATH_CODES[PATH_ZEROCOPY])
        q = self._path_queue(dst)
        while True:  # drop stale replies from an abandoned negotiation
            try:
                q.get_nowait()
            except queue.Empty:
                break
        self._send_neg(dst, _MSG_PATH, proposed)
        code = int(q.get(timeout=timeout))
        tier = _PATH_BY_CODE.get(code, PATH_SOCKET_V2)
        if tier == PATH_LOCAL and (not self._local_on
                                   or self._local_peer(dst) is None):
            # the grant outlived the peer's registration (or this side
            # disabled the tier): degrade to the socket truth
            tier = (PATH_ZEROCOPY if self._recv_pool_on
                    else PATH_SOCKET_V2)
        self._edge_path[dst] = tier
        # per-tier telemetry marker: trace_report's transport section
        # counts edges per tier from these instants
        now = time.monotonic_ns()
        telemetry.record("transport", f"{tier}:{self._rank}->{dst}",
                         now, now)
        logger.info("rank %d: edge ->%d rides the %s path", self._rank,
                    dst, tier)
        return tier

    def edge_path(self, dst: int) -> Optional[str]:
        """The tier `negotiate_edge_path` agreed for sends to `dst`
        (None = never negotiated: the legacy socket path)."""
        return self._edge_path.get(dst)

    def set_local_device(self, device) -> None:
        """Device colocated hand-offs INTO this context should land on:
        a producer's device buffers are moved device-to-device (ICI /
        DMA via `jax.device_put`, never through the host) before they
        reach this rank's recv queue. None (default) hands buffers off
        wherever they already live."""
        self._local_device = device

    def _deliver_local(self, peer: "DistDcnContext", dst: int,
                       tensors: Sequence, channel: int,
                       trace: Optional["telemetry.TraceContext"] = None
                       ) -> None:
        """Colocated-tier send: hand `tensors` (host OR device arrays)
        straight to `peer`'s bounded recv queue. Framing travels as
        metadata (src rank, sender epoch, channel, trace context); the
        send/recv monitor hooks and telemetry fire exactly like the
        socket path's."""
        if self._send_pre_hook is not None:
            self._send_pre_hook(dst, channel)
        t0 = time.monotonic_ns() if telemetry.enabled() else 0
        try:
            peer._local_put(self._rank, self.epoch, list(tensors), channel,
                            trace=trace)
        except Exception:
            if self._send_pre_hook is not None \
                    and self._send_post_hook is not None:
                self._send_post_hook(dst, channel, None)  # abort
            raise
        if t0:
            telemetry.record("wire", f"local->r{dst}", t0,
                             time.monotonic_ns(),
                             rid=trace.rid if trace else None)
        if self._send_post_hook is not None:
            self._send_post_hook(dst, channel, tensors)

    def _local_put(self, src: int, epoch: int, tensors: List,
                   channel: int,
                   trace: Optional["telemetry.TraceContext"] = None
                   ) -> None:
        """Receiver half of the colocated hand-off: the reader loop's
        contract (epoch fence, life sign, recv hooks, bounded queue
        backpressure) without a socket in between. Runs on the SENDER's
        thread; blocking on a full queue is this tier's backpressure."""
        with self._dead_lock:
            self._peer_epoch[src] = max(self._peer_epoch.get(src, 0), epoch)
            stale = epoch < self._min_epoch.get(src, 0)
        if stale:
            # same fencing as the socket reader: a zombie incarnation's
            # hand-off must never reach queues — and earns no life sign
            self.stale_frames_dropped += 1
            _STALE_FRAMES.inc(peer=str(src))
            logger.warning("rank %d: dropping stale local hand-off from "
                           "rank %d epoch %d (fence %d)", self._rank, src,
                           epoch, self.min_epoch_of(src))
            return
        self._alive_sign(src)
        if self._local_device is not None:
            tensors = _put_on_device(tensors, self._local_device)
        if self._recv_pre_hook is not None:
            self._recv_pre_hook(src, channel)
        if self._recv_post_hook is not None:
            self._recv_post_hook(src, channel, tensors)
        if trace is not None:
            _TRACED_FRAMES.inc(peer=str(src))
        q = self._queue_for(src, channel)
        while not self._stop.is_set():
            try:
                q.put((epoch, tensors, trace), timeout=0.2)
                return
            except queue.Full:
                continue
        raise ConnectionError(f"rank {self._rank} stopped; local hand-off "
                              f"from rank {src} refused")

    # -- fleet span collection (telemetry) -----------------------------

    def _span_queue(self, peer: int) -> "queue.Queue":
        with self._span_lock:
            q = self._span_replies.get(peer)
            if q is None:
                q = queue.Queue()
                self._span_replies[peer] = q
            return q

    def _reply_spans(self, dst: int, aux: int, t_rx_ns: int) -> None:
        """Answer a `_MSG_SPANS` request from `dst`: [t_rx, t_tx] receiver
        timestamps plus (full requests only) this rank's span ring as a
        uint8 JSON blob. Runs on the reader thread; the blob is built
        BEFORE t_tx is stamped so serialization time never skews the
        clock-probe math."""
        blob = np.zeros(0, np.uint8)
        if aux != _SPANS_PROBE:
            rec = telemetry.recorder()
            if rec is not None:
                blob = (telemetry.digest_to_wire(rec.digest())
                        if aux == _SPANS_DIGEST
                        else telemetry.spans_to_wire(rec.snapshot()))
        with self._cmd_conn_locks[dst]:
            conn = self._ensure_conn(dst, conns=self._cmd_conns)
            stamp = np.asarray([t_rx_ns, time.monotonic_ns()], np.int64)
            try:
                _send_frame(conn, _MSG_SPANS_ACK, aux, (stamp, blob))
            except OSError:
                with self._conns_lock:
                    if self._cmd_conns.get(dst) is conn:
                        del self._cmd_conns[dst]
                raise

    def collect_spans(self, dst: int, probes: int = 3,
                      timeout: float = 5.0):
        """Fetch `dst`'s span ring over the command channel and estimate
        its clock offset NTP-style from the same exchanges.

        Runs `probes` timestamp-only round trips plus one full request;
        the minimum-RTT sample gives the offset (telemetry.
        estimate_clock_offset). Returns `(spans, offset_ns)` with
        `offset_ns = peer_clock - local_clock` — shift the peer's spans
        onto this rank's timeline with `telemetry.align_spans`. Raises
        queue.Empty on timeout and OSError when `dst` is unreachable; one
        in-flight collection per peer (same discipline as
        `negotiate_edge_bits`)."""
        q = self._span_queue(dst)
        while True:  # drop stale replies from an abandoned collection
            try:
                q.get_nowait()
            except queue.Empty:
                break
        samples = []
        blob = None
        for i in range(max(0, probes) + 1):
            aux = _SPANS_PROBE if i < probes else _SPANS_REQUEST
            t0 = time.monotonic_ns()
            self._send_neg(dst, _MSG_SPANS, aux)
            _, tensors = q.get(timeout=timeout)
            t3 = time.monotonic_ns()
            stamp = np.asarray(tensors[0], np.int64).reshape(-1)
            samples.append((t0, int(stamp[0]), int(stamp[1]), t3))
            if aux == _SPANS_REQUEST:
                blob = tensors[1]
        offset = telemetry.estimate_clock_offset(samples)
        return telemetry.spans_from_wire(blob), offset

    def collect_digest(self, dst: int, timeout: float = 5.0):
        """Fetch `dst`'s cumulative span digest over the command channel:
        the lightweight per-round rebalance collection (telemetry.Digest,
        durations only — no clock probes, no full trace). Empty dict when
        the peer records no spans. Raises queue.Empty on timeout and
        OSError when `dst` is unreachable; one in-flight collection per
        peer (shared reply queue with `collect_spans`)."""
        q = self._span_queue(dst)
        while True:  # drop stale replies from an abandoned collection
            try:
                q.get_nowait()
            except queue.Empty:
                break
        self._send_neg(dst, _MSG_SPANS, _SPANS_DIGEST)
        deadline = time.monotonic() + timeout
        while True:
            aux, tensors = q.get(timeout=max(0.0, deadline
                                             - time.monotonic()))
            if aux == _SPANS_DIGEST:
                return telemetry.digest_from_wire(tensors[1])
            # a late reply from a previously timed-out collect_spans probe
            # (different aux): discard, keep waiting for OUR reply


class DcnPipelineStage:
    """One pipeline stage over the DCN transport: recv -> work -> send on
    background threads with bounded hand-off queues (the reference's
    `DistP2pPipelineStage` role, p2p:334-450).

    Two work contracts:

    - `work_cb(tensors) -> tensors`: the legacy single-phase form — the
      whole recv->compute->readback runs on the work thread.
    - `dispatch_cb(tensors) -> handle` + `readback_cb(handle) -> tensors`:
      the overlapped form. `dispatch_cb` runs on the work thread and must
      NOT block on device results (enqueue the jitted shard step, start
      the async device->host copies — wire.wire_encode_device — and
      return a handle immediately); `readback_cb` runs on the SEND thread
      and completes the handle into the wire tensor list. With `depth` >=
      2 the work thread dispatches microbatch i+1's compute while the
      send thread drains microbatch i's readback — compute, D2H copy and
      socket send overlap instead of serializing. FIFO order is preserved
      (single work thread, single send thread, FIFO queues).

    `depth` sizes both hand-off queues (default env DCN_STAGE_DEPTH or 2;
    the pre-overlap behavior was a hardcoded 1). Ranks outside the
    schedule pass rank_src=rank_dst=None with no callback and idle
    (reference model_cfg.py:154-159).
    """

    _SENTINEL = object()
    # dispatch_cb return value meaning "drop this item": nothing is
    # enqueued for readback/send and the stage-local sequence counter
    # does not advance — the recovery path for a corrupt inbound frame
    # whose resend will re-enter the recv loop as a fresh item
    SKIP = object()

    def __init__(self, ctx: DistDcnContext, rank_src: Optional[int],
                 rank_dst: Optional[int],
                 work_cb: Optional[
                     Callable[[List[np.ndarray]], List[np.ndarray]]] = None,
                 results_cb: Optional[Callable] = None,
                 recv_channel: int = CHANNEL_DATA,
                 send_channel: int = CHANNEL_DATA,
                 dispatch_cb: Optional[Callable] = None,
                 readback_cb: Optional[Callable] = None,
                 depth: Optional[int] = None,
                 mb_of: Optional[Callable] = None,
                 stage: Optional[int] = None):
        if depth is None:
            depth = int(os.getenv("DCN_STAGE_DEPTH", "2"))
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if dispatch_cb is not None and work_cb is not None:
            raise ValueError("pass work_cb OR dispatch_cb/readback_cb, "
                             "not both")
        if readback_cb is not None and dispatch_cb is None:
            raise ValueError("readback_cb requires dispatch_cb")
        if work_cb is None and dispatch_cb is None \
                and not (rank_src is None and rank_dst is None):
            # only the not-in-schedule idle stage may omit the callback;
            # a wired stage without one would die silently on its first
            # frame in a daemon thread
            raise ValueError("a stage with rank_src/rank_dst needs a "
                             "work_cb or dispatch_cb")
        self._ctx = ctx
        self._rank_src = rank_src
        self._rank_dst = rank_dst
        self._dispatch_cb = dispatch_cb if dispatch_cb is not None else work_cb
        self._readback_cb = readback_cb
        self._results_cb = results_cb
        self._recv_channel = recv_channel
        self._send_channel = send_channel
        # telemetry: extracts the GLOBAL microbatch id from an inbound
        # tensor list (failover frames carry it as the leading tensor);
        # without it spans tag the stage-local dispatch sequence, which a
        # failover replay would renumber from 0 — miscorrelating exactly
        # the traces failover forensics needs
        self._mb_of = mb_of
        # pipeline-stage index for span tagging: with it, this stage's
        # dispatch/readback/emit spans land on the report's per-stage
        # tracks AND in the digest the rebalancer differences per round
        self._stage = stage
        self._depth = depth
        self._queue_work: "queue.Queue" = queue.Queue(maxsize=depth)
        self._queue_out: "queue.Queue" = queue.Queue(maxsize=depth)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    def start(self) -> None:
        if self._rank_src is None and self._rank_dst is None \
                and self._dispatch_cb is None:
            return  # not in the schedule: idle (reference runtime.py:456-460)
        # fresh session state: a stopped stage can be restarted (stop()
        # joined all threads, which hold the old event/queues)
        self._stop = threading.Event()
        self._queue_work = queue.Queue(maxsize=self._depth)
        self._queue_out = queue.Queue(maxsize=self._depth)
        for target, name in ((self._recv_loop, "recv"),
                             (self._work_loop, "work"),
                             (self._send_loop, "send")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"dcn-stage-{name}")
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        # drain before inserting the sentinel so a producer blocked on a full
        # single-slot queue is released (it re-checks _stop after the put)
        for q in (self._queue_work, self._queue_out):
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            try:
                q.put_nowait(self._SENTINEL)
            except queue.Full:
                pass
        for t in self._threads:
            t.join(timeout=10)
        self._threads.clear()

    def enqueue_tensors(self, tensors: List[np.ndarray],
                        trace: Optional["telemetry.TraceContext"] = None
                        ) -> None:
        """Inject data at the head of the pipeline (reference
        enqueue_tensor, p2p:442-450); blocks when the stage is busy.
        `trace` tags this microbatch's spans and rides downstream."""
        self._queue_work.put((tensors, trace))

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *args):
        self.stop()

    def _recv_loop(self) -> None:
        if self._rank_src is None:
            return  # head stage: fed by enqueue_tensors
        while not self._stop.is_set():
            try:
                tensors, _, trace = self._ctx.recv_tensors_traced(
                    self._rank_src, timeout=0.2,
                    channel=self._recv_channel)
            except queue.Empty:
                continue
            except ConnectionError:
                # upstream died: the context's peer-death handler owns the
                # fleet-wide reaction (CMD_STOP broadcast); this thread just
                # stops pulling
                return
            self._queue_work.put((tensors, trace))

    def _work_loop(self) -> None:
        # span mb tag: the global id when the frame carries one (mb_of),
        # else the stage-local dispatch sequence (equal to the global id
        # on a FIFO run)
        seq = 0
        while True:
            item = self._queue_work.get()
            if item is self._SENTINEL or self._stop.is_set():
                return
            tensors, trace = item
            mb = seq
            if self._mb_of is not None:
                try:
                    mb = self._mb_of(tensors)
                except Exception:  # malformed frame: keep the sequence tag
                    pass
            rid = trace.rid if trace is not None else None
            # trace_scope: spans the callback records WITHOUT an explicit
            # rid (the compute span inside dispatch_cb) inherit this
            # microbatch's request id through the thread-local context
            with telemetry.trace_scope(trace), \
                    telemetry.span("stage", "dispatch", stage=self._stage,
                                   mb=mb, rid=rid):
                out = self._dispatch_cb(tensors)
            if out is self.SKIP:
                continue    # dropped (corrupt frame awaiting its resend)
            self._queue_out.put((mb, out, trace))
            seq += 1

    def _send_loop(self) -> None:
        while True:
            item = self._queue_out.get()
            if item is self._SENTINEL or self._stop.is_set():
                return
            mb, item, trace = item
            rid = trace.rid if trace is not None else None
            if self._readback_cb is not None:
                # drain the async readback HERE, after the work thread is
                # already free to dispatch the next microbatch
                with telemetry.trace_scope(trace), \
                        telemetry.span("stage", "readback",
                                       stage=self._stage, mb=mb, rid=rid):
                    item = self._readback_cb(item)
            if self._rank_dst is not None:
                try:
                    # emit span: the downstream hand-off — socket transfer
                    # plus any slow-link stall or backpressure. A cost the
                    # stage pays per microbatch REGARDLESS of its layer
                    # range, which is exactly how the rebalance solver
                    # treats it (feedback.StageEstimate.fixed_s). The
                    # trace context rides the outbound frame, so the next
                    # stage inherits the request id without the payload
                    # tensors ever carrying it.
                    with telemetry.span("stage", "emit", stage=self._stage,
                                        mb=mb, rid=rid):
                        self._ctx.send_tensors(self._rank_dst, item,
                                               channel=self._send_channel,
                                               trace=trace)
                except OSError:
                    return  # downstream died: peer-death handler notified
            elif self._results_cb is not None:
                self._results_cb(item)


# -- protocol-table self-check (import-time; pipelint PL401/PL402 is the
# -- same law enforced statically on every diff) -------------------------

def _check_protocol_table() -> None:
    """Assert the `_MSG_*` table is coherent: every id unique, and every
    constant actually dispatched by `_reader_loop` (introspected from its
    source, so the check cannot drift from the code). A message type that
    only ever needs SENDING would go in `_MSG_SENDER_ONLY` — today every
    type is also received somewhere, so it is empty. Runs at import: a
    colliding or orphaned id fails the process before any frame moves."""
    import ast as _ast
    import inspect
    import textwrap

    msgs = {name: val for name, val in globals().items()
            if name.startswith("_MSG_") and isinstance(val, int)}
    by_id: Dict[int, List[str]] = {}
    for name, val in msgs.items():
        by_id.setdefault(val, []).append(name)
    dupes = {i: sorted(ns) for i, ns in by_id.items() if len(ns) > 1}
    assert not dupes, f"_MSG_ id collisions: {dupes}"
    try:
        reader_src = inspect.getsource(DistDcnContext._reader_loop)
        reader_tree = _ast.parse(textwrap.dedent(reader_src))
    except (OSError, TypeError, SyntaxError):  # pragma: no cover
        return                    # frozen/stripped: uniqueness still checked
    # CODE references only (ast.Name) — a comment or docstring mentioning
    # a _MSG_ constant must not satisfy the dispatch requirement
    dispatched = {n.id for n in _ast.walk(reader_tree)
                  if isinstance(n, _ast.Name) and n.id.startswith("_MSG_")}
    sender_only: frozenset = frozenset()
    missing = sorted(set(msgs) - dispatched - sender_only)
    assert not missing, (
        f"_MSG_ constants with no _reader_loop dispatch entry: {missing} "
        "(add the dispatch arm, or list the name in _MSG_SENDER_ONLY "
        "inside _check_protocol_table)")


_check_protocol_table()
