"""Distributed communication layer: contexts, command plane, multi-host init.

Capability mapping from the reference's two transports
(/root/reference/src/pipeedge/comm/):

| reference                                   | here                          |
|---------------------------------------------|-------------------------------|
| `DistContext` lifecycle (comm/__init__.py)  | `DistContext` below           |
| `DistP2pContext` (gloo TCP process group)   | `SliceContext`: a JAX slice — |
|                                             | intra-slice transport is XLA  |
|                                             | collectives over ICI, not TCP |
| multi-host bring-up (MASTER_ADDR etc.)      | `MultiHostContext` wrapping   |
|                                             | `jax.distributed.initialize`  |
|                                             | (coordinator over DCN)        |
| `CommandThread` + `cmd_broadcast` on tag 10 | `CommandPlane` (in-process    |
|   (p2p/__init__.py:63-85, 298-331)          |  pub/sub; host-side, like the |
|                                             |  reference's design intent)   |
| wire protocol: framing/dtype enum/pickle    | none needed — shapes/dtypes   |
|   (p2p/__init__.py:12-38, 96-121)           | are static under jit; the     |
|                                             | "wire format" is the compiled |
|                                             | program signature             |
| `DistP2pPipelineStage` thread pipeline      | parallel.pipeline /           |
|   (p2p/__init__.py:334-450)                 | parallel.spmd drivers         |
| `DistRpcContext`/`DistRpcPipeline`          | same drivers (RPC's role —    |
|   (comm/rpc/__init__.py)                    | remote stage construction —   |
|                                             | is a non-problem with a       |
|                                             | single controller)            |

The command plane preserves the reference's CMD_STOP / CMD_SCHED semantics
(runtime.py:36-37, 404-415): a schedule can be published to a live pipeline
(consumed at the next run boundary) and a stop can be requested.
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Callable, Optional, Tuple

logger = logging.getLogger(__name__)

# Command identifiers (reference runtime.py:36-37)
CMD_STOP = 0
CMD_SCHED = 1

DistCmdHandler = Callable[[int, Tuple[Any, ...]], None]


class DistContext:
    """Base lifecycle context (reference comm/__init__.py:7-32): holds
    world_size/rank, must be entered before use, reusable as a context
    manager."""

    def __init__(self, world_size: int = 1, rank: int = 0):
        self._world_size = world_size
        self._rank = rank
        self._initialized = False

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def initialized(self) -> bool:
        return self._initialized

    def init(self) -> None:
        """Initialize the context."""
        self._initialized = True

    def shutdown(self) -> None:
        """Shutdown the context."""
        self._initialized = False

    def __enter__(self):
        self.init()
        return self

    def __exit__(self, *args):
        self.shutdown()


class SliceContext(DistContext):
    """One TPU slice under a single controller: world = local devices.

    The reference's `DistP2pContext` establishes a TCP process group because
    each rank is a separate OS process (p2p/__init__.py:41-70); a JAX slice
    needs no bring-up — devices are already addressable — so this context
    only snapshots the device list and hosts a `CommandPlane`.
    """

    def __init__(self, cmd_handler: Optional[DistCmdHandler] = None):
        import jax
        devices = jax.local_devices()
        super().__init__(world_size=len(devices), rank=0)
        self.devices = devices
        self.command_plane = CommandPlane(cmd_handler)

    def init(self) -> None:
        super().init()
        self.command_plane.start()

    def shutdown(self) -> None:
        self.command_plane.stop()
        super().shutdown()

    def cmd_broadcast(self, cmd: int, payload: Tuple[Any, ...] = ()) -> None:
        """Publish a command (reference p2p cmd_broadcast, p2p:72-85)."""
        self.command_plane.publish(cmd, payload)


class MultiHostContext(DistContext):
    """Multi-host (DCN) bring-up via `jax.distributed.initialize`.

    The TPU equivalent of the reference's MASTER_ADDR/MASTER_PORT env
    bring-up (runtime.py:581-602): every host runs the same program,
    coordinated through the given address; after `init()`, `jax.devices()`
    spans all hosts and the SPMD pipeline's collectives ride ICI within a
    slice and DCN across slices.
    """

    def __init__(self, coordinator_address: str, num_processes: int,
                 process_id: int):
        super().__init__(world_size=num_processes, rank=process_id)
        self._coordinator_address = coordinator_address

    def init(self) -> None:
        import jax
        if self._world_size > 1:
            jax.distributed.initialize(
                coordinator_address=self._coordinator_address,
                num_processes=self._world_size, process_id=self._rank)
        else:
            logger.info("single-process world: skipping jax.distributed")
        super().init()

    def shutdown(self) -> None:
        import jax
        if self._world_size > 1:
            jax.distributed.shutdown()
        super().shutdown()


class CommandPlane:
    """Host-side command pub/sub: the reference's CommandThread without the
    network (p2p/__init__.py:298-331). Commands are dispatched to the handler
    on a background thread, preserving the asynchronous delivery semantics
    the runtime relies on (schedule can arrive while the pipeline runs)."""

    def __init__(self, handler: Optional[DistCmdHandler] = None):
        self._handler = handler
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="CommandPlane")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._queue.put(None)  # wake the thread
        self._thread.join()
        self._thread = None

    def publish(self, cmd: int, payload: Tuple[Any, ...] = ()) -> None:
        self._queue.put((cmd, payload))

    def _run(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            if item is None:
                continue
            cmd, payload = item
            logger.debug("command plane: cmd=%d", cmd)
            if self._handler is not None:
                self._handler(cmd, payload)
