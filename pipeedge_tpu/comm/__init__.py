"""Distributed communication layer: contexts, command plane, multi-host init.

Capability mapping from the reference's two transports
(/root/reference/src/pipeedge/comm/):

| reference                                   | here                          |
|---------------------------------------------|-------------------------------|
| `DistContext` lifecycle (comm/__init__.py)  | `DistContext` below           |
| `DistP2pContext` (gloo TCP process group)   | `SliceContext`: a JAX slice — |
|                                             | intra-slice transport is XLA  |
|                                             | collectives over ICI, not TCP |
| multi-host bring-up (MASTER_ADDR etc.)      | `MultiHostContext` wrapping   |
|                                             | `jax.distributed.initialize`  |
|                                             | (coordinator over DCN)        |
| `CommandThread` + `cmd_broadcast` on tag 10 | `CommandPlane` (in-process    |
|   (p2p/__init__.py:63-85, 298-331)          |  pub/sub; host-side, like the |
|                                             |  reference's design intent)   |
| wire protocol: framing/dtype enum/pickle    | none needed — shapes/dtypes   |
|   (p2p/__init__.py:12-38, 96-121)           | are static under jit; the     |
|                                             | "wire format" is the compiled |
|                                             | program signature             |
| `DistP2pPipelineStage` thread pipeline      | parallel.pipeline /           |
|   (p2p/__init__.py:334-450)                 | parallel.spmd drivers         |
| `DistRpcContext`/`DistRpcPipeline`          | same drivers (RPC's role —    |
|   (comm/rpc/__init__.py)                    | remote stage construction —   |
|                                             | is a non-problem with a       |
|                                             | single controller)            |

The command plane preserves the reference's CMD_STOP / CMD_SCHED semantics
(runtime.py:36-37, 404-415): a schedule can be published to a live pipeline
(consumed at the next run boundary) and a stop can be requested. The DCN
transport additionally answers per-edge bitwidth-negotiation frames on the
same control connections (`DistDcnContext.negotiate_edge_bits`) — the
handshake behind the quantized wire-v2 edges (docs/DCN_WIRE.md).
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Callable, Optional, Tuple

from ..utils.threads import make_lock

logger = logging.getLogger(__name__)

# Command identifiers (reference runtime.py:36-37)
CMD_STOP = 0
CMD_SCHED = 1
# reverse-auction bid request (the reference fans this out as an RPC call,
# revauct.py:168-174; over DCN it is a command frame answered on the
# transport's BIDS channel)
CMD_BID = 2
# peer-death announcement (failover mode, beyond the reference): payload is
# the dead rank id. Unlike a death-carrying CMD_STOP — which aborts the
# fleet — CMD_DEAD only records the death; the data rank reacts by ending
# the round and re-scheduling over the survivors (runtime.py failover path)
CMD_DEAD = 3
# admission acknowledgment (elastic membership, the inverse of CMD_DEAD):
# the data rank confirms a rejoined peer's re-admission, payload is the
# current global round index — the rejoiner logs it and knows its next
# CMD_SCHED is live traffic, not a stale replay (runtime.py rejoin path)
CMD_ADMIT = 4

DistCmdHandler = Callable[[int, Tuple[Any, ...]], None]


class DistContext:
    """Base lifecycle context (reference comm/__init__.py:7-32): holds
    world_size/rank, must be entered before use, reusable as a context
    manager."""

    def __init__(self, world_size: int = 1, rank: int = 0):
        self._world_size = world_size
        self._rank = rank
        self._initialized = False

    @property
    def world_size(self) -> int:
        return self._world_size

    @property
    def rank(self) -> int:
        return self._rank

    @property
    def initialized(self) -> bool:
        return self._initialized

    def init(self) -> None:
        """Initialize the context."""
        self._initialized = True

    def shutdown(self) -> None:
        """Shutdown the context."""
        self._initialized = False

    def __enter__(self):
        self.init()
        return self

    def __exit__(self, *args):
        self.shutdown()


class SliceContext(DistContext):
    """One TPU slice under a single controller: world = local devices.

    The reference's `DistP2pContext` establishes a TCP process group because
    each rank is a separate OS process (p2p/__init__.py:41-70); a JAX slice
    needs no bring-up — devices are already addressable — so this context
    only snapshots the device list and hosts a `CommandPlane`.
    """

    def __init__(self, cmd_handler: Optional[DistCmdHandler] = None):
        super().__init__(world_size=0, rank=0)
        self.devices: list = []
        self.command_plane = CommandPlane(cmd_handler)

    def init(self) -> None:
        # Snapshot devices here, not in __init__: touching the backend at
        # construction time would initialize it before a MultiHostContext
        # (or dryrun_multichip's platform override) gets a chance to run.
        import jax
        self.devices = jax.local_devices()
        self._world_size = len(self.devices)
        super().init()
        self.command_plane.start()

    def shutdown(self) -> None:
        self.command_plane.stop()
        super().shutdown()

    def cmd_broadcast(self, cmd: int, payload: Tuple[Any, ...] = ()) -> None:
        """Publish a command (reference p2p cmd_broadcast, p2p:72-85)."""
        self.command_plane.publish(cmd, payload)


class MultiHostContext(DistContext):
    """Multi-host (DCN) bring-up via `jax.distributed.initialize`.

    The TPU equivalent of the reference's MASTER_ADDR/MASTER_PORT env
    bring-up (runtime.py:581-602): every host runs the same program,
    coordinated through the given address; after `init()`, `jax.devices()`
    spans all hosts and the SPMD pipeline's collectives ride ICI within a
    slice and DCN across slices.
    """

    def __init__(self, coordinator_address: str, num_processes: int,
                 process_id: int):
        super().__init__(world_size=num_processes, rank=process_id)
        self._coordinator_address = coordinator_address

    def init(self) -> None:
        import jax
        if self._world_size > 1:
            jax.distributed.initialize(
                coordinator_address=self._coordinator_address,
                num_processes=self._world_size, process_id=self._rank)
        else:
            logger.info("single-process world: skipping jax.distributed")
        super().init()

    def shutdown(self) -> None:
        import jax
        if self._world_size > 1:
            jax.distributed.shutdown()
        super().shutdown()


class CommandPlane:
    """Host-side command pub/sub: the reference's CommandThread without the
    network (p2p/__init__.py:298-331). Commands are dispatched to the handler
    on a background thread, preserving the asynchronous delivery semantics
    the runtime relies on (schedule can arrive while the pipeline runs)."""

    _SHUTDOWN = object()  # queue sentinel: everything before it is delivered

    def __init__(self, handler: Optional[DistCmdHandler] = None):
        self._handler = handler
        # Each start()/stop() session gets its own queue: stop() swaps in a
        # fresh one under the lock, so the outgoing dispatch thread drains
        # exactly its own session's commands (no replay, no cross-session
        # consumer races), while later publishes land in the new queue and
        # are held for the next start().
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        # Set only by an in-handler stop(): the dispatch thread that is
        # still draining its session's queue and couldn't be joined there.
        self._draining: Optional[threading.Thread] = None
        self._lock = make_lock("comm.dispatcher")

    def start(self) -> None:
        """Start the dispatch thread. If the previous session was stopped
        from inside its own handler, wait for that dispatcher to finish
        first so two sessions never dispatch concurrently (not possible
        when start() itself runs on the draining thread — that lone case
        accepts overlap)."""
        while True:
            with self._lock:
                # check _thread and _draining in the SAME critical section:
                # an in-handler stop() publishes both under the lock, so we
                # can never observe "no thread, nothing draining" while an
                # old dispatcher is still working through its queue
                if self._thread is not None:
                    return
                draining = self._draining
                if draining is None or draining is threading.current_thread():
                    self._draining = None
                    self._thread = threading.Thread(
                        target=self._run, args=(self._queue,), daemon=True,
                        name="CommandPlane")
                    self._thread.start()
                    return
            draining.join()
            with self._lock:
                if self._draining is draining:
                    self._draining = None

    def stop(self) -> None:
        """Stop the dispatch thread after it drains already-published
        commands (a CMD_STOP published just before shutdown must still be
        delivered). Commands published after stop()'s cutoff are held for
        the next start(); a restarted plane never replays the stopped
        session's leftovers. Safe to call concurrently from several threads
        and from inside a command handler (e.g. a handler reacting to
        CMD_STOP by shutting the context down) — in that case the dispatch
        thread finishes its queue and exits on its own instead of joining
        itself."""
        with self._lock:
            thread = self._thread
            if thread is None:
                return
            self._thread = None
            self._queue.put(self._SHUTDOWN)  # FIFO: after all prior publishes
            self._queue = queue.Queue()
            # Mark in the same critical section that retired the thread, so a
            # concurrent start() can never observe (_thread=None,
            # _draining=None) while this dispatcher is still draining — for
            # BOTH the in-handler stop (joined by the next start()) and an
            # external stop (joined right below).
            self._draining = thread
        if thread is not threading.current_thread():
            thread.join()
            with self._lock:
                if self._draining is thread:
                    self._draining = None

    def publish(self, cmd: int, payload: Tuple[Any, ...] = ()) -> None:
        with self._lock:
            self._queue.put((cmd, payload))

    def _run(self, q: "queue.Queue") -> None:
        while True:
            item = q.get()
            if item is self._SHUTDOWN:
                return
            cmd, payload = item
            logger.debug("command plane: cmd=%d", cmd)
            if self._handler is not None:
                try:
                    self._handler(cmd, payload)
                except Exception:  # keep dispatching, like the reference
                    logger.exception("command handler failed (cmd=%d)", cmd)
