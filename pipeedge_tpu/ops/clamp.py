"""Optimal pre-quantization clipping (Banner et al., NeurIPS 2019).

Capability parity with /root/reference/src/pipeedge/quantization/clamp_op.py:
clamp activations to +/- alpha before uniform quantization, where alpha is the
analytically-optimal clipping threshold for a Laplace-distributed tensor:
alpha = W(3 * 4^b) * sqrt(var/2) (clamp_op.py:22-33), with a GeLU variant that
treats the post-GeLU distribution as a half bell curve with doubled second
moment: alpha = W(3 * 4^(b+1)) * sqrt(E[x^2]) (clamp_op.py:6-19).

TPU-first design: the Lambert-W factor depends only on the *static* bitwidth,
so it is precomputed on the host at trace time (scipy), leaving the on-device
work as a fused moment-reduction + clip that XLA folds into the surrounding
quantization kernel. (The reference calls scipy inside the hot path.)
"""
from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from scipy.special import lambertw


@lru_cache(maxsize=None)
def clamp_factor_laplace(bit: int) -> float:
    """W(3 * 4^bit), the optimal Laplace clipping multiplier (clamp_op.py:22-24)."""
    return float(lambertw(3.0 * 4.0 ** bit).real)


@lru_cache(maxsize=None)
def clamp_factor_gelu(bit: int) -> float:
    """W(3 * 4^(bit+1)) for half-bell post-GeLU tensors (clamp_op.py:6-8)."""
    return float(lambertw(3.0 * 4.0 ** (bit + 1)).real)


@partial(jax.jit, static_argnames=("bit",))
def clamp_banner2019_laplace(x: jax.Array, bit: int) -> jax.Array:
    """Clamp to the Laplace-optimal threshold (clamp_op.py:27-33)."""
    var = jnp.var(x)
    alpha = clamp_factor_laplace(bit) * jnp.sqrt(0.5 * var)
    return jnp.clip(x, -alpha, alpha)


@partial(jax.jit, static_argnames=("bit",))
def clamp_banner2019_gelu(x: jax.Array, bit: int) -> jax.Array:
    """Clamp a post-GeLU tensor (half bell curve, clamp_op.py:11-19)."""
    second_moment = 2.0 * jnp.mean(jnp.square(x))
    alpha = clamp_factor_gelu(bit) * jnp.sqrt(0.5 * second_moment)
    return jnp.clip(x, -alpha, alpha)
