"""Fused int8-KV decode-step attention as a Pallas TPU kernel.

The XLA int8 decode path (parallel/decode.py `_cache_update_and_read`)
dequantizes the attended cache window to a full-precision [B, T, H, Dh]
copy before the attend matmuls — XLA does not fuse elementwise producers
into dot operands, so the dequantized K AND V copies are materialized
through HBM every decode step. This kernel streams the int8 cache
blocks into VMEM, dequantizes in-register, and runs the online-softmax
attend — HBM reads stay int8 (plus the tiny per-(position, head) scale
rows), roughly halving the decode step's dominant traffic.

Semantics match the XLA path exactly where it matters:
- the FRESH row (the token written at `pos` this step) is substituted
  unquantized inside the kernel, mirroring the XLA path's
  "freshly computed rows are in hand — attend over them exactly";
- masking keeps cache positions [0, pos]; K/V blocks wholly past `pos`
  are skipped (the streaming loop stops at the last live block, which
  is also what the bucketed attend window achieves statically).

Scope: the classic single-token decode step of MHA families
(kv_heads == num heads, no sliding window) — the hot serving path.
Span (speculative verify), GQA, and windowed attention stay on the XLA
path. `pos` reaches the kernel via scalar prefetch (it is traced; the
window width is static).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._blocks import pick_block

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_NEG_INF = -1e30


def _kernel(pos_ref, q_ref, kq_ref, ks_ref, kz_ref, vq_ref, vs_ref, vz_ref,
            kn_ref, vn_ref, o_ref, *, kv_block: int, scale: float):
    """One batch cell, ALL heads at once: stream int8 K/V row-blocks,
    dequantize in VMEM, online softmax per head over positions [0, pos].

    The head axis stays in the block (TPU lowering requires the last two
    block dims be full or tile-aligned, so a per-head grid would need a
    layout transpose — materializing the copy this kernel exists to
    avoid). At S_q=1 the attend is bandwidth-bound elementwise+reduce
    work; everything maps to the VPU, no MXU involvement."""
    pos = pos_ref[0]
    q = q_ref[0, 0].astype(jnp.float32)                  # [H, Dh]
    width, h, d = kq_ref.shape[1], q.shape[0], q.shape[1]
    n_kv = width // kv_block

    k_new = kn_ref[0, 0].astype(jnp.float32)             # [H, Dh]
    v_new = vn_ref[0, 0].astype(jnp.float32)

    def dequant(qv, s_ref, z_ref, i):
        s = s_ref[0, pl.ds(i * kv_block, kv_block), :]   # [kb, H]
        z = z_ref[0, pl.ds(i * kv_block, kv_block), :]
        return (qv.astype(jnp.float32) + 128.0) * s[..., None] + z[..., None]

    def body(i, carry):
        m_prev, l_prev, acc = carry                      # [H] [H] [H, Dh]
        rows = i * kv_block + jax.lax.broadcasted_iota(
            jnp.int32, (kv_block, h), 0)                 # [kb, H]
        k = dequant(kq_ref[0, pl.ds(i * kv_block, kv_block)],
                    ks_ref, kz_ref, i)                   # [kb, H, Dh]
        v = dequant(vq_ref[0, pl.ds(i * kv_block, kv_block)],
                    vs_ref, vz_ref, i)
        # 3D iota, not rows[..., None]: Mosaic only supports minor-dim
        # insertion for 32-bit types, and the mask is boolean
        fresh = (i * kv_block + jax.lax.broadcasted_iota(
            jnp.int32, (kv_block, h, 1), 0)) == pos      # [kb, H, 1]
        k = jnp.where(fresh, k_new[None], k)
        v = jnp.where(fresh, v_new[None], v)
        # round K/V (and below, the probs) through the pipeline dtype at
        # the same points the XLA path does (_dequantize_rows -> dtype,
        # probs.astype(dtype)); f32 pipelines make these no-ops. The
        # online softmax still differs from the full softmax at the
        # rounding level — flash-style accumulation is mathematically,
        # not bitwise, equal.
        k = k.astype(o_ref.dtype).astype(jnp.float32)
        v = v.astype(o_ref.dtype).astype(jnp.float32)
        scores = jnp.sum(q[None] * k, axis=-1) * scale   # [kb, H]
        scores = jnp.where(rows <= pos, scores, _NEG_INF)
        m_blk = jnp.max(scores, axis=0)                  # [H]
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(scores - m_new[None])                # [kb, H]
        p = p.astype(o_ref.dtype).astype(jnp.float32)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=0)
        acc = acc * corr[:, None] + jnp.sum(p[..., None] * v, axis=0)
        return m_new, l_new, acc

    m0 = jnp.full((h,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((h,), jnp.float32)
    acc0 = jnp.zeros((h, d), jnp.float32)
    n_live = jnp.minimum(pos // kv_block + 1, n_kv)   # skip dead blocks
    _, l, acc = jax.lax.fori_loop(0, n_live, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / l[:, None]).astype(o_ref.dtype)


def _kernel_v2(pos_ref, q_ref, kq_ref, ks_ref, kz_ref, vq_ref, vs_ref,
               vz_ref, kn_ref, vn_ref, o_ref, m_scr, l_scr, acc_scr, *,
               kv_block: int, scale: float, n_kv: int):
    """v2 'batch-as-sublane' formulation (round-5 verdict item 3): the
    grid runs over KV row-blocks (sequential, online-softmax state in
    VMEM scratch) and each instance processes EVERY batch cell at once —
    [B, kb, H, Dh] element blocks give the VPU B x more rows per
    instruction than v1's per-cell grid, and the kernel launches n_kv
    instances instead of B. Same masking/fresh-row/rounding semantics
    as v1 (the exactness tests parametrize over both)."""
    i = pl.program_id(0)
    pos = pos_ref[0]
    q = q_ref[:, 0].astype(jnp.float32)                  # [B, H, Dh]
    b, h, d = q.shape

    @pl.when(i == 0)
    def _init():
        m_scr[...] = jnp.full((b, h), _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros((b, h), jnp.float32)
        acc_scr[...] = jnp.zeros((b, h, d), jnp.float32)

    def dequant(qv, s, z):
        return (qv.astype(jnp.float32) + 128.0) * s[..., None] \
            + z[..., None]

    k_new = kn_ref[:, 0].astype(jnp.float32)             # [B, H, Dh]
    v_new = vn_ref[:, 0].astype(jnp.float32)
    k = dequant(kq_ref[...], ks_ref[...], kz_ref[...])   # [B, kb, H, Dh]
    v = dequant(vq_ref[...], vs_ref[...], vz_ref[...])
    rows4 = i * kv_block + jax.lax.broadcasted_iota(
        jnp.int32, (b, kv_block, h, 1), 1)
    fresh = rows4 == pos
    k = jnp.where(fresh, k_new[:, None], k)
    v = jnp.where(fresh, v_new[:, None], v)
    k = k.astype(o_ref.dtype).astype(jnp.float32)
    v = v.astype(o_ref.dtype).astype(jnp.float32)
    scores = jnp.sum(q[:, None] * k, axis=-1) * scale    # [B, kb, H]
    rows3 = i * kv_block + jax.lax.broadcasted_iota(
        jnp.int32, (b, kv_block, h), 1)
    scores = jnp.where(rows3 <= pos, scores, _NEG_INF)
    m_prev, l_prev, acc = m_scr[...], l_scr[...], acc_scr[...]
    m_blk = jnp.max(scores, axis=1)                      # [B, H]
    m_new = jnp.maximum(m_prev, m_blk)
    p = jnp.exp(scores - m_new[:, None])                 # [B, kb, H]
    p = p.astype(o_ref.dtype).astype(jnp.float32)
    corr = jnp.exp(m_prev - m_new)
    m_scr[...] = m_new
    l_scr[...] = l_prev * corr + jnp.sum(p, axis=1)
    acc_scr[...] = acc * corr[..., None] + jnp.sum(p[..., None] * v,
                                                   axis=1)

    @pl.when(i == n_kv - 1)
    def _emit():
        o_ref[:, 0] = (acc_scr[...]
                       / l_scr[...][..., None]).astype(o_ref.dtype)


# one block resolver across the fused kernels (ops/_blocks.py)
_pick_block = pick_block


_V2_VMEM_BUDGET = 8 << 20


def _pick_block_v2(width: int, b: int, h: int, d: int) -> int:
    """v2 stages [B, kb, H, Dh] blocks with ~6 f32-sized intermediates
    (dequantized K/V, probs, masks) live at once — cap kb so the scoped
    VMEM stack stays well under the ~16 MB limit (measured OOM at
    B=16, kb=128: 24.3 MB requested). Returns 0 when even the minimum
    kb=8 block busts the budget (huge B*H*Dh): callers refuse variant 2
    for that shape instead of dying in Mosaic lowering."""
    per_row = b * h * d * 4 * 6
    if per_row * 8 > _V2_VMEM_BUDGET:
        return 0
    preferred = min(128, _V2_VMEM_BUDGET // per_row) // 8 * 8
    block = _pick_block(width, preferred)
    # _pick_block falls back to the FULL width when no divisor >= 8
    # exists (e.g. width 100); re-check the budget on what it actually
    # returned rather than trusting the preference
    return block if block * per_row <= _V2_VMEM_BUDGET else 0


def int8_v2_fits(width: int, b: int, h: int, d: int) -> bool:
    """Whether the batch-as-sublane variant has a legal block size for
    this shape (decode.py's routing gate falls back to the XLA path
    when not)."""
    return _pick_block_v2(width, b, h, d) > 0


@functools.partial(jax.jit, static_argnames=("interpret", "variant"))
def int8_decode_attention(q, k_q, k_scale, k_shift, v_q, v_scale, v_shift,
                          k_new, v_new, pos, interpret: bool = False,
                          variant: int = 1):
    """Fused decode-step attention over an int8 cache window.

    q/k_new/v_new: [B, 1, H, Dh]; k_q/v_q: [B, T, H, Dh] int8;
    scales/shifts: [B, T, H] float32; `pos` traced scalar. Returns
    [B, 1, H*Dh] context, matching `_attend`'s output layout.

    `variant` 1: per-batch-cell grid, fori_loop over KV blocks (live
    blocks only). `variant` 2: per-KV-block grid processing all batch
    cells at once ('batch-as-sublane'), online-softmax state in VMEM
    scratch — B x the VPU rows per instruction, n_kv instead of B
    kernel instances, at the cost of always touching the full (bucketed)
    window. Numerically identical routes (shared exactness tests)."""
    b, _, h, d = q.shape
    width = k_q.shape[1]
    kv_block = _pick_block_v2(width, b, h, d) if variant == 2 \
        else _pick_block(width)
    scale = 1.0 / (d ** 0.5)
    if variant == 2:
        if kv_block == 0:
            raise ValueError(
                f"int8 decode kernel variant 2 has no legal block for "
                f"B={b}, H={h}, Dh={d} within the VMEM budget; use "
                "variant 1 or the XLA path (int8_v2_fits gates this)")
        n_kv = width // kv_block
        kernel = functools.partial(_kernel_v2, kv_block=kv_block,
                                   scale=scale, n_kv=n_kv)
        whole = lambda i, *_: (0, 0, 0, 0)
        whole3 = lambda i, *_: (0, 0, 0)
        blk = lambda i, *_: (0, i, 0, 0)
        blk3 = lambda i, *_: (0, i, 0)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_kv,),
            in_specs=[
                pl.BlockSpec((b, 1, h, d), whole),        # q
                pl.BlockSpec((b, kv_block, h, d), blk),   # k_q
                pl.BlockSpec((b, kv_block, h), blk3),     # k_scale
                pl.BlockSpec((b, kv_block, h), blk3),     # k_shift
                pl.BlockSpec((b, kv_block, h, d), blk),   # v_q
                pl.BlockSpec((b, kv_block, h), blk3),     # v_scale
                pl.BlockSpec((b, kv_block, h), blk3),     # v_shift
                pl.BlockSpec((b, 1, h, d), whole),        # k_new
                pl.BlockSpec((b, 1, h, d), whole),        # v_new
            ],
            out_specs=pl.BlockSpec((b, 1, h, d), whole),
            scratch_shapes=[
                pltpu.VMEM((b, h), jnp.float32),          # running max
                pltpu.VMEM((b, h), jnp.float32),          # running sum
                pltpu.VMEM((b, h, d), jnp.float32),       # running acc
            ],
        )
        compiler_params = _CompilerParams(
            dimension_semantics=("arbitrary",))
    else:
        kernel = functools.partial(_kernel, kv_block=kv_block, scale=scale)
        batch_row = lambda b_, *_: (b_, 0, 0, 0)
        batch_row3 = lambda b_, *_: (b_, 0, 0)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[
                pl.BlockSpec((1, 1, h, d), batch_row),        # q
                pl.BlockSpec((1, width, h, d), batch_row),    # k_q
                pl.BlockSpec((1, width, h), batch_row3),      # k_scale
                pl.BlockSpec((1, width, h), batch_row3),      # k_shift
                pl.BlockSpec((1, width, h, d), batch_row),    # v_q
                pl.BlockSpec((1, width, h), batch_row3),      # v_scale
                pl.BlockSpec((1, width, h), batch_row3),      # v_shift
                pl.BlockSpec((1, 1, h, d), batch_row),        # k_new
                pl.BlockSpec((1, 1, h, d), batch_row),        # v_new
            ],
            out_specs=pl.BlockSpec((1, 1, h, d), batch_row),
        )
        compiler_params = None
    kwargs = {}
    if compiler_params is not None:
        kwargs["compiler_params"] = compiler_params
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, h, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        **kwargs,
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, k_q,
      k_scale.astype(jnp.float32), k_shift.astype(jnp.float32), v_q,
      v_scale.astype(jnp.float32), v_shift.astype(jnp.float32),
      k_new, v_new)
    return out.reshape(b, 1, h * d)


def int8_decode_attention_supported() -> bool:
    """Native lowering needs a TPU; elsewhere interpret mode (tests)."""
    return jax.default_backend() == "tpu"
