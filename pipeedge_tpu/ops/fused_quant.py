"""Pallas-fused quant encode/decode: wire encode rides the producing kernel.

`ops/quant.py`'s `tensor_encode_outerdim` is a correct, jittable encoder,
but XLA schedules it as its own fusion after the stage's last matmul: the
full-width activation round-trips HBM once for the matmul output and again
for the quant reduction + pack. These Pallas kernels put the whole per-item
pipeline — min/shift reduction, scale, round, nibble/byte pack into uint32
words — into ONE kernel per item, so the epilogue reads the activation from
HBM exactly once and writes only the packed words + per-item scale/shift
(32/bit of the bytes). The decode kernel is the consumer-prologue mirror.

Bit-identity contract (the acceptance invariant, tests/test_fused_quant.py):
for bit in {4, 8} and any shape, `fused_encode_outerdim(x, bit)` produces
the same packed words, scale, and shift as `quant_ops.tensor_encode_outerdim`
— same f32 op order (min, max-of-shifted, round-half-even, shift-or pack),
same zero-padding of the packed tail — and `fused_decode_outerdim` matches
`tensor_decode_outerdim`. Any producer/consumer therefore pairs with any
other across the fused/XLA/native codec generations (the comm/wire.py
contract).

Kernel layout: the packed word `w` holds values `w*per_word + j` at bit
offset `j*bit` (reference basic_op.py layout). The kernel receives the item
pre-arranged as [per_word, words] — value (j, w) at sublane j, lane w — so
the pack is a per-sublane shift + OR-accumulate down the (static, 4- or
8-deep) sublane axis and the words dimension stays on the 128-wide lanes.
The arranging transpose runs in XLA outside the kernel where layout changes
are free.

Mode selection (`PIPEEDGE_FUSED_QUANT`):
- `auto` (default): fused kernels on TPU backends after a one-time
  lowering+bit-identity probe (falls back to the XLA ops with a warning if
  Mosaic rejects the kernel); XLA ops elsewhere.
- `interpret`: fused kernels in Pallas interpret mode — the CPU CI path
  that keeps the kernels' math honest without TPU hardware.
- `1`/`0`: force the fused path / force the XLA ops.

Consumers go through `encode_outerdim`/`decode_outerdim` below — the ONE
dispatch seam `parallel/pipeline.py` (stage epilogue), `parallel/spmd.py`
(ppermute edge codec), `comm/wire.py` (`wire_encode_device`), and
`ops/qcollectives.py` (block-scaled collective codec) all share.
"""
from __future__ import annotations

import functools
import logging
import os
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import quant as quant_ops
from ._blocks import pick_block

logger = logging.getLogger(__name__)

ENV_FUSED_QUANT = "PIPEEDGE_FUSED_QUANT"

# bitwidths with a fused kernel: the wire-path workhorses (int8 bytes,
# int4 nibbles). Other bitwidths fall back to the XLA ops.
FUSED_BITS = (4, 8)

# lane-block preference for the decode kernel (per-word sublanes x
# DECODE_LANE_BLOCK lanes of uint32 live in VMEM per grid cell)
DECODE_LANE_BLOCK = 4096


def _encode_kernel(x_ref, data_ref, scale_ref, shift_ref, *, bit: int,
                   n_valid: int):
    """One item: [per_word, words] f32 -> packed words + scale/shift.

    Mirrors `quant_ops._quantize_item` ('original' mode) exactly: the
    reductions run over the n_valid real elements (the tail lanes beyond
    them are padding), quantized padding packs as 0 (the reference pads
    AFTER quantization with zero ints)."""
    per_word, words = x_ref.shape[1], x_ref.shape[2]
    x = x_ref[0]                                    # [per_word, words] f32
    j = jax.lax.broadcasted_iota(jnp.int32, (per_word, words), 0)
    w = jax.lax.broadcasted_iota(jnp.int32, (per_word, words), 1)
    valid = w * per_word + j < n_valid
    shift = jnp.min(jnp.where(valid, x, jnp.float32(np.inf)))
    scale = jnp.max(jnp.where(valid, x - shift, jnp.float32(-np.inf)))
    safe_scale = jnp.where(scale > 0, scale, jnp.float32(1))
    x01 = (x - shift) / safe_scale
    levels = float((1 << bit) - 1)
    q = jnp.round(x01 * levels).astype(jnp.uint32)
    q = jnp.where(valid, q, jnp.uint32(0))
    # disjoint offsets: OR-accumulate the (static) sublane axis into words
    acc = q[0:1, :]
    for jj in range(1, per_word):
        acc = acc | (q[jj:jj + 1, :] << np.uint32(jj * bit))
    data_ref[:, :] = acc
    scale_ref[0, 0] = scale
    shift_ref[0, 0] = shift


def _decode_kernel(data_ref, scale_ref, shift_ref, o_ref, *, bit: int):
    """One (item, lane-block) cell: packed words -> [per_word, words] f32.

    Mirrors `quant_ops._dequantize_item`: unpack by shift+mask, then
    q / levels * scale + shift in the same op order."""
    per_word = 32 // bit
    words = data_ref[:, :]                          # [1, w_blk] uint32
    mask = np.uint32((1 << bit) - 1)
    rows = [((words >> np.uint32(jj * bit)) & mask).astype(jnp.float32)
            for jj in range(per_word)]
    q = jnp.concatenate(rows, axis=0)               # [per_word, w_blk]
    levels = float((1 << bit) - 1)
    o_ref[0] = q / levels * scale_ref[0, 0] + shift_ref[0, 0]


@functools.partial(jax.jit, static_argnames=("bit", "interpret"))
def fused_encode_outerdim(x: jax.Array, bit: int,
                          interpret: bool = False) -> quant_ops.QuantizedTensor:
    """Pallas-fused `tensor_encode_outerdim` (bit-identical, bits 4/8)."""
    if bit not in FUSED_BITS:
        raise ValueError(f"fused encode supports bits {FUSED_BITS}, got {bit}")
    shape = tuple(x.shape)
    b = shape[0]
    n = int(np.prod(shape[1:]))
    per_word = 32 // bit
    words = quant_ops.packed_words(n, bit)
    total = words * per_word
    flat = x.reshape(b, n).astype(jnp.float32)
    if total > n:
        flat = jnp.pad(flat, ((0, 0), (0, total - n)))
    # value (j, w) at sublane j, lane w — word index on the wide lane axis
    arranged = flat.reshape(b, words, per_word).transpose(0, 2, 1)
    kernel = functools.partial(_encode_kernel, bit=bit, n_valid=n)
    data, scale, shift = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((b, words), jnp.uint32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ],
        grid=(b,),
        in_specs=[pl.BlockSpec((1, per_word, words), lambda i: (i, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, words), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        interpret=interpret,
    )(arranged)
    return quant_ops.QuantizedTensor(data=data, scale=scale[:, 0],
                                     shift=shift[:, 0], shape=shape, bit=bit)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_decode_outerdim(enc: quant_ops.QuantizedTensor,
                          interpret: bool = False) -> jax.Array:
    """Pallas-fused `tensor_decode_outerdim` (bit-identical, bits 4/8)."""
    bit = enc.bit
    if bit not in FUSED_BITS:
        raise ValueError(f"fused decode supports bits {FUSED_BITS}, got {bit}")
    shape = tuple(enc.shape)
    b = shape[0]
    n = int(np.prod(shape[1:]))
    per_word = 32 // bit
    words = enc.data.shape[1]
    w_blk = pick_block(words, DECODE_LANE_BLOCK)
    kernel = functools.partial(_decode_kernel, bit=bit)
    full = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, per_word, words), jnp.float32),
        grid=(b, words // w_blk),
        in_specs=[
            pl.BlockSpec((1, w_blk), lambda i, k: (i, k)),
            pl.BlockSpec((1, 1), lambda i, k: (i, 0)),
            pl.BlockSpec((1, 1), lambda i, k: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, per_word, w_blk), lambda i, k: (i, 0, k)),
        interpret=interpret,
    )(enc.data, enc.scale.reshape(b, 1), enc.shift.reshape(b, 1))
    flat = full.transpose(0, 2, 1).reshape(b, words * per_word)
    return flat[:, :n].reshape(shape)


# -- dispatch seam (pipeline epilogue / spmd edge / wire / collectives) --

def _mode() -> str:
    return os.getenv(ENV_FUSED_QUANT, "auto").strip().lower()


# one-time native-lowering probe result per bitwidth (auto mode on TPU):
# Mosaic rejecting the kernel must degrade to the XLA ops, not kill the run
_PROBE_OK: Dict[int, bool] = {}


def _probe_native(bit: int) -> bool:
    ok = _PROBE_OK.get(bit)
    if ok is None:
        try:
            x = (jnp.arange(2 * 37, dtype=jnp.float32).reshape(2, 37)
                 * 0.731 - 11.0)
            enc = fused_encode_outerdim(x, bit, interpret=False)
            ref = quant_ops.tensor_encode_outerdim(x, bit)
            dec = fused_decode_outerdim(enc, interpret=False)
            ok = (bool(jnp.all(enc.data == ref.data))
                  and bool(jnp.all(enc.scale == ref.scale))
                  and bool(jnp.all(
                      dec == quant_ops.tensor_decode_outerdim(ref))))
            if not ok:
                logger.warning("fused quant probe (bit=%d): native kernel "
                               "output differs from the XLA ops; falling "
                               "back to the XLA encode/decode", bit)
        except Exception as exc:  # noqa: BLE001 - Mosaic lowering errors
            logger.warning("fused quant probe (bit=%d) failed to lower "
                           "natively (%s); falling back to the XLA "
                           "encode/decode", bit, exc)
            ok = False
        _PROBE_OK[bit] = ok
    return ok


def fused_available(bit: int) -> bool:
    """Whether the fused Pallas path will serve this bitwidth under the
    current `PIPEEDGE_FUSED_QUANT` mode and backend."""
    if bit not in FUSED_BITS:
        return False
    mode = _mode()
    if mode in ("0", "off"):
        return False
    if mode in ("1", "on", "interpret"):
        return True
    # auto: native kernels on TPU only, behind the one-time probe
    return jax.default_backend() == "tpu" and _probe_native(bit)


def _interpret() -> bool:
    return _mode() == "interpret"


def encode_outerdim(x: jax.Array, bit: int,
                    mode: str = "original") -> quant_ops.QuantizedTensor:
    """Per-outer-item encode through the fused kernel when available,
    else the XLA ops — bit-identical either way."""
    if bit and mode == "original" and fused_available(bit):
        return fused_encode_outerdim(x, bit, interpret=_interpret())
    return quant_ops.tensor_encode_outerdim(x, bit, mode)


def decode_outerdim(enc: quant_ops.QuantizedTensor) -> jax.Array:
    """Inverse of `encode_outerdim` (same dispatch rule)."""
    if enc.bit and fused_available(enc.bit):
        return fused_decode_outerdim(enc, interpret=_interpret())
    return quant_ops.tensor_decode_outerdim(enc)
