"""Block-scaled int8 matmul: the compute half of the quantization story.

Every quantized path before this one moves bytes (DCN edges, ICI
collectives, KV ship) while the math stays bf16/f32. This kernel runs the
matmul itself on int8 operands: per-output-channel symmetric weight scales,
per-(row, k-block) symmetric activation scales, int8 x int8 -> int32
accumulation on the MXU (`preferred_element_type=jnp.int32`), dequant in
the epilogue. The k-blocking matters for accuracy: one activation outlier
only poisons its own 128-wide block instead of the whole row (the same
block-scaling rationale as ops/qcollectives.py's codec).

Grid is (m, n, k) with k innermost, so the f32 VMEM scratch accumulator is
zeroed at k==0 and the per-channel weight scale + bias epilogue fires at
the last k step (`@pl.when`) — the canonical sequential-k accumulate shape.
The per-k-block activation scale is applied as each int32 partial product
lands in the accumulator, which is what makes the scales per-BLOCK rather
than per-row: s_x[m, kb] * s_w[n] * (x_q[m, kb*bk:...] @ w_q[...]).

Mode selection (`PIPEEDGE_INT8_MATMUL`, mirroring ops/fused_quant.py):
- `auto` (default): native Pallas kernel on TPU behind a one-time
  lowering+parity probe; the block-scaled XLA reference path elsewhere
  (same math, so CPU CI and the recipe run the identical quantization).
- `interpret`: Pallas kernel in interpret mode — the CPU CI path that
  keeps the kernel's math honest without TPU hardware.
- `1`/`0`: force the kernel / force the XLA reference.

The wire tunnel (`wire_dense`): an 8-bit `QuantizedTensor` coming off the
DCN edge codec (ops/quant.py affine layout: x = q/255*scale + shift per
outer item) is consumed DIRECTLY by the next stage's first matmul — the
packed bytes are unpacked, recentered to signed int8 (q - 128), and fed to
the same block-scaled kernel; the affine correction folds into a rank-1
epilogue term:

    y = (scale/255) * (q-128) @ W  +  (128*scale/255 + shift) * colsum(W)

so the activation never round-trips through a dequantized f32 tensor
between one stage's MXU and the next's. The producer side needs no new
code: the stage's last matmul emits f32 that the existing fused quant
epilogue (ops/fused_quant.py, bit-identical to the wire codec) packs in
the same jit.
"""
from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import quant as quant_ops
from ._blocks import pick_block

logger = logging.getLogger(__name__)

ENV_INT8_MATMUL = "PIPEEDGE_INT8_MATMUL"

# default k-block width: one lane tile — fine enough that a single
# activation outlier saturates only 128 values, coarse enough that the
# scale sidecar stays 1/128th of the activation bytes
DEFAULT_BLOCK_K = 128


# --------------------------------------------------------------------------
# quantizers (shared by the kernel path, the XLA reference, and calibration)
# --------------------------------------------------------------------------

def quantize_weight(w: jax.Array):
    """Per-output-channel symmetric int8: scale[n] = amax(w[:, n]) / 127.

    All-zero channels get scale 1 (their quantized column is all zeros, so
    any non-zero scale decodes them exactly); round-half-even matches the
    wire codec's rounding.
    """
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0)
    scale = jnp.where(amax > 0, amax / 127.0, jnp.float32(1))
    w_q = jnp.clip(jnp.round(wf / scale[None, :]), -127, 127).astype(jnp.int8)
    return w_q, scale


def quantize_act_blocks(x: jax.Array, block_k: int):
    """Per-(row, k-block) symmetric int8 over [M, K] activations.

    Returns (x_q int8 [M, K], x_scale f32 [M, K//block_k]). All-zero
    blocks get scale 1; saturating outliers clip at +/-127 (the clamp
    calibration in utils/calibrate.py bounds how often that happens).
    """
    m, k = x.shape
    kb = k // block_k
    xf = x.astype(jnp.float32).reshape(m, kb, block_k)
    amax = jnp.max(jnp.abs(xf), axis=2)
    scale = jnp.where(amax > 0, amax / 127.0, jnp.float32(1))
    x_q = jnp.clip(jnp.round(xf / scale[:, :, None]),
                   -127, 127).astype(jnp.int8)
    return x_q.reshape(m, k), scale


# --------------------------------------------------------------------------
# the kernel and its XLA reference
# --------------------------------------------------------------------------

def _matmul_kernel(x_ref, xs_ref, w_ref, ws_ref, o_ref, acc_ref):
    """One (m, n) tile, accumulated over the innermost k grid dimension.

    x_ref  [bm, bk] int8      xs_ref [bm, 1]  f32 (this k-block's scales)
    w_ref  [bk, bn] int8      ws_ref [1, bn]  f32 (per-channel scales)
    o_ref  [bm, bn] f32       acc_ref [bm, bn] f32 VMEM scratch
    """
    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    prod = jax.lax.dot_general(
        x_ref[...], w_ref[...],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    acc_ref[...] += prod.astype(jnp.float32) * xs_ref[...]

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _epilogue():
        o_ref[...] = acc_ref[...] * ws_ref[...]


@functools.partial(jax.jit, static_argnames=("block_k", "interpret"))
def matmul_pallas(x_q: jax.Array, x_scale: jax.Array, w_q: jax.Array,
                  w_scale: jax.Array, block_k: int,
                  interpret: bool = False) -> jax.Array:
    """Block-scaled int8 matmul via the Pallas kernel. [M,K]x[K,N] -> f32."""
    m, k = x_q.shape
    n = w_q.shape[1]
    if k % block_k:
        raise ValueError(f"K={k} not divisible by block_k={block_k}")
    bm = pick_block(m, 128)
    bn = pick_block(n, 128)
    grid = (m // bm, n // bn, k // block_k)
    return pl.pallas_call(
        _matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, block_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bm, 1), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((block_k, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x_q, x_scale, w_q, w_scale.reshape(1, n))


def matmul_xla(x_q: jax.Array, x_scale: jax.Array, w_q: jax.Array,
               w_scale: jax.Array, block_k: int) -> jax.Array:
    """Same block-scaled math as the kernel, in plain XLA ops.

    Used as the parity reference in tests and as the dispatch fallback off
    TPU — int8 dots with int32 accumulation lower fine on CPU, they just
    don't hit an MXU.
    """
    m, k = x_q.shape
    n = w_q.shape[1]
    kb = k // block_k
    prod = jax.lax.dot_general(
        x_q.reshape(m, kb, block_k).transpose(1, 0, 2),
        w_q.reshape(kb, block_k, n),
        dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32)                    # [kb, m, n]
    y = jnp.sum(prod.astype(jnp.float32) * x_scale.T[:, :, None], axis=0)
    return y * w_scale[None, :]


# --------------------------------------------------------------------------
# dispatch (the fused_quant mode/probe idiom)
# --------------------------------------------------------------------------

def _mode() -> str:
    return os.getenv(ENV_INT8_MATMUL, "auto").strip().lower()


_PROBE_OK = None


def _probe_native() -> bool:
    """One-time native lowering + parity probe: Mosaic rejecting the kernel
    (or producing different math) degrades to the XLA reference."""
    global _PROBE_OK
    if _PROBE_OK is None:
        try:
            rng = np.random.default_rng(0)
            x = jnp.asarray(rng.normal(size=(8, 256)), jnp.float32)
            w = jnp.asarray(rng.normal(size=(256, 128)), jnp.float32)
            x_q, x_s = quantize_act_blocks(x, 128)
            w_q, w_s = quantize_weight(w)
            got = matmul_pallas(x_q, x_s, w_q, w_s, 128, interpret=False)
            ref = matmul_xla(x_q, x_s, w_q, w_s, 128)
            ok = bool(jnp.allclose(got, ref, rtol=1e-5, atol=1e-4))
            if not ok:
                logger.warning("int8 matmul probe: native kernel differs "
                               "from the XLA reference; falling back")
            _PROBE_OK = ok
        except Exception as exc:  # noqa: BLE001 - Mosaic lowering errors
            logger.warning("int8 matmul probe failed to lower natively "
                           "(%s); falling back to the XLA reference", exc)
            _PROBE_OK = False
    return _PROBE_OK


def kernel_available() -> bool:
    """Whether `matmul_q` will run the Pallas kernel under the current
    `PIPEEDGE_INT8_MATMUL` mode and backend."""
    mode = _mode()
    if mode in ("0", "off", "xla"):
        return False
    if mode in ("1", "on", "interpret"):
        return True
    return jax.default_backend() == "tpu" and _probe_native()


def matmul_q(x_q: jax.Array, x_scale: jax.Array, w_q: jax.Array,
             w_scale: jax.Array, block_k: int) -> jax.Array:
    """Dispatch seam: Pallas kernel when available, XLA reference else —
    identical block-scaled math either way."""
    if kernel_available():
        return matmul_pallas(x_q, x_scale, w_q, w_scale, block_k,
                             interpret=_mode() == "interpret")
    return matmul_xla(x_q, x_scale, w_q, w_scale, block_k)


# --------------------------------------------------------------------------
# layer entry points
# --------------------------------------------------------------------------

def int8_dense(x: jax.Array, w: jax.Array, b=None, *,
               block_k: int = DEFAULT_BLOCK_K, clamp_alpha=None,
               out_dtype=None) -> jax.Array:
    """y = x @ w (+ b) with int8 compute, over [..., K] activations.

    `clamp_alpha` (from the calibration sidecar, utils/calibrate.py) clips
    activations to the Banner-optimal +/-alpha before quantization so a
    rare outlier doesn't stretch its block's scale; None skips the clip.
    Weights are quantized per-channel at trace time — under jit with
    traced params that recomputes per call, which XLA fuses but does not
    cache; serving paths that care pre-fold via `quantize_weight`.
    """
    orig_shape = x.shape
    k = orig_shape[-1]
    n = w.shape[1]
    x2 = x.reshape(-1, k)
    bk = pick_block(k, block_k)
    if clamp_alpha is not None:
        alpha = jnp.float32(clamp_alpha)
        x2 = jnp.clip(x2.astype(jnp.float32), -alpha, alpha)
    x_q, x_scale = quantize_act_blocks(x2, bk)
    w_q, w_scale = quantize_weight(w)
    y = matmul_q(x_q, x_scale, w_q, w_scale, bk)
    if b is not None:
        y = y + b
    if out_dtype is None:
        out_dtype = x.dtype
    return y.reshape(*orig_shape[:-1], n).astype(out_dtype)


def wire_dense(p, enc: quant_ops.QuantizedTensor, *,
               block_k: int = DEFAULT_BLOCK_K,
               out_dtype=jnp.float32) -> jax.Array:
    """Consume an 8-bit wire `QuantizedTensor` directly in an int8 matmul.

    The consumer-side half of the stage-seam tunnel: instead of
    decode_outerdim -> f32 dense, the packed bytes feed the MXU as-is.
    Exactness contract (tests/test_int8_matmul.py): the activation side is
    EXACT — the affine identity below loses nothing vs decoding first —
    so the only deviation from `dense(p, decode_outerdim(enc))` is the
    per-channel weight quantization, identical to what `int8_dense` does
    mid-stage.

        x = q/255*scale + shift   (per outer item; ops/quant.py layout)
        y = (scale/255) * ((q-128) @ W_deq)
            + (128*scale/255 + shift) * colsum(W_deq) + b
    """
    if enc.bit != 8:
        raise ValueError(f"wire_dense consumes 8-bit payloads, got bit="
                         f"{enc.bit}")
    shape = enc.shape                       # [items, ..., K]
    items = shape[0]
    k = shape[-1]
    n_per_item = int(np.prod(shape[1:]))
    rows_per_item = n_per_item // k
    m = items * rows_per_item
    n = p["w"].shape[1]
    # unpack uint32 words -> byte values 0..255, per item (the
    # quant_ops._unpack_bits layout: value i at word i//4, offset (i%4)*8)
    shifts = (jnp.arange(4, dtype=jnp.uint32) * 8)[None, None, :]
    vals = (enc.data[:, :, None] >> shifts) & jnp.uint32(0xFF)
    q = vals.reshape(items, -1)[:, :n_per_item]
    qc = (q.astype(jnp.int32) - 128).astype(jnp.int8).reshape(m, k)
    bk = pick_block(k, block_k)
    s = enc.scale.astype(jnp.float32) / 255.0              # [items]
    s_row = jnp.repeat(s, rows_per_item)                   # [m]
    x_scale = jnp.broadcast_to(s_row[:, None], (m, k // bk))
    w_q, w_scale = quantize_weight(p["w"])
    y = matmul_q(qc, x_scale, w_q, w_scale, bk)
    colsum = jnp.sum(w_q.astype(jnp.int32), axis=0).astype(jnp.float32) \
        * w_scale                                          # [n] = colsum(W_deq)
    corr = 128.0 * s + enc.shift.astype(jnp.float32)       # [items]
    y = y + jnp.repeat(corr, rows_per_item)[:, None] * colsum[None, :]
    y = y + p["b"]
    return y.reshape(*shape[:-1], n).astype(out_dtype)
