"""Fused attention as a Pallas TPU kernel.

The reference materializes full [S, S] attention scores in HF torch modules
(SURVEY.md §5.7). Under XLA the scores still round-trip HBM for long
sequences; this kernel keeps each query block's scores resident in VMEM,
streaming over key/value blocks with an online (log-sum-exp) softmax — the
flash-attention recipe mapped to the MXU/VPU split (matmuls on the MXU,
max/exp/rescale on the VPU).

Grid: (batch*heads, query blocks); the K/V sequence loop runs inside the
kernel with running (max, sum, accumulator) scratch in VMEM, so HBM traffic
is O(S*D) instead of O(S^2).

`fused_attention` falls back to the plain XLA einsum path on non-TPU
backends (Pallas interpret mode is used in tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._blocks import pick_block

_NEG_INF = -1e30


def _attention_kernel(q_ref, k_ref, v_ref, o_ref, *, kv_block: int,
                      scale: float, valid_len: int, causal: bool = False):
    """One (batch*head, q-block) cell: stream K/V blocks with online softmax.

    `valid_len` masks zero-padded key positions (sequence lengths are padded
    to the TPU sublane multiple of 8 by the wrapper). `causal` additionally
    masks future keys and skips K/V blocks entirely past this q-block's
    causal frontier (the streaming loop stops early, so the lower-triangle
    work is ~halved).
    """
    q = q_ref[0].astype(jnp.float32)          # [q_blk, D]
    seq_len = k_ref.shape[1]
    n_kv = seq_len // kv_block
    q_blk = q.shape[0]
    q_start = pl.program_id(1) * q_blk

    def body(i, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(i * kv_block, kv_block), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * kv_block, kv_block), :].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [q_blk, kv_blk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (q_blk, kv_block), 0)
            k_pos = i * kv_block + jax.lax.broadcasted_iota(
                jnp.int32, (q_blk, kv_block), 1)
            scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)
        if valid_len != seq_len:
            k_pos = i * kv_block + jax.lax.broadcasted_iota(
                jnp.int32, (q_blk, kv_block), 1)
            scores = jnp.where(k_pos < valid_len, scores, _NEG_INF)
        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(scores - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((q_blk,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q_blk,), jnp.float32)
    acc0 = jnp.zeros((q_blk, q_ref.shape[2]), jnp.float32)
    if causal:
        # last K/V block any row of this q-block may attend to
        n_kv_eff = jnp.minimum((q_start + q_blk + kv_block - 1) // kv_block,
                               n_kv)
    else:
        n_kv_eff = n_kv
    _, l, acc = jax.lax.fori_loop(0, n_kv_eff, body, (m0, l0, acc0))
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


# one block resolver across the fused kernels (ops/_blocks.py)
_pick_block = pick_block


@functools.partial(jax.jit, static_argnames=("q_block", "kv_block",
                                             "causal", "interpret"))
def fused_attention_bhsd(q: jax.Array, k: jax.Array, v: jax.Array,
                         q_block: int = 128, kv_block: int = 128,
                         causal: bool = False,
                         interpret: bool = False) -> jax.Array:
    """Fused attention over [BH, S, D] tensors (already head-flattened)."""
    bh, seq_len, d = q.shape
    scale = 1.0 / (d ** 0.5)
    # pad the sequence to the TPU sublane multiple (8); padded keys masked
    pad = (-seq_len) % 8
    s_pad = seq_len + pad
    if pad:
        widths = ((0, 0), (0, pad), (0, 0))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    q_blk = _pick_block(s_pad, q_block)
    kv_blk = _pick_block(s_pad, kv_block)
    grid = (bh, s_pad // q_blk)
    kernel = functools.partial(_attention_kernel, kv_block=kv_blk, scale=scale,
                               valid_len=seq_len, causal=causal)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, q_blk, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, s_pad, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, q_blk, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)
    return out[:, :seq_len, :] if pad else out


def fused_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    q_block: int = 128, kv_block: int = 128,
                    causal: bool = False, interpret: bool = False) -> jax.Array:
    """Fused attention over [B, S, H, D] tensors; returns the same layout."""
    b, s, h, d = q.shape

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out = fused_attention_bhsd(flat(q), flat(k), flat(v), q_block=q_block,
                               kv_block=kv_block, causal=causal,
                               interpret=interpret)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def attention_is_supported() -> bool:
    """Pallas lowers natively on TPU; elsewhere only interpret mode works."""
    return jax.default_backend() == "tpu"
