"""ctypes wrapper for the native quantized wire codec (native/quantpack.cpp).

Bit-compatible with the XLA ops in `pipeedge_tpu.ops.quant` (same packing
layout and 'original'-mode math), so a payload may be encoded natively on one
host and decoded by the XLA path on another: packed words/scale/shift are
bit-identical for the wire bitwidths (<= 16, the adaptive ladder's range —
reference runtime.py:142-153); decodes agree to f32 rounding (the
quantization error itself is orders of magnitude larger). Used by the DCN
runtime to keep wire encode/decode off the accelerator after device
readback; callers check `available()` and fall back to the XLA ops when no
native toolchain exists — no behavioral difference, only speed.
"""
from __future__ import annotations

import ctypes
import logging
import os
from typing import Optional, Sequence, Tuple

import numpy as np

from ..utils.threads import make_lock

logger = logging.getLogger(__name__)

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), 'native', 'build', 'libquantpack.so')

_lib = None
_lib_lock = make_lock("native_quant.lib")
_load_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH):
            # the scheduler's on-demand cmake build also produces the codec;
            # key the staleness check on OUR artifact, not the sched binary
            from ..sched.scheduler import build_native
            build_native(artifact=_LIB_PATH)
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as exc:
            logger.warning("native quant codec unavailable: %s", exc)
            _load_failed = True
            return None
        lib.qp_abi_version.restype = ctypes.c_int
        if lib.qp_abi_version() != 1:
            logger.warning("native quant codec ABI mismatch; ignoring")
            _load_failed = True
            return None
        u32p = np.ctypeslib.ndpointer(np.uint32, flags='C_CONTIGUOUS')
        f32p = np.ctypeslib.ndpointer(np.float32, flags='C_CONTIGUOUS')
        lib.qp_packed_words.restype = ctypes.c_int64
        lib.qp_packed_words.argtypes = [ctypes.c_int64, ctypes.c_int]
        lib.qp_encode_f32.restype = None
        lib.qp_encode_f32.argtypes = [f32p, ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int, u32p, f32p, f32p]
        lib.qp_decode_f32.restype = None
        lib.qp_decode_f32.argtypes = [u32p, ctypes.c_int64, ctypes.c_int64,
                                      ctypes.c_int, f32p, f32p, f32p]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the native codec is loadable (builds it on first call)."""
    return _load() is not None


def encode_outerdim(x: np.ndarray, bit: int) \
        -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Quantize each item along the leading axis (native equivalent of
    ops.quant.tensor_encode_outerdim): returns (packed [b, words] uint32,
    scale [b] f32, shift [b] f32)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native quant codec unavailable")
    if not 0 < bit <= 16:
        raise ValueError("native codec supports wire bitwidths 1..16")
    x = np.ascontiguousarray(x, dtype=np.float32)
    b = x.shape[0]
    n = int(np.prod(x.shape[1:], dtype=np.int64))
    words = lib.qp_packed_words(n, bit)
    packed = np.empty((b, words), np.uint32)
    scale = np.empty((b,), np.float32)
    shift = np.empty((b,), np.float32)
    lib.qp_encode_f32(x.reshape(b, n), b, n, bit, packed, scale, shift)
    return packed, scale, shift


def decode_outerdim(packed: np.ndarray, scale: np.ndarray, shift: np.ndarray,
                    shape: Sequence[int], bit: int) -> np.ndarray:
    """Inverse of `encode_outerdim`; `shape` is the full logical shape
    including the leading (microbatch) axis."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native quant codec unavailable")
    if not 0 < bit <= 16:
        raise ValueError("native codec supports wire bitwidths 1..16")
    shape = tuple(int(s) for s in shape)
    b = shape[0]
    n = int(np.prod(shape[1:], dtype=np.int64))
    packed = np.ascontiguousarray(packed, dtype=np.uint32)
    out = np.empty((b, n), np.float32)
    lib.qp_decode_f32(packed.reshape(b, -1), b, n, bit,
                      np.ascontiguousarray(scale, np.float32),
                      np.ascontiguousarray(shift, np.float32), out)
    return out.reshape(shape)
