"""Shared Pallas block-size selection for the TPU kernels in this package.

Every fused kernel faces the same question: the largest lane/sublane block
that (a) is a multiple of 8 (the TPU sublane width, guide: tiling
constraints), (b) divides the padded extent so the grid needs no ragged
masking, and (c) does not exceed a preferred size chosen for VMEM. The
attention kernels (`attention.py`), the int8 decode-attention kernels
(`decode_attention.py`), and the fused quant epilogue kernels
(`fused_quant.py`) all use this one resolver — one definition of "legal
block" instead of three drifting copies.
"""
from __future__ import annotations


def pick_block(width: int, preferred: int = 128) -> int:
    """Largest multiple of 8 (TPU sublane) <= `preferred` that divides
    `width`; falls back to the full width (always a legal block)."""
    block = min(preferred, width) // 8 * 8
    while block >= 8:
        if width % block == 0:
            return block
        block -= 8
    return width
