"""XLA-friendly ops: quantization/bit-packing, clamping, attention."""

from . import clamp, quant  # noqa: F401
