"""EQuARX-style quantized collectives for the intra-stage (ICI) plane.

The int8/int4 wire path used to stop at the DCN edge (`comm/wire.py`): every
intra-stage TP `psum` (two full-width allreduces per Megatron block,
`parallel/tensor.py`) and the sequence-parallel `all_gather`
(`parallel/spmd.py`) still moved exact-width activations over ICI. This
module pushes the wire-bits path inward, per "EQuARX: Efficient Quantized
AllReduce in XLA" (arxiv 2506.17615, PAPERS.md):

- `qpsum`: quantized allreduce = per-shard block-scaled int8/int4 encode ->
  ring reduce-scatter in quantized form with a WIDENED (f32) accumulator
  (each hop dequantizes, folds in the local chunk at full precision, and
  re-encodes only the payload that travels) -> quantized all-gather of the
  reduced chunks, each encoded ONCE. The chunk a device reduces stays exact
  f32 on that device; every remote chunk carries bounded quantization error
  (`qpsum_error_bound`).
- `qall_gather`: each shard is encoded once and forwarded n-1 hops; the
  local shard stays exact.

Both are shard_map-body functions over a named mesh axis, built purely on
`jax.lax.ppermute` — the one collective primitive available across every
jax this tree supports (utils/jax_compat.py bridges the shard_map entry
point itself; no psum_scatter/all_gather-with-custom-reduction exists on
0.4.x shard_map, so the ring IS the portable implementation, exactly the
fallback EQuARX describes for pre-collective-quantization XLA).

Block scaling reuses the repo's own codec: a chunk reshaped to
[n_blocks, block] IS an outer-dim batch, so the block-scaled encode is
`fused_quant.encode_outerdim` — the Pallas-fused kernel when enabled, the
XLA ops otherwise, bit-identical either way. The optional Banner clamp
(`ops/clamp.py`) bounds each collective's quantization step under the
Laplace activation model — the per-collective error-budget knob
(docs/QUANT_COLLECTIVES.md).

Observability: collectives execute inside XLA, so per-execution host spans
are impossible; instead every qpsum/qall_gather call records its static
per-execution wire footprint in a trace-time tally. Drivers call
`record_collectives()` after a run to fold the tally into `collective`
telemetry spans (name `{kind}{bit}:{wire_bytes}`) and the pre-declared
`pipeedge_collective_bits_total{collective,bits}` counter —
`tools/trace_report.py` folds these into the per-stage bits-moved section
that separates ICI-collective traffic from DCN-edge traffic.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .. import telemetry
from ..telemetry.metrics import REGISTRY
from ..utils import jax_compat
from . import clamp as clamp_ops
from . import fused_quant
from . import quant as quant_ops

# bitwidths a quantized collective accepts (0 = exact passthrough)
QCOLLECTIVE_BITS = (0, 4, 8)

# values per scale/shift pair: small enough that one outlier only poisons
# its own block, large enough that the f32 scale/shift metadata stays ~3%
# of the int8 payload
DEFAULT_BLOCK = 256

COLLECTIVE_BITS_TOTAL = REGISTRY.counter(
    "pipeedge_collective_bits_total",
    "wire bits moved by quantized intra-stage collectives, per collective "
    "kind and bitwidth (per-device ring traffic)")
# pre-declared label matrix (docs/OBSERVABILITY.md; pipelint PL501): the
# full kind x bitwidth domain renders before the first increment
for _kind in ("psum", "all_gather"):
    for _bit in (4, 8):
        COLLECTIVE_BITS_TOTAL.declare(collective=_kind, bits=str(_bit))


# -- trace-time wire-footprint tally -------------------------------------

# every qpsum/qall_gather CALL (i.e. traced site) appends one entry:
# {kind, bit, n_shards, wire_bytes, raw_bytes} where wire_bytes is what ONE
# device sends per execution of the site (all ring hops, packed words +
# scale/shift metadata) and raw_bytes is what the exact f32 ring equivalent
# would send — their ratio is the site's wire reduction
_TRACE_TALLY: List[Dict] = []


def reset_trace_tally() -> None:
    """Clear the tally (drivers call this before building a program)."""
    _TRACE_TALLY.clear()


def trace_tally() -> List[Dict]:
    """Snapshot of the traced collective sites since the last reset."""
    return [dict(t) for t in _TRACE_TALLY]


def _enc_bytes_per_chunk(chunk: int, block: int, bit: int) -> int:
    """Wire bytes of one block-scaled encoded chunk: packed words + the
    per-block f32 scale/shift pair."""
    n_blocks = chunk // block
    return n_blocks * (quant_ops.packed_words(block, bit) * 4 + 8)


def _tally(kind: str, bit: int, n_shards: int, hops: int, chunk: int,
           block: int) -> None:
    _TRACE_TALLY.append({
        "kind": kind, "bit": bit, "n_shards": n_shards,
        "wire_bytes": hops * _enc_bytes_per_chunk(chunk, block, bit),
        "raw_bytes": hops * chunk * 4,
    })


def record_collectives(executions: int = 1,
                       stage: Optional[int] = None) -> Dict:
    """Fold the trace tally into telemetry + /metrics after a run.

    For each traced collective site: one instant `collective` span named
    `{kind}{bit}:{wire_bytes}` (trace_report's bits-moved section parses
    the name) and `pipeedge_collective_bits_total` incremented by the
    site's per-execution wire bits x `executions` — the caller's estimate
    of how many times each traced site actually ran (e.g. microbatches x
    blocks for the SPMD pipeline). Returns a summary record benches embed.
    """
    now = time.monotonic_ns()
    wire_bits = 0
    raw_bits = 0
    for t in _TRACE_TALLY:
        site_bytes = t["wire_bytes"] * executions
        site_bits = site_bytes * 8
        wire_bits += site_bits
        raw_bits += t["raw_bytes"] * 8 * executions
        # instant span per site, name = {kind}{bit}:{run-total wire bytes}
        # — report.analyze_spans parses the name into the per-stage
        # bits-moved section (ICI-collective bytes vs DCN-edge time)
        telemetry.record("collective", f"{t['kind']}{t['bit']}:"
                         f"{site_bytes}", now, now, stage=stage)
        COLLECTIVE_BITS_TOTAL.inc(amount=site_bits,
                                  collective=t["kind"], bits=str(t["bit"]))
    return {"sites": len(_TRACE_TALLY), "executions": executions,
            "wire_bits_total": wire_bits, "raw_bits_total": raw_bits,
            "wire_reduction": (round(raw_bits / wire_bits, 3)
                               if wire_bits else None)}


# -- the collectives -----------------------------------------------------

def _check_bit(bit: int) -> None:
    if bit not in QCOLLECTIVE_BITS:
        raise ValueError(f"quantized collectives support bits "
                         f"{QCOLLECTIVE_BITS}, got {bit}")


def _block_encode(chunk: jax.Array, bit: int,
                  block: int) -> quant_ops.QuantizedTensor:
    """Block-scaled encode of a flat [m] chunk (m % block == 0): each
    `block`-value group gets its own scale/shift — a reshaped outer-dim
    batch through the fused/XLA dispatch seam."""
    return fused_quant.encode_outerdim(chunk.reshape(-1, block), bit)


def _block_decode(enc: quant_ops.QuantizedTensor) -> jax.Array:
    return fused_quant.decode_outerdim(enc).reshape(-1)


def _ring_fwd(tree, axis_name: str, n: int):
    perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.tree_util.tree_map(
        lambda t: jax.lax.ppermute(t, axis_name, perm), tree)


def qpsum(x: jax.Array, axis_name: str, bit: int, *,
          block: int = DEFAULT_BLOCK, clamp: bool = False) -> jax.Array:
    """Quantized allreduce over a shard_map mesh axis (EQuARX-style).

    bit=0 is the exact `jax.lax.psum`. Otherwise: the flat tensor splits
    into n per-device chunks (zero-padded to n x block alignment); a ring
    reduce-scatter moves block-scaled int`bit` payloads with an f32
    accumulator (each hop: dequant, + local chunk, re-encode); a quantized
    ring all-gather then broadcasts each reduced chunk, encoded once.
    Result dtype follows `x`; internal accumulation is always f32 (wider
    than a bf16 psum — the EQuARX widened-accumulator contract).

    `clamp=True` applies the Banner Laplace clamp (`ops/clamp.py`) to the
    local addend first, trading bounded bias for a smaller quantization
    step — the per-collective error-budget knob. `qpsum_error_bound` gives
    the worst-case |quantized - exact| for the unclamped path.
    """
    _check_bit(bit)
    if bit == 0:
        return jax.lax.psum(x, axis_name)
    n = jax_compat.axis_size(axis_name)
    if n == 1:
        return x
    orig_shape, orig_dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    if clamp:
        flat = clamp_ops.clamp_banner2019_laplace(flat, bit)
    m = flat.shape[0]
    chunk = block * (-(-m // (n * block)))
    total = n * chunk
    if total > m:
        flat = jnp.concatenate([flat, jnp.zeros((total - m,), jnp.float32)])
    chunks = flat.reshape(n, chunk)
    idx = jax.lax.axis_index(axis_name)

    def local(j):
        return jax.lax.dynamic_index_in_dim(chunks, j % n, axis=0,
                                            keepdims=False)

    # ring reduce-scatter, widened accumulator: at step s device i forwards
    # the partial sum of chunk (i - s) mod n and folds chunk (i - s - 1)
    # mod n of its own addend into what arrives
    send = local(idx)
    for s in range(n - 1):
        recv = _block_decode(_ring_fwd(_block_encode(send, bit, block),
                                       axis_name, n))
        send = recv + local(idx - s - 1)
    own = send                       # full sum of chunk (idx + 1) mod n

    # quantized all-gather of the reduced chunks: each encoded ONCE, so a
    # remote chunk carries exactly one quantization error and the locally
    # reduced chunk stays exact f32
    out = jnp.zeros((n, chunk), jnp.float32)
    own_pos = (idx + 1) % n

    def place(buf, piece, j):
        return jax.lax.dynamic_update_slice(buf, piece[None], (j % n, 0))

    out = place(out, own, own_pos)
    cur = _block_encode(own, bit, block)
    for k in range(1, n):
        cur = _ring_fwd(cur, axis_name, n)
        # after k hops this device holds the chunk reduced by (idx - k)
        out = place(out, _block_decode(cur), own_pos - k)
    _tally("psum", bit, n, 2 * (n - 1), chunk, block)
    return out.reshape(total)[:m].reshape(orig_shape).astype(orig_dtype)


def qall_gather(x: jax.Array, axis_name: str, bit: int, *, axis: int = 0,
                tiled: bool = True, block: int = DEFAULT_BLOCK,
                clamp: bool = False) -> jax.Array:
    """Quantized all-gather over a shard_map mesh axis.

    bit=0 is the exact `jax.lax.all_gather`. Otherwise each device
    block-scale-encodes its shard ONCE and the packed payload rides n-1
    ring hops; the local shard stays exact. `tiled=True` concatenates the
    shards along `axis` (the `jax.lax.all_gather(..., tiled=True)`
    contract the sequence-parallel pipeline uses); `tiled=False` stacks a
    new leading `axis` dimension.
    """
    _check_bit(bit)
    if bit == 0:
        return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    n = jax_compat.axis_size(axis_name)
    if n == 1:
        return x if tiled else jnp.expand_dims(x, axis)
    orig_dtype = x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    if clamp:
        flat = clamp_ops.clamp_banner2019_laplace(flat, bit)
    m = flat.shape[0]
    pad = block * (-(-m // block)) - m
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    idx = jax.lax.axis_index(axis_name)

    pieces = jnp.zeros((n,) + x.shape, jnp.float32)

    def place(buf, piece, j):
        return jax.lax.dynamic_update_slice(
            buf, piece[None], (j % n,) + (0,) * x.ndim)

    # the local shard enters exact (not its quantized roundtrip)
    pieces = place(pieces, x.astype(jnp.float32), idx)
    cur = _block_encode(flat, bit, block)
    for k in range(1, n):
        cur = _ring_fwd(cur, axis_name, n)
        piece = _block_decode(cur)[:m].reshape(x.shape)
        pieces = place(pieces, piece, idx - k)
    _tally("all_gather", bit, n, n - 1, m + pad, block)
    parts = [pieces[j].astype(orig_dtype) for j in range(n)]
    if tiled:
        return jnp.concatenate(parts, axis=axis)
    return jnp.stack(parts, axis=axis)


def qpsum_error_bound(shard_absrange: float, bit: int, n_shards: int,
                      block: int = DEFAULT_BLOCK) -> float:
    """Conservative worst-case |qpsum - psum| per element (unclamped).

    Each reduce-scatter hop s quantizes a partial sum of s+1 shard chunks
    whose per-block range is at most (s+1) x `shard_absrange`; the gather
    hop quantizes the full n-shard sum. A block-scaled encode's round-off
    is half a step = range / (2^bit - 1) / 2. Summing the n-1 RS hops and
    the single AG encode, then doubling for float round-off slack:

        2 * (sum_{s=1}^{n-1} s + n) * R / (2 (2^bit - 1))

    where R = `shard_absrange` (max - min of any one shard's block).
    """
    del block  # the bound holds per block; range is the caller's worst block
    levels = float((1 << bit) - 1)
    hops = sum(range(1, n_shards)) + n_shards
    return 2.0 * hops * shard_absrange / (2.0 * levels) + 1e-5
