"""Quantized activation encode/decode as jit-able XLA ops.

Capability parity with the reference QuantPipe subsystem
(/root/reference/src/pipeedge/quantization/basic_op.py:6-176), redesigned for
TPU/XLA:

- The reference quantizes on CPU with numpy (uint32 bit-packing via vectorized
  shifts, basic_op.py:38-90) and ships a 5-element list of dynamically-shaped
  torch tensors over TCP. Here, everything is a pure jittable function with
  *static* shapes: the wire format is a `QuantizedTensor` pytree holding one
  fixed-shape packed uint32 buffer plus per-item scale/shift scalars; the
  bitwidth and logical shape are static (pytree aux data), so a pipeline edge
  compiles to a fixed signature and the pack/unpack lowers to vectorized
  integer shifts on the VPU, fusing with the producing/consuming matmuls.
- `compression factor` = 32/bit, same discrete bitwidths {2,4,6,8,16,32}
  (reference basic_op.py:109-111, runtime.py:142-153).

Quantization math (parity with basic_op.py:114-143 'original' mode):
  shift = min(x); scale = max(x - shift); q = round((x-shift)/scale * (2^b-1));
  decode: q/(2^b-1) * scale + shift.
Each item along the leading (microbatch) axis is quantized independently
(`*_outerdim`, basic_op.py:166-176) via vmap.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Discrete bitwidths the runtime's adaptive policies select among
# (reference runtime.py:142-153). 0 means "no quantization".
SUPPORTED_BITS = (0, 1, 2, 3, 4, 5, 6, 8, 16, 32)


def compression_factor(bit: int) -> float:
    """Data-size improvement for a bitwidth > 0 (reference basic_op.py:109-111)."""
    return 32.0 / bit


def packed_words(n_values: int, bit: int) -> int:
    """Number of uint32 words needed to pack `n_values` `bit`-wide ints.

    Values per word = floor(32/bit) (reference basic_op.py:43 `enc_ratio`).
    """
    per_word = 32 // bit
    return -(-n_values // per_word)  # ceil div


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QuantizedTensor:
    """Fixed-shape quantized activation payload (the inter-stage wire format).

    Replaces the reference's `[comm_tensor, shape, scale_factor, shift,
    quant_bit]` list (basic_op.py:143): `shape` and `bit` are static aux data
    (known at trace time), so only `data`/`scale`/`shift` travel as arrays.

    data:  uint32 [leading..., words] packed payload (float32 view when bit=0)
    scale: float32 [leading...] per-item scale factors
    shift: float32 [leading...] per-item shifts
    shape: static logical shape of the decoded tensor
    bit:   static bitwidth (0 = passthrough)
    """
    data: jax.Array
    scale: jax.Array
    shift: jax.Array
    shape: Tuple[int, ...]
    bit: int

    def tree_flatten(self):
        return (self.data, self.scale, self.shift), (self.shape, self.bit)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scale, shift = children
        shape, bit = aux
        return cls(data=data, scale=scale, shift=shift, shape=shape, bit=bit)

    @property
    def nbytes_wire(self) -> int:
        """Bytes on the wire (packed payload only)."""
        return int(np.prod(self.data.shape)) * 4


def _pack_bits(ints: jax.Array, bit: int) -> jax.Array:
    """Pack a flat uint32 array of `bit`-wide values into uint32 words.

    Vectorized shift-and-or, value i goes to word i//per_word at bit offset
    (i % per_word)*bit — same layout as reference basic_op.py:38-55, but
    expressed as a single reshaped shift/or that XLA maps onto the VPU.
    """
    per_word = 32 // bit
    n = ints.shape[0]
    n_pad = packed_words(n, bit) * per_word - n
    padded = jnp.concatenate([ints, jnp.zeros((n_pad,), jnp.uint32)]) if n_pad else ints
    grouped = padded.reshape(-1, per_word)
    shifts = (jnp.arange(per_word, dtype=jnp.uint32) * bit)[None, :]
    shifted = grouped << shifts
    return jax.lax.reduce(shifted, np.uint32(0), jax.lax.bitwise_or, dimensions=[1])


def _unpack_bits(words: jax.Array, bit: int, n_values: int) -> jax.Array:
    """Inverse of `_pack_bits` (reference basic_op.py:58-90)."""
    per_word = 32 // bit
    shifts = (jnp.arange(per_word, dtype=jnp.uint32) * bit)[None, :]
    mask = np.uint32((1 << bit) - 1) if bit < 32 else np.uint32(0xFFFFFFFF)
    values = (words[:, None] >> shifts) & mask
    return values.reshape(-1)[:n_values]


def _quantize_item(x: jax.Array, bit: int, mode: str) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Quantize one tensor to (packed_words, scale, shift).

    'original' mode: q = round(x01 * (2^b - 1)); 'modified': q = clip(floor(
    x01 * 2^b), 0, 2^b - 1) (reference basic_op.py:17-29). Zero-range inputs
    (scale == 0) are guarded to avoid the reference's NaN behavior.
    """
    flat = x.reshape(-1).astype(jnp.float32)
    shift = jnp.min(flat)
    scale = jnp.max(flat - shift)
    safe_scale = jnp.where(scale > 0, scale, jnp.float32(1))
    x01 = (flat - shift) / safe_scale
    if mode == "original":
        levels = float((1 << bit) - 1)
        q = jnp.round(x01 * levels)
    elif mode == "modified":
        levels = float(1 << bit)
        q = jnp.clip(jnp.floor(x01 * levels), 0.0, levels - 1.0)
    else:
        raise ValueError(f"mode must be 'original' or 'modified', got {mode!r}")
    return _pack_bits(q.astype(jnp.uint32), bit), scale, shift


def _dequantize_item(words: jax.Array, scale: jax.Array, shift: jax.Array,
                     shape: Sequence[int], bit: int) -> jax.Array:
    levels = float((1 << bit) - 1)
    n = int(np.prod(shape))
    q = _unpack_bits(words, bit, n).astype(jnp.float32)
    return (q / levels * scale + shift).reshape(shape)


@partial(jax.jit, static_argnames=("bit", "mode"))
def tensor_encode(x: jax.Array, bit: int, mode: str = "original") -> QuantizedTensor:
    """Encode a whole tensor with one scale/shift (reference basic_op.py:114-143)."""
    shape = tuple(x.shape)
    if bit == 0:
        return QuantizedTensor(data=x, scale=jnp.float32(1), shift=jnp.float32(0),
                               shape=shape, bit=0)
    data, scale, shift = _quantize_item(x, bit, mode)
    return QuantizedTensor(data=data, scale=scale, shift=shift, shape=shape, bit=bit)


@jax.jit
def tensor_decode(enc: QuantizedTensor) -> jax.Array:
    """Decode `tensor_encode` output (reference basic_op.py:146-163)."""
    if enc.bit == 0:
        return enc.data
    return _dequantize_item(enc.data, enc.scale, enc.shift, enc.shape, enc.bit)


@partial(jax.jit, static_argnames=("bit", "mode"))
def tensor_encode_outerdim(x: jax.Array, bit: int, mode: str = "original") -> QuantizedTensor:
    """Quantize each item along the leading (microbatch) axis independently.

    Parity with reference basic_op.py:166-170, but as a single vmapped kernel
    instead of a Python loop + stack.
    """
    shape = tuple(x.shape)
    if bit == 0:
        b = shape[0]
        return QuantizedTensor(data=x, scale=jnp.ones((b,), jnp.float32),
                               shift=jnp.zeros((b,), jnp.float32), shape=shape, bit=0)
    data, scale, shift = jax.vmap(lambda t: _quantize_item(t, bit, mode))(x)
    return QuantizedTensor(data=data, scale=scale, shift=shift, shape=shape, bit=bit)


@jax.jit
def tensor_decode_outerdim(enc: QuantizedTensor) -> jax.Array:
    """Decode `tensor_encode_outerdim` output (reference basic_op.py:173-176)."""
    if enc.bit == 0:
        return enc.data
    item_shape = enc.shape[1:]
    return jax.vmap(
        lambda w, sc, sh: _dequantize_item(w, sc, sh, item_shape, enc.bit)
    )(enc.data, enc.scale, enc.shift)
