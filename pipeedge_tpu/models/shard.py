"""Generic shard execution engine shared by all model families.

The reference runs a Python list of per-block torch sub-modules
(vit.py:161-170, bert.py:142-151, deit.py:157-166). Here a shard executes as:

    embeddings? -> partial head block -> lax.scan over stacked full blocks
                -> partial tail block -> final norm/pooler/classifier?

One compiled block body serves any pipeline depth (compile time independent of
layer count), parameters for the scanned blocks live as one stacked pytree
(leading axis = block), and partial blocks at the shard edges — which exist
because PipeEdge partitions at sublayer granularity — are unrolled explicitly.

A model family plugs in three pure functions via `FamilySpec`:
  embed(embed_params, raw_input, cfg)        -> hidden [B, S, D]
  sublayer(block_params, sub, payload, cfg)  -> payload (tensor or 2-tuple)
  finalize(final_params, hidden, cfg)        -> model output
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

from . import BlockSlice, ShardConfig, plan_shard
from .layers import TransformerConfig

ShardData = Any  # jax.Array | tuple[jax.Array, jax.Array]


@dataclasses.dataclass(frozen=True)
class FamilySpec:
    """Pure-function hooks defining a model family (vit/bert/deit/gpt2/
    llama). The two optional hooks plug a decoder family into the
    KV-cache decode subsystem (parallel/decode.py): `cached_block_step`
    replaces the default GPT-2-shaped block step, `decode_embed` the
    default wte+wpe single-token embedding."""
    name: str
    embed: Callable[[Dict, Any, TransformerConfig], jax.Array]
    sublayer: Callable[[Dict, int, ShardData, TransformerConfig], ShardData]
    finalize: Callable[[Dict, jax.Array, TransformerConfig], jax.Array]
    cached_block_step: Any = None    # (p, x, bcache, pos, cfg, prefill)
    decode_embed: Any = None         # (embed_params, tok, pos) -> [B, 1, D]
    span_embed: Any = None           # (embed_params, tok [B,K], pos) ->
    #                                  [B, K, D] (speculative verify span)
    # attention reads absolute positions (RoPE): chunk-local attention
    # overrides (sequence-parallel cores) would rotate at wrong offsets
    position_dependent_attention: bool = False
    # tensor-parallel decode variants (per-device bodies under shard_map;
    # families whose cached step differs from the GPT-2 shape supply them)
    tp_cached_block_step: Any = None  # (+ axis=...) kwarg
    tp_finalize: Any = None           # (pf, hidden, cfg, axis) vocab-sharded
    # sequence-parallel prefill block for position-dependent families:
    # (p, x, bcache, cfg, axis, core, cache_gather) -> (x, bcache)
    sp_prefill_block_step: Any = None
    # sublayers that LEAD with a dense and accept an 8-bit wire
    # `QuantizedTensor` as the payload's first tensor (the int8
    # stage-seam tunnel, parallel/pipeline.py + ops/int8_matmul.py)
    wire_subs: tuple = ()


def _apply_slice(family: FamilySpec, block_params: Dict, data: ShardData,
                 blk: BlockSlice, cfg: TransformerConfig) -> ShardData:
    for sub in blk.sublayers():
        data = family.sublayer(block_params, sub, data, cfg)
    return data


def shard_apply(family: FamilySpec, cfg: TransformerConfig,
                shard_config: ShardConfig, params: Dict,
                data: ShardData) -> ShardData:
    """Apply one layer-range shard. Pure; jit with cfg/shard_config static.

    The full blocks run in one of two layouts, detected from the params:

    - stacked pytree [n_blocks, ...] -> `lax.scan` (compile time independent
      of depth; required by the SPMD driver's stage-stacked sharding);
    - tuple of per-block pytrees (see `unstack_blocks`) -> unrolled loop.
      Measured ~6% faster on ViT-Large/TPU: the scan's loop-carried
      dynamic-slice of the stacked weights is real HBM traffic each
      iteration, while unrolled blocks read their own arrays directly (a
      static in-jit slice of the stacked layout does NOT recover this — XLA
      materializes the slices). Compile is also ~20% faster at depth 24.
    """
    plan = plan_shard(shard_config)
    if shard_config.is_first:
        data = family.embed(params["embeddings"], data, cfg)
    if plan.head is not None:
        data = _apply_slice(family, params["head"], data, plan.head, cfg)
    if plan.full_ids:
        full = BlockSlice(0, 0, 3)
        blocks = params["blocks"]
        if isinstance(blocks, (tuple, list)):
            for block_params in blocks:
                data = _apply_slice(family, block_params, data, full, cfg)
        else:
            def body(carry, block_params):
                return _apply_slice(family, block_params, carry, full, cfg), None

            data, _ = jax.lax.scan(body, data, blocks)
    if plan.tail is not None:
        data = _apply_slice(family, params["tail"], data, plan.tail, cfg)
    if shard_config.is_last:
        data = family.finalize(params["final"], data, cfg)
    return data


def make_shard_fn(family: FamilySpec, cfg: TransformerConfig,
                  shard_config: ShardConfig) -> Callable[[Dict, ShardData], ShardData]:
    """Return a jit-compiled `fn(params, data)` for this shard signature."""
    return jax.jit(partial(shard_apply, family, cfg, shard_config))


def stack_blocks(block_param_list):
    """Stack per-block parameter pytrees into one scanned pytree [L, ...]."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *block_param_list)


def unstack_blocks(params: Dict) -> Dict:
    """Convert a shard's stacked 'blocks' pytree to a tuple of per-block
    pytrees, selecting the unrolled execution path in `shard_apply` (see its
    docstring for the measured TPU win). No-op for shards without full
    blocks or already-unstacked params."""
    blocks = params.get("blocks")
    if blocks is None or isinstance(blocks, (tuple, list)):
        return params
    n = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    out = dict(params)
    out["blocks"] = tuple(
        jax.tree_util.tree_map(lambda x, i=i: x[i], blocks) for i in range(n))
    return out


def build_shard_params(shard_config: ShardConfig,
                       get_embed: Callable[[], Dict],
                       get_block: Callable[[int, tuple], Dict],
                       get_final: Callable[[], Dict]) -> Dict:
    """Assemble a shard's parameter pytree from per-component getters.

    `get_block(block_id, sublayers)` returns only the parameters the listed
    sublayers need — a shard never materializes weights outside its layer
    range, mirroring the reference's lazy npz slicing (vit.py:93-118).
    """
    plan = plan_shard(shard_config)
    params: Dict = {}
    if shard_config.is_first:
        params["embeddings"] = get_embed()
    if plan.head is not None:
        params["head"] = get_block(plan.head.block_id, tuple(plan.head.sublayers()))
    if plan.full_ids:
        params["blocks"] = stack_blocks(
            [get_block(b, (0, 1, 2, 3)) for b in plan.full_ids])
    if plan.tail is not None:
        params["tail"] = get_block(plan.tail.block_id, tuple(plan.tail.sublayers()))
    if shard_config.is_last:
        params["final"] = get_final()
    return params
