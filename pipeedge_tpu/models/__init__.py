"""Model sharding core: layer-range shard configs and partition arithmetic.

Capability parity with /root/reference/src/pipeedge/models/__init__.py (the
`ModuleShard`/`ModuleShardConfig` abstractions), redesigned for JAX: a shard
is not a module object but a *(static plan, parameter pytree, pure apply
function)* triple. The same 1-based layer numbering applies: each transformer
block counts as 4 schedulable sublayers (attention, attention-output+residual,
MLP-up, MLP-down+residual — reference vit.py:41-70), so ViT-Base has 48
"layers". Any contiguous `[layer_start, layer_end]` range is a valid shard,
including mid-block cuts, whose inter-stage payload is then a 2-tensor tuple
(reference transformers/__init__.py:5).
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax

SUBLAYERS_PER_BLOCK = 4


@dataclasses.dataclass(frozen=True)
class ShardConfig:
    """Static description of a layer-range shard (reference models/__init__.py:9-22).

    Layers are 1-based and inclusive, counted in sublayers (4 per block).
    `is_first` adds the embedding layer; `is_last` adds the final norm /
    pooler / classifier head.
    """
    layer_start: int
    layer_end: int
    is_first: bool = False
    is_last: bool = False

    def __post_init__(self):
        if not 1 <= self.layer_start <= self.layer_end:
            raise ValueError(
                f"invalid layer range [{self.layer_start}, {self.layer_end}]")


@dataclasses.dataclass(frozen=True)
class BlockSlice:
    """One transformer block's contribution to a shard: sublayers [sub_start, sub_end]."""
    block_id: int   # 0-based transformer block index
    sub_start: int  # 0..3
    sub_end: int    # 0..3

    @property
    def is_full(self) -> bool:
        return self.sub_start == 0 and self.sub_end == 3

    def sublayers(self) -> range:
        return range(self.sub_start, self.sub_end + 1)


def block_slices(layer_start: int, layer_end: int) -> Tuple[BlockSlice, ...]:
    """Decompose a 1-based sublayer range into per-block slices.

    Same arithmetic as the reference shard builders (vit.py:99-113):
    block = ceil(layer/4) - 1, sublayer = (layer-1) % 4.
    """
    slices = []
    layer_curr = layer_start
    while layer_curr <= layer_end:
        block_id = math.ceil(layer_curr / SUBLAYERS_PER_BLOCK) - 1
        sub_start = (layer_curr - 1) % SUBLAYERS_PER_BLOCK
        if block_id == math.ceil(layer_end / SUBLAYERS_PER_BLOCK) - 1:
            sub_end = (layer_end - 1) % SUBLAYERS_PER_BLOCK
        else:
            sub_end = SUBLAYERS_PER_BLOCK - 1
        slices.append(BlockSlice(block_id, sub_start, sub_end))
        layer_curr += sub_end - sub_start + 1
    return tuple(slices)


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    """Static execution plan for a shard: partial head block, scanned full
    blocks, partial tail block.

    The reference builds a Python list of per-block sub-shards and loops over
    them (vit.py:99-113, 161-170); under jit we instead stack the full blocks'
    parameters and `lax.scan` over them (one compiled block body regardless of
    depth), with at most two partially-applied blocks at the shard edges.
    """
    head: Optional[BlockSlice]
    full_ids: Tuple[int, ...]
    tail: Optional[BlockSlice]

    @property
    def slices(self) -> Tuple[BlockSlice, ...]:
        out = []
        if self.head is not None:
            out.append(self.head)
        out.extend(BlockSlice(b, 0, 3) for b in self.full_ids)
        if self.tail is not None:
            out.append(self.tail)
        return tuple(out)


def plan_shard(shard_config: ShardConfig) -> ShardPlan:
    """Compute the head/scan/tail plan for a layer range."""
    slices = block_slices(shard_config.layer_start, shard_config.layer_end)
    head = None
    tail = None
    if not slices[0].is_full:
        head = slices[0]
        slices = slices[1:]
    if slices and not slices[-1].is_full:
        tail = slices[-1]
        slices = slices[:-1]
    assert all(s.is_full for s in slices)
    return ShardPlan(head=head, full_ids=tuple(s.block_id for s in slices), tail=tail)


def edge_arity(layer_end: int) -> int:
    """Number of tensors in the payload leaving a shard ending at `layer_end`.

    A cut after sublayer 0 (attention) or 2 (MLP-up) leaves a (hidden,
    residual) 2-tuple in flight; after sublayer 1 or 3 the residual has been
    folded in and a single tensor flows (reference vit.py:56-70,
    transformers/__init__.py:5).
    """
    sub = (layer_end - 1) % SUBLAYERS_PER_BLOCK
    return 2 if sub in (0, 2) else 1


def get_microbatch_size(shard_data, verify: bool = False) -> int:
    """Microbatch size of a shard payload (reference models/__init__.py:39-49)."""
    if not isinstance(shard_data, (tuple, list)):
        shard_data = (shard_data,)
    ubatch_size = 0 if len(shard_data) == 0 else len(shard_data[0])
    if verify:
        for tensor in shard_data:
            assert len(tensor) == ubatch_size
    return ubatch_size


def num_params(params) -> int:
    """Total parameter count of a pytree."""
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def params_bytes(params) -> int:
    """Total parameter bytes of a pytree."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(params))
