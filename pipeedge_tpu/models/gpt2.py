"""GPT-2 model family: causal-decoder shards with the 4-way sublayer split.

NEW capability beyond the reference, which ships only encoder families
(ViT/DeiT/BERT — /root/reference/model_cfg.py:24-43). A causal decoder slots
into the same shard/pipeline machinery because a GPT-2 block is pre-LN like
ViT's (reference vit.py:55-70), so the 4-sublayer cut points carry over:
  sub 0: ln_1 -> causal self-attention       payload becomes (ctx, residual)
  sub 1: attn output proj + residual         payload becomes hidden
  sub 2: ln_2 -> MLP-up + GeLU(tanh)         payload becomes (mlp_h, residual)
  sub 3: MLP-down + residual                 payload becomes hidden
First shard: token + learned position embeddings. Last shard: final
LayerNorm + tied LM head -> per-token vocab logits.

Parameters reuse the ViT sublayer names (ln_before/q/k/v/attn_out/ln_after/
mlp_up/mlp_down), so the Megatron TP spec table and the SPMD driver's
stacked-block sharding apply unchanged; only the block body differs (causal
mask, tanh-approximate GeLU — HF `gelu_new`).

Weight format: HF `GPT2LMHeadModel`/`GPT2Model` state-dict npz. HF stores
these as `Conv1D` with kernels already [in, out] (unlike `nn.Linear`), so no
transpose; the fused `c_attn` [D, 3D] kernel splits into q/k/v at load time
(the same trick DeiT uses for its fused qkv, deit.py:131-156).
"""
from __future__ import annotations

from typing import Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from . import ShardConfig
from .layers import TransformerConfig, dense, gelu_new, layer_norm, self_attention
from .shard import FamilySpec, build_shard_params

SUBLAYER_PARAMS = {
    0: ("ln_before", "q", "k", "v"),
    1: ("attn_out",),
    2: ("ln_after", "mlp_up"),
    3: ("mlp_down",),
}

# routed-FFN (cfg.n_experts > 0) variant: the whole switch-FFN lives in
# sublayer 2 (capacity routing cannot span a pipeline cut); sublayer 3 is
# the parameter-free residual add
MOE_SUBLAYER_PARAMS = {
    0: ("ln_before", "q", "k", "v"),
    1: ("attn_out",),
    2: ("ln_after", "moe"),
    3: (),
}


def embed(p: Dict, input_ids: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Token embedding + learned position embedding (HF `GPT2Model.forward`)."""
    seq_len = input_ids.shape[1]
    return jnp.take(p["wte"], input_ids, axis=0) + p["wpe"][:seq_len][None]


def sublayer(p: Dict, sub: int, data, cfg: TransformerConfig,
             attention_fn=None):
    """One of the 4 schedulable sublayers (pre-LN block, causal attention).

    `attention_fn(qkv_params, x, num_heads, causal=...)` overrides the
    attention core (sequence-parallel execution swaps in causal ring
    attention, parallel/spmd.py)."""
    if sub == 0:
        normed = layer_norm(p["ln_before"], data, cfg.layer_norm_eps)
        ctx = (attention_fn or self_attention)(
            {"q": p["q"], "k": p["k"], "v": p["v"]}, normed,
            cfg.num_attention_heads, causal=True)
        return (ctx, data)
    if sub == 1:
        ctx, skip = data
        return dense(p["attn_out"], ctx) + skip
    if sub == 2:
        normed = layer_norm(p["ln_after"], data, cfg.layer_norm_eps)
        if cfg.n_experts:
            # switch-FFN (Switch Transformer top-1): the whole routed
            # expert computation lives in sublayer 2 (capacity routing
            # cannot span a pipeline cut), so the sublayer-2 edge carries
            # (delta, residual) like the dense path's (mlp_h, residual)
            from ..parallel.expert import moe_ffn_delta
            delta = moe_ffn_delta(p["moe"], normed, cfg.n_experts,
                                  cfg.capacity_factor, act=gelu_new)
            return (delta, data)
        return (gelu_new(dense(p["mlp_up"], normed)), data)
    if sub == 3:
        mlp_h, skip = data
        if cfg.n_experts:
            return mlp_h + skip      # delta from sublayer 2 + residual
        return dense(p["mlp_down"], mlp_h) + skip
    raise ValueError(f"sublayer must be 0..3, got {sub}")


def finalize(p: Dict, hidden: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Final LayerNorm + LM head -> [B, S, vocab] logits (tied to wte)."""
    hidden = layer_norm(p["ln"], hidden, cfg.layer_norm_eps)
    return dense(p["head"], hidden)


FAMILY = FamilySpec(name="gpt2", embed=embed, sublayer=sublayer,
                    finalize=finalize)


def _a(x, dtype):
    return jnp.asarray(np.asarray(x), dtype=dtype)


def load_params(cfg: TransformerConfig, shard_config: ShardConfig,
                weights: Mapping, dtype=jnp.float32) -> Dict:
    """Build shard params from an HF GPT-2 state-dict npz.

    Accepts `GPT2LMHeadModel` keys (`transformer.`-prefixed + `lm_head.*`)
    and bare `GPT2Model` keys; the LM head falls back to the tied `wte`."""
    keys = set(weights.keys())
    if any(k.startswith("transformer.") for k in keys):
        sd = {k.removeprefix("transformer."): weights[k] for k in keys
              if k.startswith("transformer.")}
        if "lm_head.weight" in keys:
            sd["lm_head.weight"] = weights["lm_head.weight"]
    else:
        sd = weights if isinstance(weights, dict) else dict(weights.items())
    d = cfg.hidden_size

    def get_embed() -> Dict:
        return {"wte": _a(sd["wte.weight"], dtype),
                "wpe": _a(sd["wpe.weight"], dtype)}

    def get_block(block_id: int, subs: tuple) -> Dict:
        root = f"h.{block_id}."
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = {"scale": _a(sd[root + "ln_1.weight"], dtype),
                              "bias": _a(sd[root + "ln_1.bias"], dtype)}
            w = np.asarray(sd[root + "attn.c_attn.weight"])   # [D, 3D]
            b = np.asarray(sd[root + "attn.c_attn.bias"])     # [3D]
            for i, name in enumerate(("q", "k", "v")):
                p[name] = {"w": _a(w[:, i * d:(i + 1) * d], dtype),
                           "b": _a(b[i * d:(i + 1) * d], dtype)}
        if 1 in subs:
            p["attn_out"] = {"w": _a(sd[root + "attn.c_proj.weight"], dtype),
                             "b": _a(sd[root + "attn.c_proj.bias"], dtype)}
        if 2 in subs:
            p["ln_after"] = {"scale": _a(sd[root + "ln_2.weight"], dtype),
                             "bias": _a(sd[root + "ln_2.bias"], dtype)}
            if cfg.n_experts:
                p["moe"] = {
                    "router": {
                        "w": _a(sd[root + "moe.router.weight"], dtype),
                        "b": _a(sd[root + "moe.router.bias"], dtype)},
                    "experts": {
                        "mlp_up": {
                            "w": _a(sd[root + "moe.experts.c_fc.weight"], dtype),
                            "b": _a(sd[root + "moe.experts.c_fc.bias"], dtype)},
                        "mlp_down": {
                            "w": _a(sd[root + "moe.experts.c_proj.weight"], dtype),
                            "b": _a(sd[root + "moe.experts.c_proj.bias"], dtype)},
                    },
                }
            else:
                p["mlp_up"] = {"w": _a(sd[root + "mlp.c_fc.weight"], dtype),
                               "b": _a(sd[root + "mlp.c_fc.bias"], dtype)}
        if 3 in subs and not cfg.n_experts:
            p["mlp_down"] = {"w": _a(sd[root + "mlp.c_proj.weight"], dtype),
                             "b": _a(sd[root + "mlp.c_proj.bias"], dtype)}
        return p

    def get_final() -> Dict:
        head = sd.get("lm_head.weight", sd["wte.weight"])     # [V, D] tied
        return {"ln": {"scale": _a(sd["ln_f.weight"], dtype),
                       "bias": _a(sd["ln_f.bias"], dtype)},
                "head": {"w": _a(head, dtype).T,
                         "b": jnp.zeros((np.asarray(head).shape[0],), dtype)}}

    return build_shard_params(shard_config, get_embed, get_block, get_final)


def moe_state_dict(cfg: TransformerConfig, seed: int = 0) -> Dict:
    """Deterministic random full-model state dict for MoE configs, in the
    flat npz key layout `load_params` reads (`h.{i}.moe.*` for the routed
    FFN). No pretrained checkpoints exist for this synthetic family, so
    this is the weights-file story (save_model_weights.py --random)."""
    assert cfg.n_experts > 0
    rng = np.random.default_rng(seed)
    d, it, e = cfg.hidden_size, cfg.intermediate_size, cfg.n_experts

    def mat(*shape):
        return rng.normal(0, 0.02, size=shape).astype(np.float32)

    sd = {"wte.weight": mat(cfg.vocab_size, d),
          "wpe.weight": mat(cfg.max_position_embeddings, d),
          "ln_f.weight": np.ones(d, np.float32),
          "ln_f.bias": np.zeros(d, np.float32)}
    for i in range(cfg.num_hidden_layers):
        root = f"h.{i}."
        sd[root + "ln_1.weight"] = np.ones(d, np.float32)
        sd[root + "ln_1.bias"] = np.zeros(d, np.float32)
        sd[root + "attn.c_attn.weight"] = mat(d, 3 * d)
        sd[root + "attn.c_attn.bias"] = np.zeros(3 * d, np.float32)
        sd[root + "attn.c_proj.weight"] = mat(d, d)
        sd[root + "attn.c_proj.bias"] = np.zeros(d, np.float32)
        sd[root + "ln_2.weight"] = np.ones(d, np.float32)
        sd[root + "ln_2.bias"] = np.zeros(d, np.float32)
        sd[root + "moe.router.weight"] = mat(d, e)
        sd[root + "moe.router.bias"] = np.zeros(e, np.float32)
        sd[root + "moe.experts.c_fc.weight"] = mat(e, d, it)
        sd[root + "moe.experts.c_fc.bias"] = np.zeros((e, it), np.float32)
        sd[root + "moe.experts.c_proj.weight"] = mat(e, it, d)
        sd[root + "moe.experts.c_proj.bias"] = np.zeros((e, d), np.float32)
    return sd


def init_params(cfg: TransformerConfig, shard_config: ShardConfig,
                seed: int = 0, dtype=jnp.float32) -> Dict:
    """Random shard params with the same pytree structure as `load_params`."""
    rng = np.random.default_rng(seed)
    d, it = cfg.hidden_size, cfg.intermediate_size

    def mat(*shape):
        return jnp.asarray(rng.normal(0, 0.02, size=shape), dtype=dtype)

    def vec(n):
        return jnp.zeros((n,), dtype=dtype)

    def ln():
        return {"scale": jnp.ones((d,), dtype), "bias": vec(d)}

    def get_embed() -> Dict:
        return {"wte": mat(cfg.vocab_size, d),
                "wpe": mat(cfg.max_position_embeddings, d)}

    def get_block(block_id: int, subs: tuple) -> Dict:
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = ln()
            for name in ("q", "k", "v"):
                p[name] = {"w": mat(d, d), "b": vec(d)}
        if 1 in subs:
            p["attn_out"] = {"w": mat(d, d), "b": vec(d)}
        if 2 in subs:
            p["ln_after"] = ln()
            if cfg.n_experts:
                e = cfg.n_experts
                p["moe"] = {
                    "router": {"w": mat(d, e), "b": vec(e)},
                    "experts": {
                        "mlp_up": {"w": mat(e, d, it),
                                   "b": jnp.zeros((e, it), dtype)},
                        "mlp_down": {"w": mat(e, it, d),
                                     "b": jnp.zeros((e, d), dtype)},
                    },
                }
            else:
                p["mlp_up"] = {"w": mat(d, it), "b": vec(it)}
        if 3 in subs and not cfg.n_experts:
            p["mlp_down"] = {"w": mat(it, d), "b": vec(d)}
        return p

    def get_final() -> Dict:
        return {"ln": ln(), "head": {"w": mat(d, cfg.vocab_size),
                                     "b": vec(cfg.vocab_size)}}

    return build_shard_params(shard_config, get_embed, get_block, get_final)
