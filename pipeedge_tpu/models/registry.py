"""Model registry and shard factories (parity with /root/reference/model_cfg.py).

Same 9 supported models and layer counts (model_cfg.py:24-43), plus a
causal-decoder family (GPT-2/GPT-2-medium) the reference lacks; layer counts
are in sublayers (4 per transformer block). Unlike the reference, model
configs are local constants rather than `AutoConfig.from_pretrained` network
fetches (model_cfg.py:57-66), so everything works with zero egress; the
ViT-Huge num_labels=21843 override is baked in (model_cfg.py:62-66).
"""
from __future__ import annotations

import dataclasses
import logging
import os
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import ShardConfig
from .layers import TransformerConfig
from .shard import make_shard_fn, unstack_blocks
from . import bert as bert_mod
from . import deit as deit_mod
from . import gpt2 as gpt2_mod
from . import llama as llama_mod
from . import vit as vit_mod

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ModelEntry:
    name: str
    layers: int                  # sublayer count = 4 * blocks
    weights_file: str            # default npz filename (reference format)
    family: object               # module: vit_mod | bert_mod | deit_mod
    config: TransformerConfig


def _vit(name, layers, weights, hidden, blocks, heads, inter, labels,
         patch=16, img=224):
    return ModelEntry(name, layers, weights, vit_mod, TransformerConfig(
        model_type="vit", hidden_size=hidden, num_hidden_layers=blocks,
        num_attention_heads=heads, intermediate_size=inter, num_labels=labels,
        image_size=img, patch_size=patch))


def _bert(name, layers, weights, hidden, blocks, heads, inter, labels):
    return ModelEntry(name, layers, weights, bert_mod, TransformerConfig(
        model_type="bert", hidden_size=hidden, num_hidden_layers=blocks,
        num_attention_heads=heads, intermediate_size=inter, num_labels=labels,
        vocab_size=30522, max_position_embeddings=512))


def _deit(name, layers, weights, hidden, blocks, heads, inter):
    return ModelEntry(name, layers, weights, deit_mod, TransformerConfig(
        model_type="deit", hidden_size=hidden, num_hidden_layers=blocks,
        num_attention_heads=heads, intermediate_size=inter, num_labels=1000))


def _gpt2(name, layers, weights, hidden, blocks, heads, inter,
          vocab=50257, max_pos=1024, n_experts=0, capacity_factor=1.25):
    return ModelEntry(name, layers, weights, gpt2_mod, TransformerConfig(
        model_type="gpt2", hidden_size=hidden, num_hidden_layers=blocks,
        num_attention_heads=heads, intermediate_size=inter,
        layer_norm_eps=1e-5, vocab_size=vocab,
        max_position_embeddings=max_pos, n_experts=n_experts,
        capacity_factor=capacity_factor))


def _llama(name, layers, weights, hidden, blocks, heads, kv_heads, inter,
           vocab, max_pos, theta=10000.0, window=0):
    return ModelEntry(name, layers, weights, llama_mod, TransformerConfig(
        model_type="llama", hidden_size=hidden, num_hidden_layers=blocks,
        num_attention_heads=heads, num_kv_heads=kv_heads,
        intermediate_size=inter, layer_norm_eps=1e-5, vocab_size=vocab,
        max_position_embeddings=max_pos, rope_theta=theta,
        sliding_window=window))


_MODELS: Dict[str, ModelEntry] = {e.name: e for e in [
    _vit("google/vit-base-patch16-224", 48, "ViT-B_16-224.npz", 768, 12, 12, 3072, 1000),
    _vit("google/vit-large-patch16-224", 96, "ViT-L_16-224.npz", 1024, 24, 16, 4096, 1000),
    _vit("google/vit-huge-patch14-224-in21k", 128, "ViT-H_14.npz", 1280, 32, 16, 5120,
         21843, patch=14),
    _bert("bert-base-uncased", 48, "BERT-B.npz", 768, 12, 12, 3072, 0),
    _bert("bert-large-uncased", 96, "BERT-L.npz", 1024, 24, 16, 4096, 0),
    _bert("textattack/bert-base-uncased-CoLA", 48, "BERT-B-CoLA.npz", 768, 12, 12, 3072, 2),
    _deit("facebook/deit-base-distilled-patch16-224", 48, "DeiT_B_distilled.npz",
          768, 12, 12, 3072),
    _deit("facebook/deit-small-distilled-patch16-224", 48, "DeiT_S_distilled.npz",
          384, 12, 6, 1536),
    _deit("facebook/deit-tiny-distilled-patch16-224", 48, "DeiT_T_distilled.npz",
          192, 12, 3, 768),
    # causal-decoder family: beyond the reference's encoder-only list
    _gpt2("gpt2", 48, "GPT2.npz", 768, 12, 12, 3072),
    _gpt2("gpt2-medium", 96, "GPT2-M.npz", 1024, 24, 16, 4096),
    # synthetic switch-MoE decoder (top-1 routed FFN, 8 experts/block)
    _gpt2("pipeedge/gpt2-moe-8e", 48, "GPT2-MoE-8E.npz", 768, 12, 12, 3072,
          n_experts=8),
    # llama family: RoPE / RMSNorm / SwiGLU / grouped-query attention
    _llama("meta-llama/Llama-2-7b-hf", 128, "Llama-2-7B.npz", 4096, 32, 32,
           32, 11008, vocab=32000, max_pos=4096),
    _llama("meta-llama/Meta-Llama-3-8B", 128, "Llama-3-8B.npz", 4096, 32,
           32, 8, 14336, vocab=128256, max_pos=8192, theta=500000.0),
    # Mistral = the llama block with sliding-window attention (identical
    # HF state-dict layout; the window is a mask, not a weight change)
    _llama("mistralai/Mistral-7B-v0.1", 128, "Mistral-7B.npz", 4096, 32,
           32, 8, 14336, vocab=32000, max_pos=32768, window=4096),
    # tiny synthetic models for fast tests / CI (not in the reference's list)
    _vit("pipeedge/test-tiny-vit", 8, "test-tiny-vit.npz", 32, 2, 4, 64, 5,
         patch=4, img=16),
    _bert("pipeedge/test-tiny-bert", 8, "test-tiny-bert.npz", 32, 2, 4, 64, 2),
    _gpt2("pipeedge/test-tiny-gpt2", 8, "test-tiny-gpt2.npz", 32, 2, 4, 64,
          vocab=100, max_pos=64),
    _llama("pipeedge/test-tiny-llama", 8, "test-tiny-llama.npz", 32, 2, 4,
           2, 64, vocab=100, max_pos=64),
    _llama("pipeedge/test-tiny-mistral", 8, "test-tiny-mistral.npz", 32, 2,
           4, 2, 64, vocab=100, max_pos=64, window=4),
    # capacity_factor = n_experts -> no capacity drops: routing is then a
    # pure per-token top-1 gate, which is causal and batch-size-invariant,
    # so cached decode and split pipelines match the full forward exactly
    # (capacity-bounded models trade that exactness for bounded compute)
    _gpt2("pipeedge/test-tiny-moe", 8, "test-tiny-moe.npz", 32, 2, 4, 64,
          vocab=100, max_pos=64, n_experts=4, capacity_factor=4.0),
]}


def get_model_names() -> List[str]:
    """Available model names (model_cfg.py:45-47)."""
    return list(_MODELS.keys())


def get_model_entry(model_name: str) -> ModelEntry:
    return _MODELS[model_name]


def get_model_layers(model_name: str) -> int:
    """Total sublayer count (model_cfg.py:53-55)."""
    return _MODELS[model_name].layers


def get_model_config(model_name: str) -> TransformerConfig:
    """Static config (model_cfg.py:57-66, without the network fetch)."""
    return _MODELS[model_name].config


def get_model_default_weights_file(model_name: str) -> str:
    """Default weights filename (model_cfg.py:68-70)."""
    return _MODELS[model_name].weights_file


def make_shard_config(model_name: str, layer_start: int, layer_end: int) -> ShardConfig:
    """is_first/is_last derived from the global layer range (model_cfg.py:87-90)."""
    return ShardConfig(layer_start=layer_start, layer_end=layer_end,
                       is_first=layer_start == 1,
                       is_last=layer_end == get_model_layers(model_name))


def should_unroll_blocks(n_blocks: int) -> bool:
    """Execution-layout policy: unroll full blocks when the depth is within
    PIPEEDGE_UNROLL_BLOCKS (default 48, covering every registered model —
    unrolled runs ~6% faster and compiles faster on TPU; see
    shard.shard_apply). 0 disables unrolling (always scan)."""
    limit = int(os.getenv("PIPEEDGE_UNROLL_BLOCKS", "48"))
    return 0 < n_blocks <= limit


def module_shard_factory(model_name: str, model_file: Optional[str],
                         layer_start: int, layer_end: int, stage: int = 0,
                         dtype=jnp.float32,
                         params: Optional[Dict] = None,
                         unroll: Optional[bool] = None) \
        -> Tuple[Callable, Dict, ShardConfig]:
    """Build one pipeline stage: (jitted shard fn, params, shard config).

    Parity with model_cfg.py:80-95. `params` supplies a pre-restored
    parameter pytree (e.g. an Orbax stage checkpoint) and skips weight-file
    loading. Otherwise, if the weights file is missing, falls back to
    deterministic random initialization (same pytree structure) so the
    framework runs end-to-end with zero egress; a warning is logged since
    outputs then aren't pretrained.

    `unroll` selects the full-block execution layout (None = policy
    `should_unroll_blocks`); pass False where the stacked layout is
    required, e.g. params feeding the SPMD driver's stage stacking.
    """
    entry = _MODELS[model_name]
    if model_file is None:
        model_file = entry.weights_file
    shard_config = make_shard_config(model_name, layer_start, layer_end)
    if params is not None:
        params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, dtype=dtype
                                  if jnp.issubdtype(x.dtype, jnp.floating)
                                  else None), params)
    elif model_file and os.path.exists(model_file):
        with np.load(model_file) as weights:
            params = entry.family.load_params(entry.config, shard_config, weights,
                                              dtype=dtype)
    else:
        logger.warning("weights file %r not found for %s; using random init",
                       model_file, model_name)
        params = entry.family.init_params(entry.config, shard_config, dtype=dtype)
    blocks = params.get("blocks")
    if blocks is not None and not isinstance(blocks, (tuple, list)):
        n_blocks = jax.tree_util.tree_leaves(blocks)[0].shape[0]
        do_unroll = unroll if unroll is not None \
            else should_unroll_blocks(n_blocks)
        if do_unroll:
            params = unstack_blocks(params)
    fn = make_shard_fn(entry.family.FAMILY, entry.config, shard_config)
    logger.info("======= %s stage %d: layers [%d, %d] =======",
                model_name, stage, layer_start, layer_end)
    return fn, params, shard_config
