"""ViT model family: pure-function shards with the 4-way sublayer split.

Capability parity with /root/reference/src/pipeedge/models/transformers/vit.py.
Sublayer semantics match `ViTLayerShard.forward` (vit.py:55-70) exactly:
  sub 0: ln_before -> self-attention         payload becomes (ctx, residual)
  sub 1: output dense + residual add         payload becomes hidden
  sub 2: ln_after -> MLP-up + GeLU           payload becomes (mlp_h, residual)
  sub 3: MLP-down + residual add             payload becomes hidden
First shard prepends patch+cls+position embeddings; last shard applies the
final layernorm and (for classification) the head on the CLS token
(vit.py:115-118, 221-226).

Weight formats: Google's ViT `.npz` checkpoints (the reference's native
format, key map at vit.py:121-159) and HF `ViTModel`/`ViTForImageClassification`
state dicts (converted via `hf_to_npz_weights`). Kernels are stored [in, out].
"""
from __future__ import annotations

from typing import Dict, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import ShardConfig
from ..ops import quant as quant_ops
from .layers import TransformerConfig, dense, gelu, layer_norm, patchify, self_attention
from .shard import FamilySpec, build_shard_params

# Parameters needed per sublayer (mirror of reference vit.py:41-53).
SUBLAYER_PARAMS = {
    0: ("ln_before", "q", "k", "v"),
    1: ("attn_out",),
    2: ("ln_after", "mlp_up"),
    3: ("mlp_down",),
}


def embed(p: Dict, pixel_values: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Patch embedding (as one matmul) + CLS token + position embeddings.

    `pixel_values` is NCHW [B, C, H, W] for parity with the reference's HF
    feature-extractor inputs; transposed to NHWC internally for TPU layout.
    """
    x = jnp.transpose(pixel_values, (0, 2, 3, 1))
    patches = patchify(x, cfg.patch_size)
    hidden = dense(p["patch"], patches.astype(p["patch"]["w"].dtype))
    cls = jnp.broadcast_to(p["cls"], (hidden.shape[0], 1, cfg.hidden_size))
    hidden = jnp.concatenate([cls.astype(hidden.dtype), hidden], axis=1)
    return hidden + p["pos"].astype(hidden.dtype)


def sublayer(p: Dict, sub: int, data, cfg: TransformerConfig,
             attention_fn=None):
    """One of the 4 schedulable sublayers (reference vit.py:55-70).

    `attention_fn(qkv_params, x, num_heads)` overrides the attention core —
    the hook sequence-parallel execution uses to swap in ring attention
    over a mesh axis (parallel/spmd.py) without duplicating the block.

    Stage-seam tunnel: subs 1 and 3 lead with a dense, so when a stage
    boundary lands there the payload's leading tensor may arrive as an
    8-bit wire `QuantizedTensor` (parallel/pipeline.py leaves it encoded
    under the QuantizeCompute tunnel) — it feeds the int8 matmul directly
    via `wire_dense`, no dequant round-trip."""
    if sub == 0:
        normed = layer_norm(p["ln_before"], data, cfg.layer_norm_eps)
        if attention_fn is not None:
            ctx = attention_fn({"q": p["q"], "k": p["k"], "v": p["v"]},
                               normed, cfg.num_attention_heads)
        else:
            ctx = self_attention({"q": p["q"], "k": p["k"], "v": p["v"]},
                                 normed, cfg.num_attention_heads,
                                 tag_prefix="attn")
        return (ctx, data)
    if sub == 1:
        ctx, skip = data
        if isinstance(ctx, quant_ops.QuantizedTensor):
            from ..ops.int8_matmul import wire_dense
            return wire_dense(p["attn_out"], ctx,
                              out_dtype=skip.dtype) + skip
        return dense(p["attn_out"], ctx, tag="attn.out") + skip
    if sub == 2:
        normed = layer_norm(p["ln_after"], data, cfg.layer_norm_eps)
        return (gelu(dense(p["mlp_up"], normed, tag="mlp.up")), data)
    if sub == 3:
        mlp_h, skip = data
        if isinstance(mlp_h, quant_ops.QuantizedTensor):
            from ..ops.int8_matmul import wire_dense
            return wire_dense(p["mlp_down"], mlp_h,
                              out_dtype=skip.dtype) + skip
        return dense(p["mlp_down"], mlp_h, tag="mlp.down") + skip
    raise ValueError(f"sublayer must be 0..3, got {sub}")


def finalize(p: Dict, hidden: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Final layernorm; classifier head on CLS token when present."""
    hidden = layer_norm(p["ln"], hidden, cfg.layer_norm_eps)
    if "head" in p:
        return dense(p["head"], hidden[:, 0, :])
    return hidden


FAMILY = FamilySpec(name="vit", embed=embed, sublayer=sublayer,
                    finalize=finalize, wire_subs=(1, 3))


# --- weight loading -------------------------------------------------------

def _google_block_getter(weights: Mapping, cfg: TransformerConfig, dtype):
    """Per-block params from Google ViT npz keys (reference vit.py:137-159)."""
    d = cfg.hidden_size

    def get_block(block_id: int, subs: tuple) -> Dict:
        root = f"Transformer/encoderblock_{block_id}/"
        attn = root + "MultiHeadDotProductAttention_1/"
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = {"scale": _a(weights[root + "LayerNorm_0/scale"], dtype),
                              "bias": _a(weights[root + "LayerNorm_0/bias"], dtype)}
            for name, key in (("q", "query"), ("k", "key"), ("v", "value")):
                p[name] = {"w": _a(weights[attn + key + "/kernel"], dtype).reshape(d, d),
                           "b": _a(weights[attn + key + "/bias"], dtype).reshape(-1)}
        if 1 in subs:
            p["attn_out"] = {"w": _a(weights[attn + "out/kernel"], dtype).reshape(d, d),
                             "b": _a(weights[attn + "out/bias"], dtype).reshape(-1)}
        if 2 in subs:
            p["ln_after"] = {"scale": _a(weights[root + "LayerNorm_2/scale"], dtype),
                             "bias": _a(weights[root + "LayerNorm_2/bias"], dtype)}
            p["mlp_up"] = {"w": _a(weights[root + "MlpBlock_3/Dense_0/kernel"], dtype),
                           "b": _a(weights[root + "MlpBlock_3/Dense_0/bias"], dtype)}
        if 3 in subs:
            p["mlp_down"] = {"w": _a(weights[root + "MlpBlock_3/Dense_1/kernel"], dtype),
                             "b": _a(weights[root + "MlpBlock_3/Dense_1/bias"], dtype)}
        return p

    return get_block


def _a(x, dtype) -> jnp.ndarray:
    return jnp.asarray(np.asarray(x), dtype=dtype)


def load_params(cfg: TransformerConfig, shard_config: ShardConfig,
                weights: Mapping, dtype=jnp.float32) -> Dict:
    """Build shard params from a Google-format npz mapping (vit.py:121-159)."""

    def get_embed() -> Dict:
        kernel = np.asarray(weights["embedding/kernel"])  # [ph, pw, C, D]
        return {
            "cls": _a(weights["cls"], dtype),
            "pos": _a(weights["Transformer/posembed_input/pos_embedding"], dtype),
            "patch": {"w": _a(kernel.reshape(-1, kernel.shape[-1]), dtype),
                      "b": _a(weights["embedding/bias"], dtype)},
        }

    def get_final() -> Dict:
        p = {"ln": {"scale": _a(weights["Transformer/encoder_norm/scale"], dtype),
                    "bias": _a(weights["Transformer/encoder_norm/bias"], dtype)}}
        if cfg.num_labels > 0 and "head/kernel" in weights:
            p["head"] = {"w": _a(weights["head/kernel"], dtype),
                         "b": _a(weights["head/bias"], dtype)}
        return p

    return build_shard_params(shard_config, get_embed,
                              _google_block_getter(weights, cfg, dtype), get_final)


def hf_to_npz_weights(state_dict: Mapping, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    """Convert an HF ViT state dict to the Google-npz key scheme.

    Replaces the reference's `save_weights` download from
    storage.googleapis.com (vit.py:172-186) with a local conversion so
    checkpoints can come from any HF `ViTForImageClassification`/`ViTModel`.
    """
    sd = {k.removeprefix("vit."): np.asarray(v) for k, v in state_dict.items()}
    d = cfg.hidden_size
    nh = cfg.num_attention_heads
    out = {
        "cls": sd["embeddings.cls_token"],
        "Transformer/posembed_input/pos_embedding": sd["embeddings.position_embeddings"],
        # torch conv kernel [D, C, ph, pw] -> [ph, pw, C, D]
        "embedding/kernel": sd["embeddings.patch_embeddings.projection.weight"].transpose(2, 3, 1, 0),
        "embedding/bias": sd["embeddings.patch_embeddings.projection.bias"],
        "Transformer/encoder_norm/scale": sd["layernorm.weight"],
        "Transformer/encoder_norm/bias": sd["layernorm.bias"],
    }
    if "classifier.weight" in sd:
        out["head/kernel"] = sd["classifier.weight"].T
        out["head/bias"] = sd["classifier.bias"]
    for i in range(cfg.num_hidden_layers):
        hf_root = f"encoder.layer.{i}."
        # HF renamed attention.attention -> attention.self in some versions
        attn_prefix = None
        for cand in ("attention.attention.", "attention.self."):
            if hf_root + cand + "query.weight" in sd:
                attn_prefix = hf_root + cand
                break
        root = f"Transformer/encoderblock_{i}/"
        mha = root + "MultiHeadDotProductAttention_1/"
        out[root + "LayerNorm_0/scale"] = sd[hf_root + "layernorm_before.weight"]
        out[root + "LayerNorm_0/bias"] = sd[hf_root + "layernorm_before.bias"]
        for name in ("query", "key", "value"):
            # torch [out, in] -> flax [in, heads, head_dim]
            out[mha + name + "/kernel"] = sd[attn_prefix + name + ".weight"].T.reshape(d, nh, d // nh)
            out[mha + name + "/bias"] = sd[attn_prefix + name + ".bias"].reshape(nh, d // nh)
        out[mha + "out/kernel"] = sd[hf_root + "attention.output.dense.weight"].T.reshape(nh, d // nh, d)
        out[mha + "out/bias"] = sd[hf_root + "attention.output.dense.bias"]
        out[root + "LayerNorm_2/scale"] = sd[hf_root + "layernorm_after.weight"]
        out[root + "LayerNorm_2/bias"] = sd[hf_root + "layernorm_after.bias"]
        out[root + "MlpBlock_3/Dense_0/kernel"] = sd[hf_root + "intermediate.dense.weight"].T
        out[root + "MlpBlock_3/Dense_0/bias"] = sd[hf_root + "intermediate.dense.bias"]
        out[root + "MlpBlock_3/Dense_1/kernel"] = sd[hf_root + "output.dense.weight"].T
        out[root + "MlpBlock_3/Dense_1/bias"] = sd[hf_root + "output.dense.bias"]
    return out


# --- random init (benchmarks / tests without checkpoints) -----------------

def init_params(cfg: TransformerConfig, shard_config: ShardConfig,
                seed: int = 0, dtype=jnp.float32) -> Dict:
    """Random shard params with the same pytree structure as `load_params`."""
    rng = np.random.default_rng(seed)

    def mat(*shape):
        scale = 0.02
        return jnp.asarray(rng.normal(0, scale, size=shape), dtype=dtype)

    def vec(n):
        return jnp.zeros((n,), dtype=dtype)

    def ln():
        return {"scale": jnp.ones((cfg.hidden_size,), dtype), "bias": vec(cfg.hidden_size)}

    d, it = cfg.hidden_size, cfg.intermediate_size

    def get_block(block_id: int, subs: tuple) -> Dict:
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = ln()
            for name in ("q", "k", "v"):
                p[name] = {"w": mat(d, d), "b": vec(d)}
        if 1 in subs:
            p["attn_out"] = {"w": mat(d, d), "b": vec(d)}
        if 2 in subs:
            p["ln_after"] = ln()
            p["mlp_up"] = {"w": mat(d, it), "b": vec(it)}
        if 3 in subs:
            p["mlp_down"] = {"w": mat(it, d), "b": vec(d)}
        return p

    def get_embed() -> Dict:
        n_patch_in = cfg.patch_size * cfg.patch_size * cfg.num_channels
        return {"cls": mat(1, 1, d), "pos": mat(1, cfg.num_patches + 1, d),
                "patch": {"w": mat(n_patch_in, d), "b": vec(d)}}

    def get_final() -> Dict:
        p = {"ln": ln()}
        if cfg.num_labels > 0:
            p["head"] = {"w": mat(d, cfg.num_labels), "b": vec(cfg.num_labels)}
        return p

    return build_shard_params(shard_config, get_embed, get_block, get_final)
