"""LLaMA model family: RoPE/RMSNorm/SwiGLU/GQA decoder shards.

NEW capability beyond the reference (whose model list is encoder-only,
/root/reference/model_cfg.py:24-43) and beyond the GPT-2 family: the
modern decoder shape — rotary position embeddings instead of a learned
position table, RMSNorm instead of LayerNorm, a gated SwiGLU MLP, and
grouped-query attention (fewer K/V heads than query heads — the KV-cache
memory lever serving stacks rely on). It slots into the same 4-sublayer
cut discipline as every other family:
  sub 0: rms_norm -> RoPE'd GQA self-attention   payload becomes (ctx, residual)
  sub 1: attention output proj + residual        payload becomes hidden
  sub 2: rms_norm -> silu(gate) * up             payload becomes (mlp_h, residual)
  sub 3: MLP-down + residual                     payload becomes hidden
First shard: token embedding (no position table — positions live in the
rotation). Last shard: final RMSNorm + LM head.

KV-cache decoding: the family supplies its own cached block step
(`cached_block_step`) and single-token embed (`decode_embed`) through the
FamilySpec hooks, so `DecodePipeline` / the continuous batcher / the SPMD
wave decoder drive LLaMA unchanged. The cache stores POST-RoPE K at the
GQA head count ([blocks, B, T, kv_heads, Dh] — `cfg.kv_heads` sizes it),
and each step rotates only the new token's q/k at its position.

Weight format: HF `LlamaForCausalLM` state dict (`model.`-prefixed
`nn.Linear` kernels, stored [out, in] -> transposed to [in, out] at load;
no biases — zero vectors keep the {w, b} pytree shape shared with the
other families). The FORWARD-pipeline sequence-parallel attention
override is refused (those cores compute projections chunk-locally with
no global RoPE offset); the decode subsystem's sp PREFILL is supported
via `sp_prefill_block_step`, which pre-rotates q/k at global chunk
positions before the chunk-local core.
"""
from __future__ import annotations

from typing import Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from . import ShardConfig
from .layers import TransformerConfig, dense, rms_norm, rope_rotate
from .shard import FamilySpec, build_shard_params

SUBLAYER_PARAMS = {
    0: ("ln_before", "q", "k", "v"),
    1: ("attn_out",),
    2: ("ln_after", "mlp_gate", "mlp_up"),
    3: ("mlp_down",),
}


def _split_heads(y: jax.Array, n_heads: int) -> jax.Array:
    b, s, _ = y.shape
    return y.reshape(b, s, n_heads, -1)


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, kv_heads, Dh] -> [B, S, kv_heads * n_rep, Dh] (GQA groups)."""
    return x if n_rep == 1 else jnp.repeat(x, n_rep, axis=2)


def _window_keep(keep: jax.Array, q_pos, cfg: TransformerConfig):
    """Intersect a keep mask [S_q, S_k] with the sliding window: position
    q attends to k in (q - window, q] (Mistral semantics — the last
    `sliding_window` positions including itself). `q_pos` gives each
    query row's absolute position; no-op when the window is off."""
    if not cfg.sliding_window:
        return keep
    k_pos = jax.lax.broadcasted_iota(jnp.int32, keep.shape, 1)
    return keep & (k_pos > q_pos - cfg.sliding_window)


def _gqa_attend(q, k, v, cfg: TransformerConfig, keep=None,
                q_pos=None) -> jax.Array:
    """softmax(QK^T)V with GQA head repetition; `keep` optionally masks
    key positions ([S_q, S_k], decode path — pass `q_pos` [S_q, 1] so the
    sliding window can anchor to absolute positions), else causal (+
    window). Delegates the masked-softmax body to the decode subsystem's
    `_attend` — ONE copy of the attention numerics for both consumers."""
    from ..parallel.decode import _attend

    h = q.shape[2]
    k = _repeat_kv(k, h // k.shape[2])
    v = _repeat_kv(v, h // v.shape[2])
    if keep is None:                 # full forward: causal over [S, S]
        s_q, s_k = q.shape[1], k.shape[1]
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        keep = k_pos <= q_pos
    if q_pos is not None:
        keep = _window_keep(keep, q_pos, cfg)
    return _attend(q, k, v, keep, cfg)


def _qkv_rope(p: Dict, normed: jax.Array, cfg: TransformerConfig, pos):
    """Project + RoPE-rotate q/k (v unrotated) at positions `pos` [S]."""
    q = _split_heads(dense(p["q"], normed), cfg.num_attention_heads)
    k = _split_heads(dense(p["k"], normed), cfg.kv_heads)
    v = _split_heads(dense(p["v"], normed), cfg.kv_heads)
    return (rope_rotate(q, pos, cfg.rope_theta),
            rope_rotate(k, pos, cfg.rope_theta), v)


def embed(p: Dict, input_ids: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Token embedding only — positions live in the rotation."""
    return jnp.take(p["wte"], input_ids, axis=0)


def sublayer(p: Dict, sub: int, data, cfg: TransformerConfig,
             attention_fn=None):
    """One of the 4 schedulable sublayers (pre-RMSNorm block, RoPE GQA)."""
    if attention_fn is not None:
        raise NotImplementedError(
            "llama attention cores are position-dependent (RoPE); the "
            "sequence-parallel attention override is not supported")
    if sub == 0:
        normed = rms_norm(p["ln_before"], data, cfg.layer_norm_eps)
        pos = jnp.arange(normed.shape[1])
        q, k, v = _qkv_rope(p, normed, cfg, pos)
        return (_gqa_attend(q, k, v, cfg), data)
    if sub == 1:
        ctx, skip = data
        return dense(p["attn_out"], ctx) + skip
    if sub == 2:
        normed = rms_norm(p["ln_after"], data, cfg.layer_norm_eps)
        gated = jax.nn.silu(dense(p["mlp_gate"], normed).astype(
            jnp.float32)).astype(normed.dtype)
        return (gated * dense(p["mlp_up"], normed), data)
    if sub == 3:
        mlp_h, skip = data
        return dense(p["mlp_down"], mlp_h) + skip
    raise ValueError(f"sublayer must be 0..3, got {sub}")


def finalize(p: Dict, hidden: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Final RMSNorm + LM head -> [B, S, vocab] logits."""
    return dense(p["head"], rms_norm(p["ln"], hidden, cfg.layer_norm_eps))


def _abs_q_pos(pos, s: int, prefill: bool):
    """Absolute query positions [S_q, 1] for the cached attention's
    sliding-window anchor: query row i sits at pos + i — prefill binds
    pos=0 (the prompt rows), a decode step has s=1 at the traced `pos`,
    and a span (speculative verify) step covers [pos, pos+s)."""
    del prefill  # pos + offset covers every mode (prefill binds pos=0)
    return jnp.asarray(pos) + jnp.arange(s)[:, None]


def decode_embed(pe: Dict, tok: jax.Array, pos) -> jax.Array:
    """Single decode-step token embed [B, 1, D]: wte row only (RoPE puts
    the position into the attention rotation, not the embedding)."""
    return jnp.take(pe["wte"], tok.reshape(-1), axis=0)[:, None]


def span_embed(pe: Dict, tok: jax.Array, pos) -> jax.Array:
    """K-token span embed [B, K] -> [B, K, D] (speculative verify):
    wte rows only — positions enter via RoPE in the attention."""
    return jnp.take(pe["wte"], tok, axis=0)


def _block_tail(p: Dict, x, ctx, cfg: TransformerConfig):
    """Post-attention half of a llama block (output proj + residual,
    RMSNorm, SwiGLU + residual) — ONE copy shared by the cached decode
    step and the sp prefill so their numerics cannot diverge."""
    h = dense(p["attn_out"], ctx) + x
    normed = rms_norm(p["ln_after"], h, cfg.layer_norm_eps)
    gated = jax.nn.silu(dense(p["mlp_gate"], normed).astype(
        jnp.float32)).astype(normed.dtype)
    return dense(p["mlp_down"], gated * dense(p["mlp_up"], normed)) + h


def cached_block_step(p: Dict, x, bcache, pos, cfg: TransformerConfig,
                      prefill: bool, read_len=None):
    """KV-cached llama block (decode subsystem contract, parallel/decode.py
    `_block_step` shape): prefill writes the whole prompt's POST-RoPE K and
    V at [0, S); a decode step rotates the single new token at `pos` and
    attends over the masked cache window (truncated to the static
    `read_len` bucket when the pipeline passes one — cache positions are
    absolute from 0, so the window mask anchors unchanged)."""
    from ..parallel.decode import _cache_update_and_read

    normed = rms_norm(p["ln_before"], x, cfg.layer_norm_eps)
    s = normed.shape[1]
    # pos + offset covers prefill (pos=0), decode (s=1), and span steps
    pos_ids = jnp.asarray(pos) + jnp.arange(s)
    q, k_new, v_new = _qkv_rope(p, normed, cfg, pos_ids)
    k, v, keep, bcache = _cache_update_and_read(
        bcache, k_new, v_new, pos, prefill, s, q.dtype, read_len=read_len)
    ctx = _gqa_attend(q, k, v, cfg, keep=keep,
                      q_pos=_abs_q_pos(pos, s, prefill))
    return _block_tail(p, x, ctx, cfg), bcache


def tp_cached_block_step(p: Dict, x, bcache, pos, cfg: TransformerConfig,
                         prefill: bool, axis: str, read_len=None):
    """Tensor-parallel KV-cached llama block under `shard_map`: the
    forward Megatron body (parallel/tensor.py `_tp_llama_block_local` —
    ONE copy of the projection/psum/SwiGLU numerics) with the attention
    core swapped for a cache-attend over the head-sharded GQA cache
    slice. Requires heads AND kv_heads divisible by the tp degree.
    `read_len`: static bucketed attend window (position axis unsharded)."""
    from ..parallel.decode import _cache_update_and_read
    from ..parallel.tensor import _tp_llama_block_local

    new_cache = {}

    def cache_attend(q, k_new, v_new):
        k, v, keep, bc = _cache_update_and_read(
            bcache, k_new, v_new, pos, prefill, x.shape[1], q.dtype,
            read_len=read_len)
        new_cache.update(bc)
        return _gqa_attend(q, k, v, cfg, keep=keep,
                           q_pos=_abs_q_pos(pos, x.shape[1], prefill))

    pos_ids = jnp.asarray(pos) + jnp.arange(x.shape[1])
    y = _tp_llama_block_local(p, x, cfg, axis, qkv_to_ctx=cache_attend,
                              pos_ids=pos_ids)
    return y, new_cache


def tp_finalize(pf: Dict, hidden, cfg: TransformerConfig, axis: str):
    """Vocab-sharded LM head under tp (shared helper, RMS norm)."""
    from ..parallel.decode import tp_vocab_head_finalize
    return tp_vocab_head_finalize(pf, hidden, cfg, axis, norm_fn=rms_norm)


def sp_prefill_block_step(p: Dict, x, bcache, cfg: TransformerConfig,
                          axis: str, core, cache_gather):
    """Sequence-parallel llama prefill block: RoPE is applied at GLOBAL
    chunk positions (chunk_start + local offset) BEFORE the sp core, so
    the rotation carries the position information and the chunk-local
    ring/Ulysses core stays position-agnostic — exactly why the plain
    attention-override path refuses RoPE families but this hook is sound.
    The sp cores are GQA-aware (parallel/sequence.py): unrepeated K/V
    ride the ring ppermutes / all-to-alls and repeat only inside the
    local attend, so the inter-chip traffic keeps GQA's kv_heads/heads
    size advantage; the cache likewise gathers the UNREPEATED post-RoPE
    rows the per-token decode steps read. Sliding-window (Mistral)
    configs need no handling here: make_sp_prefill_fn binds
    cfg.sliding_window into `core`, and the cache gathers the full
    post-RoPE rows — the per-token decode steps apply their own window
    mask over the cache (_window_keep)."""
    normed = rms_norm(p["ln_before"], x, cfg.layer_norm_eps)
    b, s_local, _ = x.shape
    idx = jax.lax.axis_index(axis)
    pos = idx * s_local + jnp.arange(s_local)
    q, k_new, v_new = _qkv_rope(p, normed, cfg, pos)
    ctx = core(q, k_new, v_new, axis, causal=True)
    return (_block_tail(p, x, ctx.reshape(b, s_local, -1), cfg),
            cache_gather(bcache, k_new, v_new))


FAMILY = FamilySpec(name="llama", embed=embed, sublayer=sublayer,
                    finalize=finalize, cached_block_step=cached_block_step,
                    decode_embed=decode_embed, span_embed=span_embed,
                    position_dependent_attention=True,
                    tp_cached_block_step=tp_cached_block_step,
                    tp_finalize=tp_finalize,
                    sp_prefill_block_step=sp_prefill_block_step)


def _a(x, dtype):
    return jnp.asarray(np.asarray(x), dtype=dtype)


def _lin(sd, key, dtype):
    """HF nn.Linear kernel [out, in] -> {w [in, out], b zeros}."""
    w = np.asarray(sd[key])
    return {"w": _a(w.T, dtype), "b": jnp.zeros((w.shape[0],), dtype)}


def load_params(cfg: TransformerConfig, shard_config: ShardConfig,
                weights: Mapping, dtype=jnp.float32) -> Dict:
    """Build shard params from an HF `LlamaForCausalLM` state-dict npz."""
    keys = set(weights.keys())
    sd = {k.removeprefix("model."): weights[k] for k in keys
          if k.startswith("model.")}
    if "lm_head.weight" in keys:
        sd["lm_head.weight"] = weights["lm_head.weight"]

    def get_embed() -> Dict:
        return {"wte": _a(sd["embed_tokens.weight"], dtype)}

    def get_block(block_id: int, subs: tuple) -> Dict:
        root = f"layers.{block_id}."
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = {
                "scale": _a(sd[root + "input_layernorm.weight"], dtype)}
            p["q"] = _lin(sd, root + "self_attn.q_proj.weight", dtype)
            p["k"] = _lin(sd, root + "self_attn.k_proj.weight", dtype)
            p["v"] = _lin(sd, root + "self_attn.v_proj.weight", dtype)
        if 1 in subs:
            p["attn_out"] = _lin(sd, root + "self_attn.o_proj.weight", dtype)
        if 2 in subs:
            p["ln_after"] = {
                "scale": _a(sd[root + "post_attention_layernorm.weight"],
                            dtype)}
            p["mlp_gate"] = _lin(sd, root + "mlp.gate_proj.weight", dtype)
            p["mlp_up"] = _lin(sd, root + "mlp.up_proj.weight", dtype)
        if 3 in subs:
            p["mlp_down"] = _lin(sd, root + "mlp.down_proj.weight", dtype)
        return p

    def get_final() -> Dict:
        head = sd.get("lm_head.weight", sd["embed_tokens.weight"])  # tied
        return {"ln": {"scale": _a(sd["norm.weight"], dtype)},
                "head": {"w": _a(np.asarray(head).T, dtype),
                         "b": jnp.zeros((np.asarray(head).shape[0],),
                                        dtype)}}

    return build_shard_params(shard_config, get_embed, get_block, get_final)


def init_params(cfg: TransformerConfig, shard_config: ShardConfig,
                seed: int = 0, dtype=jnp.float32) -> Dict:
    """Random shard params with the same pytree structure as `load_params`."""
    rng = np.random.default_rng(seed)
    d, it = cfg.hidden_size, cfg.intermediate_size
    kv_d = cfg.kv_heads * cfg.head_dim

    def mat(*shape):
        return jnp.asarray(rng.normal(0, 0.02, size=shape), dtype=dtype)

    def lin(n_in, n_out):
        return {"w": mat(n_in, n_out), "b": jnp.zeros((n_out,), dtype)}

    def rms():
        return {"scale": jnp.ones((d,), dtype)}

    def get_embed() -> Dict:
        return {"wte": mat(cfg.vocab_size, d)}

    def get_block(block_id: int, subs: tuple) -> Dict:
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = rms()
            p["q"] = lin(d, d)
            p["k"] = lin(d, kv_d)
            p["v"] = lin(d, kv_d)
        if 1 in subs:
            p["attn_out"] = lin(d, d)
        if 2 in subs:
            p["ln_after"] = rms()
            p["mlp_gate"] = lin(d, it)
            p["mlp_up"] = lin(d, it)
        if 3 in subs:
            p["mlp_down"] = lin(it, d)
        return p

    def get_final() -> Dict:
        return {"ln": rms(), "head": lin(d, cfg.vocab_size)}

    return build_shard_params(shard_config, get_embed, get_block, get_final)
