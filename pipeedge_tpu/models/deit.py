"""DeiT model family: ViT sublayer math with distillation-token embeddings.

Capability parity with /root/reference/src/pipeedge/models/transformers/deit.py.
The encoder block is identical to ViT (the reference's `DeiTLayerShard` is a
copy of `ViTLayerShard`, deit.py:27-69), so this module reuses `vit.sublayer`.
Differences: embeddings prepend both a CLS and a distillation token
(deit.py:119-126), and the native checkpoint format is the facebookresearch
torch-hub state dict with *fused* qkv kernels that must be split
(deit.py:130-156). The classifier head uses the CLS token only (deit.py:224-227).
"""
from __future__ import annotations

from typing import Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from . import ShardConfig
from .layers import TransformerConfig, dense, layer_norm, patchify
from .shard import FamilySpec, build_shard_params
from .vit import SUBLAYER_PARAMS, sublayer  # block math shared with ViT

__all__ = ["FAMILY", "SUBLAYER_PARAMS", "load_params", "init_params",
           "hf_to_npz_weights"]


def embed(p: Dict, pixel_values: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Patch embedding + [CLS, DIST] tokens + position embeddings (deit.py:119-126)."""
    x = jnp.transpose(pixel_values, (0, 2, 3, 1))
    patches = patchify(x, cfg.patch_size)
    hidden = dense(p["patch"], patches.astype(p["patch"]["w"].dtype))
    b = hidden.shape[0]
    cls = jnp.broadcast_to(p["cls"], (b, 1, cfg.hidden_size)).astype(hidden.dtype)
    dist = jnp.broadcast_to(p["dist"], (b, 1, cfg.hidden_size)).astype(hidden.dtype)
    hidden = jnp.concatenate([cls, dist, hidden], axis=1)
    return hidden + p["pos"].astype(hidden.dtype)


def finalize(p: Dict, hidden: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Final layernorm; classifier on the CLS token (deit.py:157-166, 224-227)."""
    hidden = layer_norm(p["ln"], hidden, cfg.layer_norm_eps)
    if "head" in p:
        return dense(p["head"], hidden[:, 0, :])
    return hidden


FAMILY = FamilySpec(name="deit", embed=embed, sublayer=sublayer, finalize=finalize)


def _a(x, dtype):
    return jnp.asarray(np.asarray(x), dtype=dtype)


def load_params(cfg: TransformerConfig, shard_config: ShardConfig,
                weights: Mapping, dtype=jnp.float32) -> Dict:
    """Build shard params from a torch-hub DeiT state-dict npz (deit.py:118-156)."""
    d = cfg.hidden_size

    def get_embed() -> Dict:
        kernel = np.asarray(weights["patch_embed.proj.weight"])  # [D, C, ph, pw]
        return {
            "cls": _a(weights["cls_token"], dtype),
            "dist": _a(weights["dist_token"], dtype),
            "pos": _a(weights["pos_embed"], dtype),
            "patch": {"w": _a(kernel.transpose(2, 3, 1, 0).reshape(-1, d), dtype),
                      "b": _a(weights["patch_embed.proj.bias"], dtype)},
        }

    def get_block(block_id: int, subs: tuple) -> Dict:
        root = f"blocks.{block_id}."
        p: Dict = {}
        if 0 in subs:
            p["ln_before"] = {"scale": _a(weights[root + "norm1.weight"], dtype),
                              "bias": _a(weights[root + "norm1.bias"], dtype)}
            # fused qkv [3D, D] torch-layout -> split + transpose to [in, out]
            qkv_w = np.asarray(weights[root + "attn.qkv.weight"])
            qkv_b = np.asarray(weights[root + "attn.qkv.bias"])
            for i, name in enumerate(("q", "k", "v")):
                p[name] = {"w": _a(qkv_w[i * d:(i + 1) * d, :].T, dtype),
                           "b": _a(qkv_b[i * d:(i + 1) * d], dtype)}
        if 1 in subs:
            p["attn_out"] = {"w": _a(np.asarray(weights[root + "attn.proj.weight"]).T, dtype),
                             "b": _a(weights[root + "attn.proj.bias"], dtype)}
        if 2 in subs:
            p["ln_after"] = {"scale": _a(weights[root + "norm2.weight"], dtype),
                             "bias": _a(weights[root + "norm2.bias"], dtype)}
            p["mlp_up"] = {"w": _a(np.asarray(weights[root + "mlp.fc1.weight"]).T, dtype),
                           "b": _a(weights[root + "mlp.fc1.bias"], dtype)}
        if 3 in subs:
            p["mlp_down"] = {"w": _a(np.asarray(weights[root + "mlp.fc2.weight"]).T, dtype),
                             "b": _a(weights[root + "mlp.fc2.bias"], dtype)}
        return p

    def get_final() -> Dict:
        p = {"ln": {"scale": _a(weights["norm.weight"], dtype),
                    "bias": _a(weights["norm.bias"], dtype)}}
        if cfg.num_labels > 0 and "head.weight" in weights:
            p["head"] = {"w": _a(np.asarray(weights["head.weight"]).T, dtype),
                         "b": _a(weights["head.bias"], dtype)}
        return p

    return build_shard_params(shard_config, get_embed, get_block, get_final)


def hf_to_npz_weights(state_dict: Mapping, cfg: TransformerConfig) -> Dict[str, np.ndarray]:
    """Convert an HF DeiT state dict to the torch-hub key scheme the loader
    (and the reference, deit.py:118-156) expects."""
    sd = {k.removeprefix("deit."): np.asarray(v) for k, v in state_dict.items()}
    out = {
        "cls_token": sd["embeddings.cls_token"],
        "dist_token": sd["embeddings.distillation_token"],
        "pos_embed": sd["embeddings.position_embeddings"],
        "patch_embed.proj.weight": sd["embeddings.patch_embeddings.projection.weight"],
        "patch_embed.proj.bias": sd["embeddings.patch_embeddings.projection.bias"],
        "norm.weight": sd["layernorm.weight"],
        "norm.bias": sd["layernorm.bias"],
    }
    if "cls_classifier.weight" in sd:
        out["head.weight"] = sd["cls_classifier.weight"]
        out["head.bias"] = sd["cls_classifier.bias"]
    for i in range(cfg.num_hidden_layers):
        hf_root = f"encoder.layer.{i}."
        attn_prefix = None
        for cand in ("attention.attention.", "attention.self."):
            if hf_root + cand + "query.weight" in sd:
                attn_prefix = hf_root + cand
                break
        root = f"blocks.{i}."
        out[root + "norm1.weight"] = sd[hf_root + "layernorm_before.weight"]
        out[root + "norm1.bias"] = sd[hf_root + "layernorm_before.bias"]
        out[root + "attn.qkv.weight"] = np.concatenate(
            [sd[attn_prefix + n + ".weight"] for n in ("query", "key", "value")], axis=0)
        out[root + "attn.qkv.bias"] = np.concatenate(
            [sd[attn_prefix + n + ".bias"] for n in ("query", "key", "value")], axis=0)
        out[root + "attn.proj.weight"] = sd[hf_root + "attention.output.dense.weight"]
        out[root + "attn.proj.bias"] = sd[hf_root + "attention.output.dense.bias"]
        out[root + "norm2.weight"] = sd[hf_root + "layernorm_after.weight"]
        out[root + "norm2.bias"] = sd[hf_root + "layernorm_after.bias"]
        out[root + "mlp.fc1.weight"] = sd[hf_root + "intermediate.dense.weight"]
        out[root + "mlp.fc1.bias"] = sd[hf_root + "intermediate.dense.bias"]
        out[root + "mlp.fc2.weight"] = sd[hf_root + "output.dense.weight"]
        out[root + "mlp.fc2.bias"] = sd[hf_root + "output.dense.bias"]
    return out


def init_params(cfg: TransformerConfig, shard_config: ShardConfig,
                seed: int = 0, dtype=jnp.float32) -> Dict:
    """Random shard params with the same pytree structure as `load_params`."""
    from .vit import init_params as vit_init
    rng = np.random.default_rng(seed + 1)
    params = vit_init(cfg, shard_config, seed=seed, dtype=dtype)
    if shard_config.is_first:
        d = cfg.hidden_size
        params["embeddings"]["dist"] = jnp.asarray(
            rng.normal(0, 0.02, size=(1, 1, d)), dtype=dtype)
        params["embeddings"]["pos"] = jnp.asarray(
            rng.normal(0, 0.02, size=(1, cfg.num_patches + 2, d)), dtype=dtype)
    return params
