"""Pure-function transformer building blocks (MXU-friendly, dtype-flexible).

These replace the reference's use of HuggingFace torch modules
(ViTSelfAttention/ViTIntermediate/... — reference vit.py:12-14, bert.py:10-12)
with jittable functions over parameter pytrees. Matmuls accumulate in float32
via `preferred_element_type` so bfloat16 parameters/activations keep MXU
throughput without losing accumulation precision.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Static model hyperparameters (stands in for HF `AutoConfig`, which the
    reference fetches over the network — model_cfg.py:57-66; here configs are
    local constants so the framework runs with zero egress)."""
    model_type: str              # 'vit' | 'bert' | 'deit' | 'gpt2'
    hidden_size: int
    num_hidden_layers: int       # transformer blocks (sublayers = 4x this)
    num_attention_heads: int
    intermediate_size: int
    layer_norm_eps: float = 1e-12
    num_labels: int = 0
    # vision
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    # text
    vocab_size: int = 0
    max_position_embeddings: int = 0
    type_vocab_size: int = 2
    # mixture-of-experts (switch-FFN blocks; 0 = dense FFN)
    n_experts: int = 0
    capacity_factor: float = 1.25
    # grouped-query attention (llama family): 0 = same as query heads
    num_kv_heads: int = 0
    # rotary position embedding base (llama family)
    rope_theta: float = 10000.0
    # sliding-window attention (Mistral-style): each position attends to
    # the last `sliding_window` positions (incl. itself); 0 = full causal
    sliding_window: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def kv_heads(self) -> int:
        """Key/value head count (GQA: fewer than query heads; 0 = equal)."""
        return self.num_kv_heads or self.num_attention_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


@dataclasses.dataclass(frozen=True)
class QuantizeCompute:
    """Int8 compute-path config (ops/int8_matmul.py).

    `enabled` routes every TAGGED dense (ViT's attention projections,
    attn-out, and the FFN pair — untagged call sites always stay exact)
    through the block-scaled int8 matmul. `skip_tags` is the per-layer
    opt-out for numerically fragile layers (e.g. frozenset({"head"}));
    `clamp_alphas` maps tags to calibrated Banner clip thresholds
    (utils/calibrate.py sidecar); `tunnel` additionally lets a stage's
    first matmul consume the 8-bit wire payload directly
    (parallel/pipeline.py seam, ops/int8_matmul.wire_dense).

    TRACE-TIME config, like fast numerics: programs compiled while a
    config is active keep it — set it BEFORE building/first-calling a
    model.
    """
    enabled: bool = False
    block_k: int = 128
    skip_tags: frozenset = frozenset()
    clamp_alphas: Optional[dict] = None
    tunnel: bool = False


_QC_OFF = QuantizeCompute()
_QUANTIZE_COMPUTE = None   # None = unset (consult the env var)
_QC_OBSERVER = None        # calibration hook: fn(tag, x) per tagged dense


def set_quantize_compute(cfg) -> None:
    """Install the int8 compute-path config.

    `cfg` is a `QuantizeCompute`, True/False (defaults / off), or None to
    RESET: discard the programmatic choice and defer to the env again
    (PIPEEDGE_QUANTIZE_COMPUTE=1 enables the defaults,
    PIPEEDGE_QUANTIZE_SKIP=tag,tag populates the opt-out) — the same
    setter-wins-but-None-restores contract as `set_fast_numerics`.
    """
    global _QUANTIZE_COMPUTE
    if cfg is None or isinstance(cfg, QuantizeCompute):
        _QUANTIZE_COMPUTE = cfg
    else:
        _QUANTIZE_COMPUTE = QuantizeCompute(enabled=bool(cfg))


def quantize_compute() -> QuantizeCompute:
    """The active int8 compute config (programmatic choice wins; env
    PIPEEDGE_QUANTIZE_COMPUTE is the fallback; disabled otherwise)."""
    if _QUANTIZE_COMPUTE is not None:
        return _QUANTIZE_COMPUTE
    import os
    env = os.getenv("PIPEEDGE_QUANTIZE_COMPUTE")
    if env is not None and env.strip().lower() not in (
            "", "0", "false", "no", "off"):
        skip = frozenset(t for t in os.getenv(
            "PIPEEDGE_QUANTIZE_SKIP", "").split(",") if t)
        return QuantizeCompute(enabled=True, skip_tags=skip)
    return _QC_OFF


_FAST_NUMERICS = None      # None = unset (consult the env var)


def set_fast_numerics(enabled) -> None:
    """Opt-in fast-numerics mode (also env PIPEEDGE_FAST_NUMERICS=1 when
    this setter was never called or was reset — the programmatic toggle
    WINS so exact-vs-fast A/Bs can't be silently poisoned by an inherited
    env): LayerNorm statistics and attention softmax run in the model
    dtype instead of float32, and exact-erf GeLU becomes the tanh
    approximation. Trades exact HF/reference numerics parity for fewer
    f32 intermediates (less VPU/HBM traffic between the MXU matmuls) —
    the measured cost of the parity default is the 'f32 numerics'
    bucket in docs/PERF.md's MFU attribution.

    `enabled` is True/False, or None to RESET: discard any programmatic
    choice and defer to PIPEEDGE_FAST_NUMERICS again (without None the
    env opt-in would be permanently dead for the rest of the process
    after any caller touched the toggle — ADVICE.md r5).

    TRACE-TIME flag: programs compiled while the mode is on keep it
    (jit caches by shape/dtype, not by this flag) — enable it BEFORE
    building/first-calling a model, as bench.py's fast-numerics pass and
    tools/bench_mfu_buckets.py do. Accuracy delta vs the exact mode is
    measured and recorded (tests/test_models.py, docs/PERF.md)."""
    global _FAST_NUMERICS
    _FAST_NUMERICS = None if enabled is None else bool(enabled)


def fast_numerics_enabled() -> bool:
    if _FAST_NUMERICS is not None:
        return _FAST_NUMERICS
    import os
    env = os.getenv("PIPEEDGE_FAST_NUMERICS")
    if env is not None:
        return env.strip().lower() not in ("", "0", "false", "no", "off")
    return False


def layer_norm(p, x: jax.Array, eps: float) -> jax.Array:
    """LayerNorm with scale/bias, computed in float32 for stability
    (model-dtype statistics under fast-numerics)."""
    if fast_numerics_enabled():
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        normed = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(eps, x.dtype))
        return normed * p["scale"].astype(x.dtype) \
            + p["bias"].astype(x.dtype)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (normed * p["scale"] + p["bias"]).astype(x.dtype)


def dense(p, x: jax.Array, tag: Optional[str] = None) -> jax.Array:
    """x @ w + b with kernels stored [in, out] (JAX convention; torch state
    dicts store [out, in] and are transposed at load time).

    `tag` names the call site for the int8 compute path: tagged denses
    route through the block-scaled int8 matmul when a `QuantizeCompute`
    config is active (and the tag isn't opted out); untagged denses are
    always exact. The calibration observer hook also keys on tags."""
    if tag is not None:
        if _QC_OBSERVER is not None:
            _QC_OBSERVER(tag, x)
        qc = quantize_compute()
        if qc.enabled and tag not in qc.skip_tags:
            from ..ops import int8_matmul
            alpha = (qc.clamp_alphas or {}).get(tag)
            return int8_matmul.int8_dense(
                x, p["w"], p["b"], block_k=qc.block_k, clamp_alpha=alpha,
                out_dtype=x.dtype)
    y = jnp.dot(x, p["w"].astype(x.dtype), preferred_element_type=jnp.float32)
    return (y + p["b"]).astype(x.dtype)


def _use_fused_attention(seq_len: int) -> bool:
    """Pallas fused attention: on TPU for long sequences, where streaming the
    [S, S] scores through VMEM beats XLA (measured ~5x at S=8192); for short
    sequences (ViT's 197, BERT's 512) XLA's fused einsum path wins. Override
    with env PIPEEDGE_FUSED_ATTENTION=0/1."""
    import os
    env = os.getenv("PIPEEDGE_FUSED_ATTENTION")
    if env is not None:
        return env not in ("0", "false", "no")
    return jax.default_backend() == "tpu" and seq_len >= 1024


def rms_norm(p, x: jax.Array, eps: float) -> jax.Array:
    """RMSNorm (scale only, no mean subtraction — llama family), computed
    in float32 like HF `LlamaRMSNorm`."""
    xf = x.astype(jnp.float32)
    normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1,
                                         keepdims=True) + eps)
    return (normed * p["scale"]).astype(x.dtype)


def rope_rotate(x: jax.Array, pos: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding on [B, S, H, Dh] at positions `pos` [S]
    (HF llama convention: half-split rotate, angles in float32, one
    frequency per pair duplicated across the two halves)."""
    hd = x.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, hd, 2, dtype=jnp.float32)
                                / hd))
    angles = pos.astype(jnp.float32)[:, None] * inv_freq[None]   # [S, hd/2]
    cos = jnp.concatenate([jnp.cos(angles)] * 2, axis=-1)        # [S, hd]
    sin = jnp.concatenate([jnp.sin(angles)] * 2, axis=-1)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos[None, :, None] + rotated
            * sin[None, :, None]).astype(x.dtype)


def apply_causal_mask(scores: jax.Array) -> jax.Array:
    """Mask strictly-future key positions in [..., S_q, S_k] scores
    (shared by the XLA attention path and the TP block bodies)."""
    s_q, s_k = scores.shape[-2], scores.shape[-1]
    q_pos = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
    return jnp.where(k_pos <= q_pos, scores, -1e30)


def self_attention(p, x: jax.Array, num_heads: int,
                   mask: Optional[jax.Array] = None,
                   core_fn=None, causal: bool = False,
                   tag_prefix: Optional[str] = None) -> jax.Array:
    """Multi-head self-attention context (pre-projection), batched over [B,S,D].

    Matches HF `{ViT,Bert}SelfAttention` semantics: returns the concatenated
    per-head context; the output projection lives in the next sublayer
    (reference vit.py:58-63). Softmax in float32. On TPU the
    softmax(QK^T)V core runs as a fused Pallas kernel (ops/attention.py).

    `causal` applies a lower-triangular mask (decoder families, e.g. GPT-2);
    the fused kernel handles it natively (and skips past-frontier K/V
    blocks), so the long-sequence perf path covers decoders too.

    `tag_prefix` tags the q/k/v projections (`<prefix>.q` etc.) for the
    int8 compute path — see `dense`.

    `core_fn(q, k, v) -> ctx` ([B,S,H,D]-shaped) overrides the attention
    core while reusing THIS projection code — how sequence-parallel
    execution swaps in ring attention (parallel/spmd.py). A core_fn is
    responsible for its own causal masking (ring/Ulysses attention take a
    `causal` flag), so `causal` is ignored on that path.
    """
    b, s, d = x.shape
    hd = d // num_heads
    tags = {n: f"{tag_prefix}.{n}" if tag_prefix else None
            for n in ("q", "k", "v")}
    q = dense(p["q"], x, tag=tags["q"]).reshape(b, s, num_heads, hd)
    k = dense(p["k"], x, tag=tags["k"]).reshape(b, s, num_heads, hd)
    v = dense(p["v"], x, tag=tags["v"]).reshape(b, s, num_heads, hd)
    if core_fn is not None:
        if mask is not None:
            # the override receives no mask; reject the combination rather
            # than silently attending to padding tokens
            raise NotImplementedError(
                "core_fn overrides do not support masks")
        return core_fn(q, k, v).reshape(b, s, d)
    if mask is None and _use_fused_attention(s):
        from ..ops.attention import fused_attention
        return fused_attention(q, k, v, causal=causal).reshape(b, s, d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    if causal:
        scores = apply_causal_mask(scores)
    if mask is not None:
        # mask: [B, S] with 1 = attend, 0 = ignore
        bias = jnp.where(mask[:, None, None, :] > 0, 0.0, -1e9).astype(jnp.float32)
        scores = scores + bias
    if fast_numerics_enabled():
        # model-dtype softmax: the MXU accumulation above stays f32
        # (free); only the VPU softmax intermediates narrow
        probs = jax.nn.softmax(scores.astype(x.dtype), axis=-1)
    else:
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    return ctx.reshape(b, s, d)


def gelu(x: jax.Array) -> jax.Array:
    """Exact (erf) GeLU, matching torch `nn.GELU()` default used by HF
    (tanh approximation under fast-numerics)."""
    return jax.nn.gelu(x, approximate=fast_numerics_enabled())


def gelu_new(x: jax.Array) -> jax.Array:
    """Tanh-approximate GeLU, matching HF `gelu_new` (GPT-2's activation)."""
    return jax.nn.gelu(x, approximate=True)


def patchify(x: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, C] -> [B, N, patch*patch*C] with (ph, pw, c) flattening order.

    Expressing patch embedding as reshape + one big matmul (instead of a
    strided conv) maps directly onto the MXU; the kernel layout matches, e.g.,
    Google's ViT npz `embedding/kernel` [ph, pw, C, D] reshaped to
    [ph*pw*C, D] (reference vit.py:124-128 does the conv-layout dance instead).
    """
    b, h, w, c = x.shape
    nh, nw = h // patch, w // patch
    x = x.reshape(b, nh, patch, nw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, nh * nw, patch * patch * c)
