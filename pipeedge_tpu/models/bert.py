"""BERT model family: pure-function shards with the 4-way sublayer split.

Capability parity with /root/reference/src/pipeedge/models/transformers/bert.py.
BERT is post-LN, so the sublayer split differs from ViT (`BertLayerShard.forward`,
bert.py:41-52):
  sub 0: self-attention (no pre-norm)     payload becomes (ctx, residual)
  sub 1: output dense + residual, then LN payload becomes hidden
  sub 2: MLP-up + GeLU                    payload becomes (mlp_h, residual)
  sub 3: MLP-down + residual, then LN     payload becomes hidden
First shard: word/position/token-type embeddings + LN (bert.py:76-80). Last
shard: tanh pooler over the CLS token (bert.py:98-102), plus a classifier head
for sequence classification (bert.py:186-208).

Weight format: HF `BertModel` state-dict npz, the reference's native format
(bert.py:153-161); classification checkpoints carry a `bert.` prefix that is
stripped (bert.py:191-196).
"""
from __future__ import annotations

from typing import Dict, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from . import ShardConfig
from .layers import TransformerConfig, dense, gelu, layer_norm, self_attention
from .shard import FamilySpec, build_shard_params

SUBLAYER_PARAMS = {
    0: ("q", "k", "v"),
    1: ("attn_out", "attn_ln"),
    2: ("mlp_up",),
    3: ("mlp_down", "out_ln"),
}


def embed(p: Dict, input_ids: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Sum of word/position/token-type embeddings + LayerNorm.

    Token type ids default to zeros and positions to [0, S) — the reference
    passes only input ids to `BertEmbeddings` (bert.py:145-146).
    """
    seq_len = input_ids.shape[1]
    word = jnp.take(p["word"], input_ids, axis=0)
    pos = p["pos"][:seq_len][None, :, :]
    ttype = p["type"][0][None, None, :]
    hidden = word + pos + ttype
    return layer_norm(p["ln"], hidden, cfg.layer_norm_eps)


def sublayer(p: Dict, sub: int, data, cfg: TransformerConfig,
             attention_fn=None):
    """One of the 4 schedulable sublayers (reference bert.py:41-52).

    `attention_fn` overrides the attention core (see vit.sublayer)."""
    if sub == 0:
        ctx = (attention_fn or self_attention)(
            {"q": p["q"], "k": p["k"], "v": p["v"]}, data,
            cfg.num_attention_heads)
        return (ctx, data)
    if sub == 1:
        ctx, skip = data
        return layer_norm(p["attn_ln"], dense(p["attn_out"], ctx) + skip,
                          cfg.layer_norm_eps)
    if sub == 2:
        return (gelu(dense(p["mlp_up"], data)), data)
    if sub == 3:
        mlp_h, skip = data
        return layer_norm(p["out_ln"], dense(p["mlp_down"], mlp_h) + skip,
                          cfg.layer_norm_eps)
    raise ValueError(f"sublayer must be 0..3, got {sub}")


def finalize(p: Dict, hidden: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Tanh pooler on CLS (bert.py:98-102); classifier head when present."""
    pooled = jnp.tanh(dense(p["pooler"], hidden[:, 0, :]))
    if "head" in p:
        return dense(p["head"], pooled)
    return pooled


FAMILY = FamilySpec(name="bert", embed=embed, sublayer=sublayer, finalize=finalize)


def _a(x, dtype):
    return jnp.asarray(np.asarray(x), dtype=dtype)


def load_params(cfg: TransformerConfig, shard_config: ShardConfig,
                weights: Mapping, dtype=jnp.float32) -> Dict:
    """Build shard params from an HF-state-dict npz (bert.py:104-141).

    Accepts both bare `BertModel` keys and `bert.`-prefixed classification
    checkpoints (with `classifier.*`, bert.py:191-201).
    """
    if any(k.startswith("bert.") for k in weights.keys()):
        sd = {k.removeprefix("bert."): weights[k] for k in weights.keys()
              if k.startswith("bert.")}
        classifier = {k: weights[k] for k in weights.keys()
                      if k.startswith("classifier.")}
    else:
        sd = dict(weights.items()) if not isinstance(weights, dict) else weights
        classifier = sd

    def get_embed() -> Dict:
        return {
            "word": _a(sd["embeddings.word_embeddings.weight"], dtype),
            "pos": _a(sd["embeddings.position_embeddings.weight"], dtype),
            "type": _a(sd["embeddings.token_type_embeddings.weight"], dtype),
            "ln": {"scale": _a(sd["embeddings.LayerNorm.weight"], dtype),
                   "bias": _a(sd["embeddings.LayerNorm.bias"], dtype)},
        }

    def get_block(block_id: int, subs: tuple) -> Dict:
        root = f"encoder.layer.{block_id}."
        p: Dict = {}
        if 0 in subs:
            for name, key in (("q", "query"), ("k", "key"), ("v", "value")):
                p[name] = {"w": _a(sd[root + f"attention.self.{key}.weight"], dtype).T,
                           "b": _a(sd[root + f"attention.self.{key}.bias"], dtype)}
        if 1 in subs:
            p["attn_out"] = {"w": _a(sd[root + "attention.output.dense.weight"], dtype).T,
                             "b": _a(sd[root + "attention.output.dense.bias"], dtype)}
            p["attn_ln"] = {"scale": _a(sd[root + "attention.output.LayerNorm.weight"], dtype),
                            "bias": _a(sd[root + "attention.output.LayerNorm.bias"], dtype)}
        if 2 in subs:
            p["mlp_up"] = {"w": _a(sd[root + "intermediate.dense.weight"], dtype).T,
                           "b": _a(sd[root + "intermediate.dense.bias"], dtype)}
        if 3 in subs:
            p["mlp_down"] = {"w": _a(sd[root + "output.dense.weight"], dtype).T,
                             "b": _a(sd[root + "output.dense.bias"], dtype)}
            p["out_ln"] = {"scale": _a(sd[root + "output.LayerNorm.weight"], dtype),
                           "bias": _a(sd[root + "output.LayerNorm.bias"], dtype)}
        return p

    def get_final() -> Dict:
        p = {"pooler": {"w": _a(sd["pooler.dense.weight"], dtype).T,
                        "b": _a(sd["pooler.dense.bias"], dtype)}}
        if cfg.num_labels > 0 and "classifier.weight" in classifier:
            p["head"] = {"w": _a(classifier["classifier.weight"], dtype).T,
                         "b": _a(classifier["classifier.bias"], dtype)}
        return p

    return build_shard_params(shard_config, get_embed, get_block, get_final)


def init_params(cfg: TransformerConfig, shard_config: ShardConfig,
                seed: int = 0, dtype=jnp.float32) -> Dict:
    """Random shard params with the same pytree structure as `load_params`."""
    rng = np.random.default_rng(seed)
    d, it = cfg.hidden_size, cfg.intermediate_size

    def mat(*shape):
        return jnp.asarray(rng.normal(0, 0.02, size=shape), dtype=dtype)

    def vec(n):
        return jnp.zeros((n,), dtype=dtype)

    def ln():
        return {"scale": jnp.ones((d,), dtype), "bias": vec(d)}

    def get_embed() -> Dict:
        return {"word": mat(cfg.vocab_size, d),
                "pos": mat(cfg.max_position_embeddings, d),
                "type": mat(cfg.type_vocab_size, d), "ln": ln()}

    def get_block(block_id: int, subs: tuple) -> Dict:
        p: Dict = {}
        if 0 in subs:
            for name in ("q", "k", "v"):
                p[name] = {"w": mat(d, d), "b": vec(d)}
        if 1 in subs:
            p["attn_out"] = {"w": mat(d, d), "b": vec(d)}
            p["attn_ln"] = ln()
        if 2 in subs:
            p["mlp_up"] = {"w": mat(d, it), "b": vec(it)}
        if 3 in subs:
            p["mlp_down"] = {"w": mat(it, d), "b": vec(d)}
            p["out_ln"] = ln()
        return p

    def get_final() -> Dict:
        p = {"pooler": {"w": mat(d, d), "b": vec(d)}}
        if cfg.num_labels > 0:
            p["head"] = {"w": mat(d, cfg.num_labels), "b": vec(cfg.num_labels)}
        return p

    return build_shard_params(shard_config, get_embed, get_block, get_final)
