"""Expert parallelism: a switch-routed FFN with experts sharded over an
'ep' mesh axis.

NEW capability beyond the reference (SURVEY.md §2.4: PipeEdge has no MoE
models, so expert parallelism is n/a there). This module provides the
mesh-axis mechanics so an MoE block composes with the pipeline the same way
tp/sp do: parameters shard over 'ep' (each device owns n_experts/n local
experts), tokens are routed top-1 with a fixed per-expert capacity (static
shapes — XLA requirement), each device computes only its own experts'
tokens, and one `psum` combines the expert outputs.

Routing semantics (Switch Transformer style, top-1):
- router logits [T, E] -> softmax -> each token's expert + gate weight;
- per expert, the C highest-probability tokens assigned to it are kept
  (C = capacity_factor * T / E, rounded up); overflow tokens pass through
  unchanged (the standard capacity-drop residual behavior).
- T is the token set the caller presents: under data parallelism each dp
  shard routes its own tokens with its own capacity (the standard
  data-parallel MoE semantics) — outputs are batch-size-dependent by
  construction, like any capacity-routed MoE.

The GPT-2 family consumes this as `moe_ffn_delta` for its routed-FFN
blocks (models/gpt2.py, registry models pipeedge/gpt2-moe-8e and
pipeedge/test-tiny-moe), so MoE decoders run through the shard engine,
host/SPMD pipelines, and KV-cache decoding (tests/test_moe_family.py).

Exactness: `ep_ffn` over an n-device 'ep' axis matches the single-device
reference (`reference_moe_ffn`) to float tolerance (the distributed
combine re-associates one add) — tested in tests/test_expert.py.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import jax_compat
from ..models.layers import TransformerConfig, gelu


def init_moe_params(cfg: TransformerConfig, n_experts: int,
                    seed: int = 0) -> Dict:
    """Router + per-expert MLP params (expert axis leading)."""
    rng = np.random.default_rng(seed)
    d, f = cfg.hidden_size, cfg.intermediate_size

    def glorot(*shape):
        fan = shape[-2] + shape[-1]
        return jnp.asarray(rng.normal(0, math.sqrt(2.0 / fan), shape),
                           jnp.float32)

    return {
        "router": {"w": glorot(d, n_experts),
                   "b": jnp.zeros((n_experts,), jnp.float32)},
        "experts": {
            "mlp_up": {"w": glorot(n_experts, d, f),
                       "b": jnp.zeros((n_experts, f), jnp.float32)},
            "mlp_down": {"w": glorot(n_experts, f, d),
                         "b": jnp.zeros((n_experts, d), jnp.float32)},
        },
    }


def _routing(router, x, n_experts: int, capacity: int):
    """Top-1 routing with per-expert capacity.

    Returns (expert_of_token [T], gate [T], keep [E, C] token indices,
    kept [E, C] validity) — deterministic, static shapes."""
    t = x.shape[0]
    logits = x @ router["w"] + router["b"]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gate = jnp.max(probs, axis=-1)            # [T]
    expert = jnp.argmax(probs, axis=-1)       # [T]
    # per expert: the C highest-gate tokens assigned to it
    assigned = jnp.where(expert[None, :] == jnp.arange(n_experts)[:, None],
                         gate[None, :], -jnp.inf)          # [E, T]
    top_gate, keep = jax.lax.top_k(assigned, capacity)     # [E, C]
    kept = jnp.isfinite(top_gate)
    return expert, gate, keep, kept


def moe_capacity(n_tokens: int, n_experts: int,
                 capacity_factor: float) -> int:
    """Per-expert token capacity (static; standard switch formula)."""
    return max(1, min(n_tokens,
                      math.ceil(capacity_factor * n_tokens / n_experts)))


def _scatter_expert_deltas(experts: Dict, tokens: jax.Array, gate, keep,
                           kept, act) -> jax.Array:
    """THE expert-compute core shared by the single-device delta FFN and
    the ep-sharded body: vmap act(x@up)@down over the (possibly local)
    expert slab, gate, zero invalid slots, scatter-add into token rows.
    One implementation, so the family FFN and the 'ep' axis cannot
    diverge."""
    def one_expert(w_up, b_up, w_down, b_down, ids, valid):
        xe = tokens[ids]
        up = act(xe @ w_up + b_up)
        ye = up @ w_down + b_down
        return jnp.where(valid[:, None], ye * gate[ids][:, None], 0.0), ids

    deltas, ids = jax.vmap(one_expert)(
        experts["mlp_up"]["w"], experts["mlp_up"]["b"],
        experts["mlp_down"]["w"], experts["mlp_down"]["b"], keep, kept)
    return jnp.zeros_like(tokens).at[ids.reshape(-1)].add(
        deltas.reshape(-1, tokens.shape[-1]))


def moe_ffn_delta(params: Dict, normed: jax.Array, n_experts: int,
                  capacity_factor: float, *, act) -> jax.Array:
    """Single-device switch-FFN **delta**: gate * expert(normed) per kept
    token, zeros for capacity-dropped tokens. Pre-LN families add this to
    the raw residual (h = x + delta), so the residual semantics live with
    the caller — this is the form the GPT-2 MoE blocks use
    (models/gpt2.py). `act` is required (GPT-2 uses gelu_new; a defaulted
    activation would be a silent-wrong-numbers trap)."""
    b, s, d = normed.shape
    tokens = normed.reshape(-1, d)
    capacity = moe_capacity(tokens.shape[0], n_experts, capacity_factor)
    _, gate, keep, kept = _routing(params["router"], tokens, n_experts,
                                   capacity)
    delta = _scatter_expert_deltas(params["experts"], tokens, gate, keep,
                                   kept, act)
    return delta.reshape(b, s, d).astype(normed.dtype)


def reference_moe_ffn(params: Dict, x: jax.Array, n_experts: int,
                      capacity_factor: float = 1.25) -> jax.Array:
    """Single-device oracle: identical routing, experts applied in a loop."""
    b, s, d = x.shape
    tokens = x.reshape(-1, d)
    capacity = moe_capacity(tokens.shape[0], n_experts, capacity_factor)
    _, gate, keep, kept = _routing(params["router"], tokens, n_experts,
                                   capacity)
    out = tokens  # capacity-dropped tokens pass through (residual)
    for e in range(n_experts):
        ids = keep[e]
        xe = tokens[ids]
        up = gelu(xe @ params["experts"]["mlp_up"]["w"][e]
                  + params["experts"]["mlp_up"]["b"][e])
        ye = up @ params["experts"]["mlp_down"]["w"][e] \
            + params["experts"]["mlp_down"]["b"][e]
        ye = ye * gate[ids][:, None] + tokens[ids]
        out = out.at[ids].set(jnp.where(kept[e][:, None], ye, out[ids]))
    return out.reshape(b, s, d)


def _ep_local(params: Dict, x: jax.Array, *, n_experts: int,
              capacity: int, axis: str, act=gelu) -> jax.Array:
    """Per-device body under shard_map: local experts [E/n, ...], tokens
    replicated; each device computes its experts' capacity slots and a psum
    combines."""
    b, s, d = x.shape
    tokens = x.reshape(-1, d)
    _, gate, keep, kept = _routing(params["router"], tokens, n_experts,
                                   capacity)
    combined = _ep_delta_from_routing(params, tokens, gate, keep, kept,
                                      n_experts, axis, act)
    return (tokens + combined).reshape(b, s, d)


def _ep_delta_from_routing(params: Dict, tokens: jax.Array, gate, keep,
                           kept, n_experts: int, axis: str,
                           act) -> jax.Array:
    """This device's expert rows of the global routing tables -> local
    deltas (shared core) -> psum combine across `axis`. Used by the
    standalone ep FFN and the expert-parallel decode step."""
    n = jax_compat.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    e_local = n_experts // n
    first = idx * e_local
    my_keep = jax.lax.dynamic_slice_in_dim(keep, first, e_local, axis=0)
    my_kept = jax.lax.dynamic_slice_in_dim(kept, first, e_local, axis=0)
    local = _scatter_expert_deltas(params["experts"], tokens, gate, my_keep,
                                   my_kept, act)
    return jax.lax.psum(local, axis)


def ep_ffn_delta(params: Dict, normed: jax.Array, n_experts: int,
                 capacity_factor: float, axis: str, *, act) -> jax.Array:
    """Expert-parallel counterpart of `moe_ffn_delta`: the same routed-FFN
    delta with the expert slab sharded over `axis` (call under shard_map).
    Exact vs the single-device delta — top-1 routing means the psum adds
    exactly one nonzero term per token."""
    b, s, d = normed.shape
    tokens = normed.reshape(-1, d)
    capacity = moe_capacity(tokens.shape[0], n_experts, capacity_factor)
    _, gate, keep, kept = _routing(params["router"], tokens, n_experts,
                                   capacity)
    delta = _ep_delta_from_routing(params, tokens, gate, keep, kept,
                                   n_experts, axis, act)
    return delta.reshape(b, s, d).astype(normed.dtype)


def make_ep_ffn_fn(cfg: TransformerConfig, mesh: Mesh, n_experts: int,
                   capacity_factor: float = 1.25, axis: str = "ep", *,
                   act):
    """Jitted `fn(params, x) -> x`: switch-FFN with experts sharded over
    `axis`. Place params with `shard_moe_params` first. Token count must be
    static per call (standard XLA); capacity derives from it."""
    n = mesh.shape[axis]
    if n_experts % n:
        raise ValueError(f"n_experts ({n_experts}) must divide by the ep "
                         f"axis size ({n})")

    param_specs = {
        "router": {"w": P(), "b": P()},
        "experts": {
            "mlp_up": {"w": P(axis), "b": P(axis)},
            "mlp_down": {"w": P(axis), "b": P(axis)},
        },
    }

    def fn(params, x):
        b, s, _ = x.shape
        capacity = moe_capacity(b * s, n_experts, capacity_factor)
        body = jax_compat.shard_map(
            partial(_ep_local, n_experts=n_experts, capacity=capacity,
                    axis=axis, act=act),
            mesh=mesh, in_specs=(param_specs, P()), out_specs=P())
        return body(params, x)

    return jax.jit(fn)


def shard_moe_params(params: Dict, mesh: Mesh, axis: str = "ep") -> Dict:
    """Place MoE params: experts sharded over `axis`, router replicated."""
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return {
        "router": {k: put(v, P()) for k, v in params["router"].items()},
        "experts": jax.tree_util.tree_map(
            lambda v: put(v, P(axis)), params["experts"]),
    }
