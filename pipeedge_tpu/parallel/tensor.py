"""Tensor parallelism: Megatron-style within-stage sharding of a block.

NEW capability beyond the reference (SURVEY.md §2.4: PipeEdge has no TP).
A transformer block's attention heads and MLP hidden dimension shard over a
mesh axis: q/k/v and MLP-up kernels column-split (no communication), the
attention-output and MLP-down kernels row-split, followed by one `psum` each
— the canonical 2-allreduce-per-block layout that keeps every matmul dense
on the local MXU.

Composes with the pipeline: a ('tp',)-sharded block runs inside one pipeline
stage, so a ('dp', 'stage', 'tp') mesh gives dp x pp x tp.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import jax_compat
from ..models.layers import (TransformerConfig, apply_causal_mask, gelu,
                             layer_norm)


# -- quantized TP collectives (trace-time flag, layers.set_fast_numerics
#    idiom): 0 = exact full-width psum; 4/8 = EQuARX-style quantized
#    allreduce (ops/qcollectives.py). Consumers must trace AFTER setting
#    it — make_tp_block_fn builds fresh per call, and SpmdPipeline keys
#    its compile cache on the current value.
_TP_QUANT_BITS = 0


def set_tp_quant_bits(bit: int) -> None:
    """Select the bitwidth of intra-stage TP/SP collectives (the
    runtime's --tp-quant-bits knob; docs/QUANT_COLLECTIVES.md)."""
    global _TP_QUANT_BITS  # pylint: disable=global-statement
    if bit not in (0, 4, 8):
        raise ValueError(f"tp quant bits must be 0, 4 or 8, got {bit}")
    _TP_QUANT_BITS = int(bit)


def get_tp_quant_bits() -> int:
    return _TP_QUANT_BITS


def tp_psum(x: jax.Array, axis: str) -> jax.Array:
    """THE allreduce of every Megatron block body here: exact psum at
    bits=0, quantized collective otherwise — the single gate the
    --tp-quant-bits knob flips for all six psum sites."""
    bit = _TP_QUANT_BITS
    if bit:
        from ..ops import qcollectives
        return qcollectives.qpsum(x, axis, bit)
    return jax.lax.psum(x, axis)


def _shard_by_specs(params: Dict, specs: Dict, mesh: Mesh,
                    axis: str) -> Dict:
    """Place a block's params per the SAME spec table shard_map uses as
    in_specs — one source of truth, so the placement can never drift from
    the compiled expectation (drift would silently reshard every call)."""
    specs = _rename_axis(specs, axis)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params,
        specs)


def shard_vit_block_params(params: Dict, mesh: Mesh, axis: str = "tp") -> Dict:
    """Place one ViT/DeiT block's params with Megatron TP sharding.

    Column-parallel (out-dim sharded): q/k/v, mlp_up. Row-parallel (in-dim
    sharded): attn_out, mlp_down. LayerNorms replicated.
    """
    return _shard_by_specs(params, _VIT_PARAM_SPECS, mesh, axis)


def _tp_block_local(p: Dict, x: jax.Array, cfg: TransformerConfig,
                    axis: str, act=gelu, causal: bool = False,
                    qkv_to_ctx=None, ffn_delta=None) -> jax.Array:
    """Per-device block body under shard_map: local head/hidden slices +
    two psums. `x` is replicated across the tp axis. Serves every pre-LN
    family: ViT/DeiT as-is, GPT-2 via act=gelu_new + causal=True.

    `qkv_to_ctx(q, k, v) -> ctx` ([b, s, h_local*hd]) overrides the
    attention core over the local heads — how KV-cache decoding plugs its
    cache-attend into this same projection/psum/MLP body
    (parallel/decode.py). `ffn_delta(p, normed) -> delta` replaces the
    dense Megatron MLP entirely — how the tp x ep MoE decode plugs the
    ep-sharded routed FFN under the tp-sharded attention
    (decode.make_tp_ep_stage_fns)."""
    n = jax_compat.axis_size(axis)
    heads_local = cfg.num_attention_heads // n
    b, s, d = x.shape
    hd = cfg.head_dim

    normed = layer_norm(p["ln_before"], x, cfg.layer_norm_eps)

    def proj(name):
        w = p[name]["w"]  # [D, D/n] local column slice
        y = jnp.dot(normed, w.astype(x.dtype),
                    preferred_element_type=jnp.float32) + p[name]["b"]
        return y.astype(x.dtype).reshape(b, s, heads_local, hd)

    q, k, v = proj("q"), proj("k"), proj("v")
    if qkv_to_ctx is not None:
        ctx = qkv_to_ctx(q, k, v)
    else:
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32) / jnp.sqrt(
                                jnp.float32(hd))
        if causal:
            scores = apply_causal_mask(scores)
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                         preferred_element_type=jnp.float32).astype(x.dtype)
        ctx = ctx.reshape(b, s, heads_local * hd)
    # row-parallel output projection: partial products summed across devices
    attn = jnp.dot(ctx, p["attn_out"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    attn = tp_psum(attn, axis) + p["attn_out"]["b"]
    x = attn.astype(x.dtype) + x

    normed = layer_norm(p["ln_after"], x, cfg.layer_norm_eps)
    if ffn_delta is not None:
        return x + ffn_delta(p, normed)
    up = jnp.dot(normed, p["mlp_up"]["w"].astype(x.dtype),
                 preferred_element_type=jnp.float32) + p["mlp_up"]["b"]
    hidden = act(up.astype(x.dtype))
    down = jnp.dot(hidden, p["mlp_down"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    down = tp_psum(down, axis) + p["mlp_down"]["b"]
    return down.astype(x.dtype) + x


def shard_bert_block_params(params: Dict, mesh: Mesh, axis: str = "tp") \
        -> Dict:
    """Place one BERT (post-LN) block's params with Megatron TP sharding:
    same column/row layout as ViT, LayerNorms (attn_ln/out_ln) replicated."""
    return _shard_by_specs(params, _BERT_PARAM_SPECS, mesh, axis)


def family_tp_plan(cfg: TransformerConfig):
    """THE family dispatch point for tensor parallelism: returns
    (param spec table, per-device block body). Every TP consumer — the
    placement helpers here and the SPMD pipeline's stacked specs/block
    body — goes through this, so adding a family is one edit. MoE
    configs refuse here (the dense column/row kernel table does not
    describe a routed FFN) — the MoE composition lives in
    `family_tp_ep_plan`."""
    if cfg.n_experts:
        raise NotImplementedError(
            "Megatron TP does not cover MoE blocks (experts shard over "
            "'ep', not the column/row kernel table) — see family_tp_ep_plan "
            "/ decode.make_tp_ep_stage_fns for the tp x ep composition")
    if cfg.model_type == "bert":
        return _BERT_PARAM_SPECS, _tp_bert_block_local
    if cfg.model_type == "gpt2":
        from ..models.layers import gelu_new
        return _VIT_PARAM_SPECS, partial(_tp_block_local, act=gelu_new,
                                         causal=True)
    if cfg.model_type == "llama":
        return _LLAMA_PARAM_SPECS, _tp_llama_block_local
    return _VIT_PARAM_SPECS, _tp_block_local


def family_tp_ep_plan(cfg: TransformerConfig):
    """Family dispatch for the tp x ep MoE composition: returns
    (attention param spec table over 'tp', FFN activation). The attention
    half of an MoE block shards exactly like its dense family's attention
    (column q/k/v, row attn_out, replicated LNs); the routed FFN shards
    over 'ep' (parallel/expert.py). decode.make_tp_ep_stage_fns is the
    consumer — adding an MoE family is one edit HERE, mirroring
    family_tp_plan's single-dispatch-point contract."""
    if not cfg.n_experts:
        raise ValueError("family_tp_ep_plan requires an MoE config "
                         "(cfg.n_experts > 0); use family_tp_plan")
    if cfg.model_type == "gpt2":
        from ..models.layers import gelu_new
        return _VIT_PARAM_SPECS, gelu_new
    raise NotImplementedError(
        f"no tp x ep plan for MoE family {cfg.model_type!r}")


def shard_block_params(cfg: TransformerConfig, params: Dict, mesh: Mesh,
                       axis: str = "tp") -> Dict:
    """Megatron placement for one block's params (family-dispatched)."""
    specs, _ = family_tp_plan(cfg)
    return _shard_by_specs(params, specs, mesh, axis)


def _tp_bert_block_local(p: Dict, x: jax.Array, cfg: TransformerConfig,
                         axis: str) -> jax.Array:
    """Per-device BERT block body (post-LN residuals, bert.py sublayer
    semantics 0-3): attention on raw x, LayerNorm AFTER each residual."""
    n = jax_compat.axis_size(axis)
    heads_local = cfg.num_attention_heads // n
    b, s, _ = x.shape
    hd = cfg.head_dim

    def proj(name):
        w = p[name]["w"]  # [D, D/n] local column slice
        y = jnp.dot(x, w.astype(x.dtype),
                    preferred_element_type=jnp.float32) + p[name]["b"]
        return y.astype(x.dtype).reshape(b, s, heads_local, hd)

    q, k, v = proj("q"), proj("k"), proj("v")
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
                            jnp.float32(hd))
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    ctx = ctx.reshape(b, s, heads_local * hd)
    attn = jnp.dot(ctx, p["attn_out"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    attn = tp_psum(attn, axis) + p["attn_out"]["b"]
    x = layer_norm(p["attn_ln"], attn.astype(x.dtype) + x,
                   cfg.layer_norm_eps)

    up = jnp.dot(x, p["mlp_up"]["w"].astype(x.dtype),
                 preferred_element_type=jnp.float32) + p["mlp_up"]["b"]
    hidden = gelu(up.astype(x.dtype))
    down = jnp.dot(hidden, p["mlp_down"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    down = tp_psum(down, axis) + p["mlp_down"]["b"]
    return layer_norm(p["out_ln"], down.astype(x.dtype) + x,
                      cfg.layer_norm_eps)


def _tp_llama_block_local(p: Dict, x: jax.Array, cfg: TransformerConfig,
                          axis: str, qkv_to_ctx=None,
                          pos_ids=None) -> jax.Array:
    """Per-device llama block body (pre-RMSNorm, RoPE, GQA, SwiGLU).

    Column-sharded q/k/v keep GQA grouping local: shard i holds query
    heads [i*h/n, (i+1)*h/n) and kv heads [i*kv/n, (i+1)*kv/n), and query
    head g's kv head g//(h/kv) lands on the same shard, so the local
    repeat-and-attend needs no collective. Requires heads, kv_heads, and
    intermediate_size divisible by the tp degree (reshapes fail loudly
    otherwise). Two psums per block, like every Megatron body here.

    `qkv_to_ctx(q, k, v) -> ctx` overrides the attention core over the
    local (RoPE'd) heads and `pos_ids` the rotation positions — how the
    llama KV-cached tp decode step plugs its cache-attend into this same
    projection/psum/SwiGLU body (models/llama.py tp_cached_block_step),
    mirroring _tp_block_local's hook for GPT-2."""
    from ..models.layers import rms_norm, rope_rotate
    from ..models.llama import _gqa_attend

    n = jax_compat.axis_size(axis)
    heads_local = cfg.num_attention_heads // n
    kv_local = cfg.kv_heads // n
    b, s, _ = x.shape
    hd = cfg.head_dim

    normed = rms_norm(p["ln_before"], x, cfg.layer_norm_eps)
    pos = jnp.arange(s) if pos_ids is None else pos_ids

    def proj(name, n_heads):
        y = jnp.dot(normed, p[name]["w"].astype(x.dtype),
                    preferred_element_type=jnp.float32) + p[name]["b"]
        return y.astype(x.dtype).reshape(b, s, n_heads, hd)

    q = rope_rotate(proj("q", heads_local), pos, cfg.rope_theta)
    k = rope_rotate(proj("k", kv_local), pos, cfg.rope_theta)
    v = proj("v", kv_local)
    ctx = (qkv_to_ctx(q, k, v) if qkv_to_ctx is not None
           else _gqa_attend(q, k, v, cfg))   # local heads, causal
    attn = jnp.dot(ctx, p["attn_out"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    attn = tp_psum(attn, axis) + p["attn_out"]["b"]
    x = attn.astype(x.dtype) + x

    normed = rms_norm(p["ln_after"], x, cfg.layer_norm_eps)
    gate = jnp.dot(normed, p["mlp_gate"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32) + p["mlp_gate"]["b"]
    up = jnp.dot(normed, p["mlp_up"]["w"].astype(x.dtype),
                 preferred_element_type=jnp.float32) + p["mlp_up"]["b"]
    hidden = jax.nn.silu(gate).astype(x.dtype) * up.astype(x.dtype)
    down = jnp.dot(hidden, p["mlp_down"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    down = tp_psum(down, axis) + p["mlp_down"]["b"]
    return down.astype(x.dtype) + x


_LLAMA_PARAM_SPECS = {
    "q": {"w": P(None, "tp"), "b": P("tp")},
    "k": {"w": P(None, "tp"), "b": P("tp")},
    "v": {"w": P(None, "tp"), "b": P("tp")},
    "attn_out": {"w": P("tp", None), "b": P()},
    "mlp_gate": {"w": P(None, "tp"), "b": P("tp")},
    "mlp_up": {"w": P(None, "tp"), "b": P("tp")},
    "mlp_down": {"w": P("tp", None), "b": P()},
    "ln_before": {"scale": P()},
    "ln_after": {"scale": P()},
}

_VIT_PARAM_SPECS = {
    "q": {"w": P(None, "tp"), "b": P("tp")},
    "k": {"w": P(None, "tp"), "b": P("tp")},
    "v": {"w": P(None, "tp"), "b": P("tp")},
    "attn_out": {"w": P("tp", None), "b": P()},
    "mlp_up": {"w": P(None, "tp"), "b": P("tp")},
    "mlp_down": {"w": P("tp", None), "b": P()},
    "ln_before": {"scale": P(), "bias": P()},
    "ln_after": {"scale": P(), "bias": P()},
}

_BERT_PARAM_SPECS = {
    "q": {"w": P(None, "tp"), "b": P("tp")},
    "k": {"w": P(None, "tp"), "b": P("tp")},
    "v": {"w": P(None, "tp"), "b": P("tp")},
    "attn_out": {"w": P("tp", None), "b": P()},
    "mlp_up": {"w": P(None, "tp"), "b": P("tp")},
    "mlp_down": {"w": P("tp", None), "b": P()},
    "attn_ln": {"scale": P(), "bias": P()},
    "out_ln": {"scale": P(), "bias": P()},
}


def _rename_axis(specs, axis):
    if axis == "tp":
        return specs
    return jax.tree_util.tree_map(
        lambda s: P(*(axis if a == "tp" else a for a in s)), specs,
        is_leaf=lambda s: isinstance(s, P))


def make_tp_block_fn(cfg: TransformerConfig, mesh: Mesh, axis: str = "tp"):
    """Jitted `fn(sharded_params, x) -> x` running one full transformer block
    with tensor parallelism over `axis`. `x` is replicated. Dispatches on
    the family: ViT/DeiT pre-LN blocks or BERT post-LN blocks."""
    specs, local = family_tp_plan(cfg)
    param_specs = _rename_axis(specs, axis)
    body = jax_compat.shard_map(partial(local, cfg=cfg, axis=axis),
                         mesh=mesh, in_specs=(param_specs, P()),
                         out_specs=P())
    return jax.jit(body)
