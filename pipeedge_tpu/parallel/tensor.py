"""Tensor parallelism: Megatron-style within-stage sharding of a block.

NEW capability beyond the reference (SURVEY.md §2.4: PipeEdge has no TP).
A transformer block's attention heads and MLP hidden dimension shard over a
mesh axis: q/k/v and MLP-up kernels column-split (no communication), the
attention-output and MLP-down kernels row-split, followed by one `psum` each
— the canonical 2-allreduce-per-block layout that keeps every matmul dense
on the local MXU.

Composes with the pipeline: a ('tp',)-sharded block runs inside one pipeline
stage, so a ('dp', 'stage', 'tp') mesh gives dp x pp x tp.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.layers import TransformerConfig, gelu, layer_norm


def shard_vit_block_params(params: Dict, mesh: Mesh, axis: str = "tp") -> Dict:
    """Place one ViT/DeiT block's params with Megatron TP sharding.

    Column-parallel (out-dim sharded): q/k/v, mlp_up. Row-parallel (in-dim
    sharded): attn_out, mlp_down. LayerNorms replicated.
    """
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    out = {}
    for name in ("q", "k", "v"):
        out[name] = {"w": put(params[name]["w"], P(None, axis)),
                     "b": put(params[name]["b"], P(axis))}
    out["attn_out"] = {"w": put(params["attn_out"]["w"], P(axis, None)),
                       "b": put(params["attn_out"]["b"], P())}
    out["mlp_up"] = {"w": put(params["mlp_up"]["w"], P(None, axis)),
                     "b": put(params["mlp_up"]["b"], P(axis))}
    out["mlp_down"] = {"w": put(params["mlp_down"]["w"], P(axis, None)),
                       "b": put(params["mlp_down"]["b"], P())}
    for ln in ("ln_before", "ln_after"):
        out[ln] = {k: put(v, P()) for k, v in params[ln].items()}
    return out


def _tp_block_local(p: Dict, x: jax.Array, cfg: TransformerConfig,
                    axis: str) -> jax.Array:
    """Per-device block body under shard_map: local head/hidden slices +
    two psums. `x` is replicated across the tp axis."""
    n = jax.lax.axis_size(axis)
    heads_local = cfg.num_attention_heads // n
    b, s, d = x.shape
    hd = cfg.head_dim

    normed = layer_norm(p["ln_before"], x, cfg.layer_norm_eps)

    def proj(name):
        w = p[name]["w"]  # [D, D/n] local column slice
        y = jnp.dot(normed, w.astype(x.dtype),
                    preferred_element_type=jnp.float32) + p[name]["b"]
        return y.astype(x.dtype).reshape(b, s, heads_local, hd)

    q, k, v = proj("q"), proj("k"), proj("v")
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) / jnp.sqrt(
                            jnp.float32(hd))
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(x.dtype)
    ctx = ctx.reshape(b, s, heads_local * hd)
    # row-parallel output projection: partial products summed across devices
    attn = jnp.dot(ctx, p["attn_out"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    attn = jax.lax.psum(attn, axis) + p["attn_out"]["b"]
    x = attn.astype(x.dtype) + x

    normed = layer_norm(p["ln_after"], x, cfg.layer_norm_eps)
    up = jnp.dot(normed, p["mlp_up"]["w"].astype(x.dtype),
                 preferred_element_type=jnp.float32) + p["mlp_up"]["b"]
    hidden = gelu(up.astype(x.dtype))
    down = jnp.dot(hidden, p["mlp_down"]["w"].astype(x.dtype),
                   preferred_element_type=jnp.float32)
    down = jax.lax.psum(down, axis) + p["mlp_down"]["b"]
    return down.astype(x.dtype) + x


def make_tp_block_fn(cfg: TransformerConfig, mesh: Mesh, axis: str = "tp"):
    """Jitted `fn(sharded_params, x) -> x` running one full transformer block
    with tensor parallelism over `axis`. `x` is replicated."""
    param_specs = {
        "q": {"w": P(None, axis), "b": P(axis)},
        "k": {"w": P(None, axis), "b": P(axis)},
        "v": {"w": P(None, axis), "b": P(axis)},
        "attn_out": {"w": P(axis, None), "b": P()},
        "mlp_up": {"w": P(None, axis), "b": P(axis)},
        "mlp_down": {"w": P(axis, None), "b": P()},
        "ln_before": {"scale": P(), "bias": P()},
        "ln_after": {"scale": P(), "bias": P()},
    }
    body = jax.shard_map(partial(_tp_block_local, cfg=cfg, axis=axis),
                         mesh=mesh, in_specs=(param_specs, P()),
                         out_specs=P(), check_vma=False)
    return jax.jit(body)
