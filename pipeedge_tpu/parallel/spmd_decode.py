"""SPMD wave decoding: continuous batching as ONE XLA program per phase.

The host-driven `ContinuousBatcher` (parallel/batcher.py) dispatches one
stage program per (stage, tick) — n_stages dispatches per tick, with the
host in the loop. On real hardware each dispatch costs fixed overhead
(tens of ms through a tunneled controller — docs/PERF.md), which dwarfs a
decode step's compute. This module compiles the ENTIRE wave schedule into
two `shard_map` programs over a ('stage',) mesh:

- **prefill program**: R = n_stages requests enter stage 0 on successive
  ticks; each stage prefills a different request per tick (full-prompt
  pass), hidden states hop stage-to-stage via `lax.ppermute` over ICI,
  and the last stage emits each request's first greedy token. 2K-1 ticks.
- **decode program**: the steady-state wave — per tick, stage i decodes
  the request whose wave is at stage i (`req = (t - i) mod K`), so every
  stage works every tick and the fleet emits ~one token per tick
  (min(S, K)x a solo stream, with ZERO host round-trips inside the
  generation: one `lax.scan` over all (N-1)*K + K-1 ticks).

Design notes (mirrors parallel/spmd.py's forward pipeline):
- Stage-stacked zero-padded blocks with an `n_blocks` validity count;
  embeddings/finalize run under `lax.cond` on the device-local stage
  index, so only stage 0 pays the embed and only the last stage pays the
  LM-head matmul per tick.
- Per-stage KV caches hold every request's rows for that stage's blocks:
  leaf [stage, max_b, R, B, T, H, Dh], sharded over 'stage'. A tick
  dynamic-slices its request's cache, runs the shared cached block step
  (parallel/decode.py `_block_step` — one attention/cache semantics for
  host and SPMD decode), and writes back gated on tick validity so
  fill/drain garbage never corrupts a cache.
- Wave bookkeeping is arithmetic, not state: request r's decode wave m
  runs pos = S_p + m - 1, and stage i at tick t serves req (t-i) mod K at
  wave floor((t-i)/K)+1 — every device derives it from t, keeping all
  replicated state in lockstep. New tokens broadcast last-stage -> all
  via one psum (the only collective besides the edge ppermute).

Scope: greedy or temperature/top-k sampled decoding (per-slot rng chains
split once per picked token, in lockstep on every device — the host
generate() discipline), R == n_stages request slots, equal prompt
lengths/budgets per slot (the static-shape steady state; the host-driven
batcher handles ragged arrivals). Token-identical to per-request
`DecodePipeline.generate` (tests/test_spmd_decode.py).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..utils import jax_compat
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models import ShardConfig
from ..models.layers import TransformerConfig
from ..models.shard import FamilySpec
from . import decode as dec
from .spmd import _pad_stack, partition_to_blocks


class SpmdDecodePipeline:
    """Wave-scheduled decoding compiled over a ('stage',) mesh.

    `generate(ids, new_tokens)` takes ids [R, B, S_p] — R = n_stages
    request slots decoded concurrently — and returns [R, B, S_p + N].
    """

    def __init__(self, family: FamilySpec, cfg: TransformerConfig,
                 partition: Sequence[Tuple[int, int]],
                 stage_params: Sequence[Dict], mesh: Mesh, max_len: int,
                 dtype=jnp.float32, edge_bits: int = 0):
        total = 4 * cfg.num_hidden_layers
        dec.validate_partition(partition, total)
        dec.validate_capacity(cfg, max_len)
        block_ranges = partition_to_blocks(partition)
        n_stages = len(partition)
        if mesh.shape["stage"] != n_stages:
            raise ValueError(f"mesh 'stage' axis {mesh.shape['stage']} != "
                             f"{n_stages} pipeline stages")
        if cfg.n_experts:
            raise NotImplementedError(
                "SPMD wave decode covers dense families; MoE decodes via "
                "DecodePipeline(ep_mesh/tp_ep_mesh)")
        if edge_bits not in (0, 2, 4, 6, 8, 16):
            raise ValueError(f"edge_bits must be one of 0/2/4/6/8/16, got "
                             f"{edge_bits}")
        self.family, self.cfg, self.mesh = family, cfg, mesh
        self.n_stages, self.max_len, self.dtype = n_stages, max_len, dtype
        self.edge_bits = edge_bits

        stage_blocks, n_blocks = [], []
        embed = final = None
        for i, p in enumerate(stage_params):
            p = dict(p)
            p["blocks"] = dec.stage_blocks(p)
            stage_blocks.append(p["blocks"])
            n_blocks.append(block_ranges[i][1] - block_ranges[i][0] + 1)
            if i == 0:
                embed = p["embeddings"]
            if i == n_stages - 1:
                final = p["final"]
        if embed is None or final is None:
            raise ValueError("stage 0 must carry 'embeddings' and the last "
                             "stage 'final'")
        self.max_b = max(n_blocks)
        self._n_blocks = tuple(n_blocks)   # per-stage, for prefix sigs
        # place params ONCE with the same shardings the programs compile
        # against (spmd.py's placement discipline): blocks/n_blocks
        # stage-sharded, embed/final replicated. Without this the padded
        # stack would materialize on one device and reshard every call.
        from jax.sharding import NamedSharding
        params = {
            "embed": embed, "final": final,
            "blocks": _pad_stack(stage_blocks, self.max_b),
            "n_blocks": jnp.asarray(n_blocks, jnp.int32),
        }
        shard = NamedSharding(mesh, P("stage"))
        repl = NamedSharding(mesh, P())
        self.params = {
            "embed": jax.device_put(params["embed"], repl),
            "final": jax.device_put(params["final"], repl),
            "blocks": jax.tree_util.tree_map(
                lambda x: jax.device_put(x, shard), params["blocks"]),
            "n_blocks": jax.device_put(params["n_blocks"], shard),
        }
        self._programs: Dict = {}
        self._cache_init: Dict = {}

    # -- shared per-tick pieces -------------------------------------------

    def _run_blocks(self, blocks, n_valid, x, bcache, pos, prefill):
        """Scan this stage's (padded) blocks over x with cache read/update;
        padded slots pass through unchanged. The block body is the
        family's cached step when it provides one (llama RoPE/GQA/SwiGLU),
        else the default GPT-2-shaped step — same dispatch as the host
        decode pipeline."""
        cfg = self.cfg
        block_fn = getattr(self.family, "cached_block_step", None) \
            or dec._block_step

        def step(carry, xs):
            j, bp, bc = xs

            def live(args):
                c, cache_j = args
                return block_fn(bp, c, cache_j, pos, cfg, prefill)

            out, bc_new = jax.lax.cond(
                j < n_valid, live, lambda args: args, (carry, bc))
            return out, bc_new

        idx = jnp.arange(self.max_b)
        return jax.lax.scan(step, x, (idx, blocks, bcache))

    def _cache_slice(self, caches, req):
        """caches leaf [max_b, R, B, T, H, Dh] -> request slice [max_b, B,..]."""
        return jax.tree_util.tree_map(
            lambda c: jax.lax.dynamic_index_in_dim(c, req, axis=1,
                                                   keepdims=False), caches)

    def _cache_write(self, caches, bcache, req, valid):
        def wr(c, new):
            new = jnp.where(valid, new, jax.lax.dynamic_index_in_dim(
                c, req, axis=1, keepdims=False))
            return jax.lax.dynamic_update_index_in_dim(
                c, new.astype(c.dtype), req, axis=1)

        return jax.tree_util.tree_map(wr, caches, bcache)

    def _zero_caches(self, r_slots, batch):
        """Stage-sharded zero caches, allocated ALREADY sharded: a plain
        jnp.zeros would materialize every stage's cache on one device (an
        HBM spike ~n_stages x the per-device share) before resharding.
        The jitted init is cached per shape so repeated generate() calls
        hit the jit cache instead of recompiling."""
        if (r_slots, batch) not in self._cache_init:
            from jax.sharding import NamedSharding
            shape = (self.n_stages, self.max_b, r_slots, batch,
                     self.max_len, self.cfg.kv_heads, self.cfg.head_dim)
            self._cache_init[(r_slots, batch)] = jax.jit(
                partial(jnp.zeros, shape, self.dtype),
                out_shardings=NamedSharding(self.mesh, P("stage")))
        zeros = self._cache_init[(r_slots, batch)]
        return {"k": zeros(), "v": zeros()}

    def _broadcast_prefix_caches(self, handle, r_slots, batch):
        """Tile a `precompute_prefix` handle's [stage, max_b, 1, 1, T, ..]
        cache to every (slot, batch row) — sharded on allocation, like
        `_zero_caches` (prompt caching's batch-tiling rule)."""
        from jax.sharding import NamedSharding
        key = ("pfx-tile", r_slots, batch)
        if key not in self._cache_init:
            shape = (self.n_stages, self.max_b, r_slots, batch,
                     self.max_len, self.cfg.kv_heads, self.cfg.head_dim)
            self._cache_init[key] = jax.jit(
                partial(jnp.broadcast_to, shape=shape),
                out_shardings=NamedSharding(self.mesh, P("stage")))
        tile = self._cache_init[key]
        return {k: tile(v) for k, v in handle["caches"].items()}

    # -- compiled phases ---------------------------------------------------

    @staticmethod
    def _local(params, caches):
        blocks = jax.tree_util.tree_map(lambda x: x[0], params["blocks"])
        caches = {k: v[0] for k, v in caches.items()}
        n_valid = params["n_blocks"][0]
        stage = jax.lax.axis_index("stage")
        return blocks, caches, n_valid, stage

    def _make_split_for(self, r_slots):
        """Split the key of the request at the LAST stage this tick —
        computed identically on every device (replicated rngs, tick
        arithmetic), so the fleet's rng state stays in lockstep. One
        split per picked token, the host generate() discipline. ONE
        definition for all three wave programs (prefill/decode/span)."""
        k_stages = self.n_stages

        def split_for(rngs, t):
            req_last = jnp.mod(t - (k_stages - 1), r_slots)
            key, sub = jax.random.split(rngs[req_last])
            return req_last, jax.lax.dynamic_update_index_in_dim(
                rngs, key, req_last, axis=0), sub

        return split_for

    def _edge_codec(self):
        """Stage-edge payload codec: QuantPipe activation compression on
        the big ([B, S, D]-sized) ppermute hops when `edge_bits` is set —
        shared by the prefill wave AND the span wave so prefix-seeded
        suffix passes stay numerically identical to monolithic runs."""
        from ..ops import quant as quant_ops
        bit = self.edge_bits

        def enc(h):
            return h if bit == 0 else \
                quant_ops.tensor_encode_outerdim(h, bit)

        def decode_payload(payload):
            return payload if bit == 0 else \
                quant_ops.tensor_decode_outerdim(payload).astype(self.dtype)

        return enc, decode_payload

    def _specs(self):
        blocks_spec = jax.tree_util.tree_map(
            lambda _: P("stage"), self.params["blocks"])
        p_spec = {"embed": P(), "final": P(), "blocks": blocks_spec,
                  "n_blocks": P("stage")}
        return p_spec, {"k": P("stage"), "v": P("stage")}

    def _prefill_prog(self, r_slots, batch, prompt_len, temperature=0.0,
                      top_k=0):
        """Cached compiled prefill wave — keyed WITHOUT new_tokens (the
        prefill program doesn't depend on it), so every generation
        length and the speculative driver share one compile."""
        key = ("prefill", r_slots, batch, prompt_len, float(temperature),
               int(top_k))
        if key not in self._programs:
            self._programs[key] = self._build_prefill(
                r_slots, batch, prompt_len, float(temperature),
                int(top_k))
        return self._programs[key]

    def _decode_prog(self, r_slots, batch, prompt_len, new_tokens,
                     temperature=0.0, top_k=0):
        key = ("decode", r_slots, batch, prompt_len, new_tokens,
               float(temperature), int(top_k))
        if key not in self._programs:
            self._programs[key] = self._build_decode(
                r_slots, batch, prompt_len, new_tokens,
                float(temperature), int(top_k))
        return self._programs[key]

    def _build_prefill(self, r_slots: int, batch: int, prompt_len: int,
                       temperature: float, top_k: int):
        family, cfg, k_stages = self.family, self.cfg, self.n_stages
        d = cfg.hidden_size
        pick = dec.make_token_picker(temperature, top_k)
        local = self._local
        split_for = self._make_split_for(r_slots)

        def prefill_body(params, ids, caches, rngs):
            """Wave-prefill all R requests; returns (caches, token1 [R, B],
            advanced rng keys). With `edge_bits`, the [B, S_p, D] prompt
            hops — the wave decoder's big payloads — cross the stage edge
            as packed uint32 (QuantPipe activation compression riding the
            ppermute, like the forward SPMD pipeline's quantized edges);
            the [B, 1, D] decode-step hops stay raw (metadata-sized)."""
            blocks, caches, n_valid, stage = local(params, caches)
            is_first = stage == 0
            is_last = stage == k_stages - 1
            # QuantizedTensor is a registered pytree (static shape/bit aux),
            # so the encoded payload rides the tree_map'd ppermute directly
            # — the same discipline as spmd.py's uniform quantized edges
            edge_enc, edge_dec = self._edge_codec()
            tokens0 = jnp.zeros((r_slots, batch), jnp.int32)

            def tick(carry, t):
                hidden, caches, tokens, rngs = carry
                recv = jax.tree_util.tree_map(
                    lambda leaf: jax.lax.ppermute(
                        leaf, "stage",
                        [(i, (i + 1) % k_stages) for i in range(k_stages)]),
                    hidden)
                req = jnp.mod(t - stage, r_slots)
                valid = jnp.logical_and(t - stage >= 0,
                                        t - stage < r_slots)
                # stage 0 embeds its request's prompt; every other stage
                # consumes the ppermuted (possibly packed) hop
                x = jax.lax.cond(
                    is_first,
                    lambda r: family.embed(
                        params["embed"],
                        jax.lax.dynamic_index_in_dim(ids, r, 0, False),
                        cfg).astype(self.dtype),
                    lambda r: edge_dec(recv), req)
                bcache = self._cache_slice(caches, req)
                h, bcache = self._run_blocks(blocks, n_valid, x, bcache,
                                             0, prefill=True)
                caches = self._cache_write(caches, bcache, req, valid)
                req_last, rngs_new, sub = split_for(rngs, t)
                valid_last = jnp.logical_and(t >= k_stages - 1,
                                             t - (k_stages - 1) < r_slots)
                rngs = jnp.where(valid_last, rngs_new, rngs)

                def fin(hh):
                    logits = family.finalize(params["final"], hh, cfg)
                    return pick(logits[:, prompt_len - 1].astype(
                        jnp.float32), sub).astype(jnp.int32)

                tok = jax.lax.cond(
                    is_last, fin,
                    lambda hh: jnp.zeros((batch,), jnp.int32), h)
                write = jnp.logical_and(valid, is_last)
                upd = jax.lax.dynamic_update_index_in_dim(
                    tokens, tok, req, axis=0)
                tokens = jnp.where(write, upd, tokens)
                return (edge_enc(h), caches, tokens, rngs), None

            hidden0 = edge_enc(jnp.zeros((batch, prompt_len, d),
                                         self.dtype))
            (_, caches, tokens, rngs), _ = jax.lax.scan(
                tick, (hidden0, caches, tokens0, rngs),
                jnp.arange(r_slots + k_stages - 1))
            # only the last stage wrote tokens; fan out to every device
            return ({k: v[None] for k, v in caches.items()},
                    jax.lax.psum(tokens, "stage"), rngs)

        p_spec, c_spec = self._specs()
        return jax.jit(jax_compat.shard_map(
            prefill_body, mesh=self.mesh,
            in_specs=(p_spec, P(), c_spec, P()),
            out_specs=(c_spec, P(), P())))

    def _build_decode(self, r_slots: int, batch: int, prompt_len: int,
                      new_tokens: int, temperature: float, top_k: int):
        family, cfg, k_stages = self.family, self.cfg, self.n_stages
        d = cfg.hidden_size
        pick = dec.make_token_picker(temperature, top_k)
        local = self._local
        split_for = self._make_split_for(r_slots)

        def decode_body(params, token1, caches, rngs):
            """All remaining waves: returns tokens [R, new_tokens, B]."""
            blocks, caches, n_valid, stage = local(params, caches)
            is_first = stage == 0
            is_last = stage == k_stages - 1
            n_waves = new_tokens - 1     # wave m in [1, n_waves] -> token m+1

            def embed_tok(tok, pos):
                # the family's single-token embedding rule, shared with
                # the host stage runner (llama: wte only; default wte+wpe)
                tok_embed = getattr(family, "decode_embed", None) \
                    or dec.single_token_embed
                return tok_embed(params["embed"], tok, pos).astype(
                    self.dtype)

            outputs0 = jnp.zeros((r_slots, new_tokens, batch), jnp.int32)
            outputs0 = outputs0.at[:, 0].set(token1)

            def tick(carry, t):
                hidden, caches, cur_tok, outputs, rngs = carry
                recv = jax.lax.ppermute(
                    hidden, "stage",
                    [(i, (i + 1) % k_stages) for i in range(k_stages)])
                req = jnp.mod(t - stage, r_slots)
                wave = jnp.floor_divide(t - stage, r_slots) + 1
                valid = jnp.logical_and(t - stage >= 0, wave <= n_waves)
                pos = prompt_len + wave - 1

                x = jax.lax.cond(
                    is_first,
                    lambda a: embed_tok(*a),
                    lambda a: recv,
                    (cur_tok[req], pos))
                bcache = self._cache_slice(caches, req)
                h, bcache = self._run_blocks(blocks, n_valid, x, bcache,
                                             pos, prefill=False)
                caches = self._cache_write(caches, bcache, req, valid)
                # the request at the LAST stage this tick (device-uniform)
                req_last, rngs_new, sub = split_for(rngs, t)
                wave_last = jnp.floor_divide(t - (k_stages - 1), r_slots) + 1
                valid_last = jnp.logical_and(t >= k_stages - 1,
                                             wave_last <= n_waves)
                rngs = jnp.where(valid_last, rngs_new, rngs)

                def fin(hh):
                    logits = family.finalize(params["final"], hh, cfg)
                    return pick(logits[:, 0].astype(jnp.float32),
                                sub).astype(jnp.int32)

                tok = jax.lax.cond(
                    is_last, fin,
                    lambda hh: jnp.zeros((batch,), jnp.int32), h)
                # broadcast the new token to every stage (one psum)
                tok_all = jax.lax.psum(tok, "stage")
                upd = jax.lax.dynamic_update_index_in_dim(
                    cur_tok, tok_all, req_last, axis=0)
                cur_tok = jnp.where(valid_last, upd, cur_tok)
                out_upd = jax.lax.dynamic_update_slice(
                    outputs, tok_all[None, None],
                    (req_last, jnp.clip(wave_last, 0, new_tokens - 1), 0))
                outputs = jnp.where(valid_last, out_upd, outputs)
                return (h, caches, cur_tok, outputs, rngs), None

            hidden0 = jnp.zeros((batch, 1, d), self.dtype)
            n_ticks = n_waves * r_slots + k_stages - 1
            (_, _, _, outputs, _), _ = jax.lax.scan(
                tick, (hidden0, caches, token1, outputs0, rngs),
                jnp.arange(n_ticks))
            return outputs

        p_spec, c_spec = self._specs()
        return jax.jit(jax_compat.shard_map(
            decode_body, mesh=self.mesh,
            in_specs=(p_spec, P(), c_spec, P()),
            out_specs=P()))

    def _build_span(self, r_slots: int, batch: int, span_k: int,
                    emit: str, temperature: float = 0.0, top_k: int = 0):
        """ONE wave over K-token spans: tick t, stage i runs slot
        (t-i) mod R's [B, K] span at cache offset `pos` (a traced scalar
        — one compiled program serves every round/offset). The span
        semantics are the host pipeline's `extend` (K/V written at
        [pos, pos+K), causal within the span, full history before it) —
        the same `_block_step` body, so wave spans and host spans can
        never diverge.

        `emit='pick_last'` returns (caches, picked last-row token [R, B],
        advanced rngs) — the prefix-seeded SUFFIX prompt pass.
        `emit='argmax_all'` returns (caches, greedy argmax of every span
        row [R, K, B]) — the speculative VERIFY primitive."""
        family, cfg, k_stages = self.family, self.cfg, self.n_stages
        d = cfg.hidden_size
        pick = dec.make_token_picker(temperature, top_k)
        local = self._local
        split_for = self._make_split_for(r_slots)

        def span_embed_slot(params, tok, pos):
            tok_embed = getattr(family, "span_embed", None) \
                or dec.span_embed
            return tok_embed(params["embed"], tok, pos).astype(self.dtype)

        def span_body(params, spans, caches, pos, rngs):
            blocks, caches, n_valid, stage = local(params, caches)
            is_first = stage == 0
            is_last = stage == k_stages - 1
            # span hops are prompt-sized [B, K, D]: the edge codec rides
            # them exactly like the prefill wave's, so prefix-seeded
            # suffix passes match monolithic runs on quantized-edge
            # pipelines too
            edge_enc, edge_dec = self._edge_codec()
            if emit == "pick_last":
                outputs0 = jnp.zeros((r_slots, batch), jnp.int32)
            else:
                outputs0 = jnp.zeros((r_slots, span_k, batch), jnp.int32)

            def tick(carry, t):
                hidden, caches, outputs, rngs_ = carry
                recv = jax.tree_util.tree_map(
                    lambda leaf: jax.lax.ppermute(
                        leaf, "stage",
                        [(i, (i + 1) % k_stages) for i in range(k_stages)]),
                    hidden)
                req = jnp.mod(t - stage, r_slots)
                valid = jnp.logical_and(t - stage >= 0,
                                        t - stage < r_slots)
                x = jax.lax.cond(
                    is_first,
                    lambda r: span_embed_slot(
                        params,
                        jax.lax.dynamic_index_in_dim(spans, r, 0, False),
                        pos),
                    lambda r: edge_dec(recv), req)
                bcache = self._cache_slice(caches, req)
                h, bcache = self._run_blocks(blocks, n_valid, x, bcache,
                                             pos, prefill=False)
                caches = self._cache_write(caches, bcache, req, valid)
                req_last, rngs_new, sub = split_for(rngs_, t)
                valid_last = jnp.logical_and(t >= k_stages - 1,
                                             t - (k_stages - 1) < r_slots)
                rngs_ = jnp.where(valid_last, rngs_new, rngs_)

                if emit == "pick_last":
                    def fin(hh):
                        logits = family.finalize(params["final"], hh, cfg)
                        return pick(logits[:, span_k - 1].astype(
                            jnp.float32), sub).astype(jnp.int32)

                    zero = jnp.zeros((batch,), jnp.int32)
                else:
                    def fin(hh):
                        logits = family.finalize(params["final"], hh, cfg)
                        return jnp.argmax(
                            logits.astype(jnp.float32),
                            -1).astype(jnp.int32).T        # [K, B]

                    zero = jnp.zeros((span_k, batch), jnp.int32)
                tok = jax.lax.cond(is_last, fin, lambda hh: zero, h)
                upd = jax.lax.dynamic_update_index_in_dim(
                    outputs, tok, req_last, axis=0)
                outputs = jnp.where(valid_last, upd, outputs)
                return (edge_enc(h), caches, outputs, rngs_), None

            hidden0 = edge_enc(jnp.zeros((batch, span_k, d), self.dtype))
            (_, caches, outputs, rngs), _ = jax.lax.scan(
                tick, (hidden0, caches, outputs0, rngs),
                jnp.arange(r_slots + k_stages - 1))
            return ({k: v[None] for k, v in caches.items()},
                    jax.lax.psum(outputs, "stage"), rngs)

        p_spec, c_spec = self._specs()
        return jax.jit(jax_compat.shard_map(
            span_body, mesh=self.mesh,
            in_specs=(p_spec, P(), c_spec, P(), P()),
            out_specs=(c_spec, P(), P())))

    def _prefix_sig(self) -> Tuple:
        """Cache-compatibility signature for wave prefix handles (the
        host pipeline's `_prefix_sig` discipline: a handle is only valid
        on a pipeline whose cache layout AND numerics match — per-stage
        block counts catch same-shape different-partition pipelines,
        edge_bits catches quantized-edge numerics)."""
        return ("spmd-prefix-v1", self._n_blocks, self.max_len,
                jax.dtypes.canonicalize_dtype(self.dtype).name,
                self.cfg.kv_heads, self.cfg.head_dim, self.edge_bits)

    def check_prefix(self, prefix) -> None:
        sig = prefix.get("sig") if isinstance(prefix, dict) else None
        if sig is None:
            raise ValueError(
                "prefix is not a precompute_prefix handle (no 'sig' "
                "stamp); build it with this pipeline's precompute_prefix")
        if sig != self._prefix_sig():
            raise ValueError(
                "prefix handle was built by an incompatible wave "
                f"pipeline: handle sig {sig} vs {self._prefix_sig()}")

    def precompute_prefix(self, prefix_ids) -> Dict:
        """Prefill a shared prompt PREFIX once through the wave pipeline
        (a one-slot, batch-1 wave); the handle's [stage, max_b, 1, 1, T,
        ..] cache rows tile to every (slot, row) at `generate(prefix=)`.
        Exactness matches the host pipeline's prefix contract (fp
        caches; suffix spans attend prefix K/V exactly as a monolithic
        prefill would)."""
        ids = jnp.asarray(prefix_ids, jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if ids.ndim != 2 or ids.shape[0] != 1:
            raise ValueError("a shared prefix is one sequence [P] or "
                             f"[1, P]; got shape {ids.shape}")
        p_len = ids.shape[1]
        dec.validate_capacity(self.cfg, self.max_len, p_len, 1)
        prefill = self._prefill_prog(1, 1, p_len)
        caches = self._zero_caches(1, 1)
        rngs = jnp.stack([jax.random.PRNGKey(0)])
        caches, _token1, _ = prefill(self.params, ids[None], caches, rngs)
        return {"caches": caches, "len": p_len, "sig": self._prefix_sig()}

    def _span_fn(self, r_slots, batch, span_k, emit, temperature=0.0,
                 top_k=0):
        key = ("span", emit, r_slots, batch, span_k, float(temperature),
               int(top_k))
        if key not in self._programs:
            self._programs[key] = self._build_span(
                r_slots, batch, span_k, emit, float(temperature),
                int(top_k))
        return self._programs[key]

    def generate(self, ids, new_tokens: int, temperature: float = 0.0,
                 top_k: int = 0, seeds=None, prefix: Optional[Dict] = None):
        """Decode R = n_stages concurrent prompts [R, B, S_p] ->
        [R, B, S_p + new_tokens].

        `temperature=0` is greedy; otherwise each slot samples with its
        own rng chain seeded from `seeds[r]` (default: slot index), split
        once per picked token — request r's token stream is identical to
        `DecodePipeline.generate(ids[r], ..., seed=seeds[r])`.

        `prefix` (from `precompute_prefix`) seeds every slot's cache
        with a shared prompt prefix; `ids` is then each slot's SUFFIX
        [R, B, S_s], its prompt pass runs as ONE span wave at the prefix
        offset, and the returned array omits the prefix — the host
        pipeline's prefix contract, through the wave programs."""
        ids = jnp.asarray(ids, jnp.int32)
        if ids.ndim != 3 or ids.shape[0] != self.n_stages:
            raise ValueError(f"ids must be [R={self.n_stages} slots, B, "
                             f"S_p], got {ids.shape}")
        r_slots, batch, prompt_len = ids.shape
        if new_tokens < 1:
            raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
        base = 0
        if prefix is not None:
            self.check_prefix(prefix)
            if prompt_len == 0:
                raise ValueError(
                    "prefix reuse needs a non-empty suffix (the span "
                    "produces the first token's logits)")
            base = prefix["len"]
        dec.validate_capacity(self.cfg, self.max_len, base + prompt_len,
                              new_tokens)
        if seeds is None:
            seeds = range(r_slots)
        seeds = list(seeds)
        if len(seeds) != r_slots:
            raise ValueError(f"seeds must have {r_slots} entries, got "
                             f"{len(seeds)}")
        rngs = jnp.stack([jax.random.PRNGKey(s) for s in seeds])
        if prefix is None:
            prefill = self._prefill_prog(r_slots, batch, prompt_len,
                                         temperature, top_k)
            caches = self._zero_caches(r_slots, batch)
            caches, token1, rngs = prefill(self.params, ids, caches, rngs)
        else:
            # suffix prompt pass: ONE span wave at the prefix offset
            caches = self._broadcast_prefix_caches(prefix, r_slots, batch)
            span = self._span_fn(r_slots, batch, prompt_len, "pick_last",
                                 temperature, top_k)
            caches, token1, rngs = span(self.params, ids, caches,
                                        jnp.asarray(base, jnp.int32),
                                        rngs)
        if new_tokens == 1:
            outputs = token1[:, None]                     # [R, 1, B]
        else:
            decode_fn = self._decode_prog(r_slots, batch,
                                          base + prompt_len, new_tokens,
                                          temperature, top_k)
            outputs = decode_fn(self.params, token1, caches, rngs)
        return jnp.concatenate(
            [ids, jnp.transpose(outputs, (0, 2, 1))], axis=2)


class SpmdSpeculativeDecoder:
    """Speculative decoding whose VERIFY runs through the wave pipeline.

    The host `SpeculativeDecoder` verifies one request's span per target
    dispatch; here ONE span-wave program (`_build_span('argmax_all')`)
    verifies ALL R slots' (gamma+1)-token spans in a single compiled
    program per round — every stage verifies a different slot per tick,
    the wave decoder's utilization argument applied to verification.
    The draft is any host-driven `DecodePipeline` over the same
    vocabulary; its R x B rows flatten into one batch, so each draft
    step is ONE dispatch for the whole fleet.

    Greedy-exact per slot: a round accepts the MINIMUM matching prefix
    across ALL slots and rows — the host decoder's batch-safe rule
    extended to the slot axis, which keeps every slot at the SAME cache
    position (the wave's position arithmetic stays pure tick math; no
    per-slot divergence state). Slots that matched deeper re-derive
    those tokens next round; greedy determinism makes the output
    token-identical to `SpmdDecodePipeline.generate(ids, n)` (and hence
    to per-slot host `DecodePipeline.generate`) — tests/
    test_spmd_decode.py. The trade is lower effective acceptance as
    R grows, in exchange for verify spans that ride ICI with zero
    host round trips inside the wave.
    """

    def __init__(self, target: SpmdDecodePipeline, draft, gamma: int = 4):
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                "draft and target must share a vocabulary: "
                f"{draft.cfg.vocab_size} vs {target.cfg.vocab_size}")
        self.target = target
        self.draft = draft
        self.gamma = gamma
        self.last_acceptance_rate: Optional[float] = None

    def generate(self, ids, new_tokens: int):
        """Greedy-decode all R slots: [R, B, S_p] -> [R, B, S_p + N],
        token-identical to the wave pipeline's own greedy generate."""
        ids = jnp.asarray(ids, jnp.int32)
        tgt = self.target
        if ids.ndim != 3 or ids.shape[0] != tgt.n_stages:
            raise ValueError(f"ids must be [R={tgt.n_stages} slots, B, "
                             f"S_p], got {ids.shape}")
        r_slots, batch, prompt_len = ids.shape
        if new_tokens < 1:
            raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
        g = self.gamma
        dec.validate_capacity(tgt.cfg, tgt.max_len, prompt_len,
                              new_tokens + g)
        dec.validate_capacity(self.draft.cfg, self.draft.max_len,
                              prompt_len, new_tokens + g)

        # target wave prefill: caches + each slot's first greedy token
        # (the shared program cache — one compile for every generation
        # length and for plain generate too)
        prefill = tgt._prefill_prog(r_slots, batch, prompt_len)
        rngs = jnp.stack([jax.random.PRNGKey(s) for s in range(r_slots)])
        t_caches = tgt._zero_caches(r_slots, batch)
        t_caches, token1, _ = prefill(tgt.params, ids, t_caches, rngs)
        verify = tgt._span_fn(r_slots, batch, g + 1, "argmax_all")

        # draft prefill: slots flatten into the batch axis (one dispatch
        # drafts for the whole fleet)
        flat = ids.reshape(r_slots * batch, prompt_len)
        _, d_caches = self.draft._prefill(flat)

        pending = np.asarray(token1, np.int32)          # [R, B]
        known = [pending]     # committed continuation tokens, [R, B] each
        n_emitted = 1
        t_pos = prompt_len
        d_pos = prompt_len
        proposed = accepted = 0

        while n_emitted < new_tokens:
            # draft catch-up (committed tokens it hasn't seen) + gamma
            # proposals, host-driven on the flattened fleet batch
            catch = np.stack([k.reshape(-1) for k in
                              known[d_pos - prompt_len:]], axis=1)
            d_logits, d_caches = self.draft.extend(
                jnp.asarray(catch), d_caches, d_pos)
            d_pos += catch.shape[1]
            props = [np.asarray(jnp.argmax(
                d_logits[:, -1].astype(jnp.float32), -1), np.int32)]
            for _ in range(g - 1):
                d_logits, d_caches = self.draft.extend(
                    jnp.asarray(props[-1][:, None]), d_caches, d_pos)
                props.append(np.asarray(jnp.argmax(
                    d_logits[:, -1].astype(jnp.float32), -1), np.int32))
                d_pos += 1

            # ONE span wave verifies every slot's pending + proposals
            spans = np.concatenate(
                [pending.reshape(r_slots, batch, 1)]
                + [p.reshape(r_slots, batch, 1) for p in props], axis=2)
            t_caches, targets, _ = verify(
                tgt.params, jnp.asarray(spans), t_caches,
                jnp.asarray(t_pos, jnp.int32), rngs)
            targets = np.asarray(targets, np.int32)     # [R, g+1, B]

            # accept the minimum matching prefix across ALL slots + rows
            a = 0
            while a < g and bool(np.all(
                    props[a].reshape(r_slots, batch) == targets[:, a])):
                a += 1
            proposed += g
            accepted += a
            known.extend([props[k].reshape(r_slots, batch)
                          for k in range(a)] + [targets[:, a]])
            n_emitted += a + 1
            pending = targets[:, a]
            t_pos += a + 1
            d_pos = t_pos - 1 if a == g else t_pos

        self.last_acceptance_rate = accepted / proposed if proposed \
            else None
        gen = jnp.asarray(np.stack(known[:new_tokens], axis=2))
        return jnp.concatenate([ids, gen], axis=2)
