"""Pipeline-parallel execution: host-driven and SPMD (shard_map + ppermute) drivers."""
