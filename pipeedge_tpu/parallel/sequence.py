"""Sequence/context parallelism: ring attention and Ulysses-style all-to-all.

NEW capability beyond the reference: PipeEdge only ever splits the layer axis
and tops out at 512 tokens (SURVEY.md §5.7 — no ring/blockwise/Ulysses
anywhere). For long contexts the sequence axis must shard across chips; this
module provides both standard formulations, built on XLA collectives over a
`shard_map` mesh axis so the communication rides ICI:

- `ring_attention`: each chip holds a query/key/value sequence chunk; K/V
  chunks rotate around the ring via `lax.ppermute` while a streaming
  (log-sum-exp) softmax accumulates partial attention — memory per chip is
  O(S/n * S/n) for scores, O(S/n) for state, so sequence length scales
  linearly with chip count. Compute of block t overlaps the transfer of
  block t+1 (XLA schedules the ppermute asynchronously). With a sliding
  window the ring stops early: K/V blocks wholly behind the window are
  never rotated in, so a 4k-window/128k-prompt prefill does ~window/S of
  the full-causal work.
- `ulysses_attention`: all-to-all swaps sequence sharding for head sharding,
  runs blockwise local attention per head group (streaming softmax over
  S/n-sized key blocks — no [S, S] score materialization), and swaps back.
  Cheaper collectives when heads >= chips; per-chip score memory matches
  ring's O(H * (S/n)^2).

Both are exact (match full attention to float tolerance) and support causal
masking with global position offsets, plus Mistral-style sliding windows
(position q attends to k in (q - window, q], models/llama.py::_window_keep
semantics).
"""
from __future__ import annotations

import logging
import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import jax_compat
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

_WARNED_GQA_FALLBACK = set()


def _block_attention(q, k, v, m_prev, l_prev, acc_prev, q_offset, k_offset,
                     causal: bool, scale: float,
                     window: Optional[int] = None):
    """One streaming-softmax block update.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D] — or [B, Sk, KV, D] with KV < H
    (GQA): the kv heads repeat LOCALLY here, so ring_attention's
    ppermutes carry only the unrepeated rows (H/KV times fewer
    inter-chip bytes). Running (max, sum, acc) over the key axis;
    scores/stats in float32 regardless of input dtype. `window` bounds
    how far back a query attends: k in (q - window, q].
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal or window is not None:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)
        k_pos = k_offset + jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        if window is not None:
            mask &= k_pos[None, :] > q_pos[:, None] - window
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)                       # [B, H, Sq]
    m_new = jnp.maximum(m_prev, m_blk)
    # guard fully-masked rows (m == -inf) against NaN from exp(-inf - -inf)
    safe_m = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(jnp.where(jnp.isinf(scores), -jnp.inf, scores) -
                safe_m[..., None])
    corr = jnp.exp(jnp.where(jnp.isinf(m_prev), -jnp.inf, m_prev) - safe_m)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc_new = acc_prev * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _finish_softmax(acc, l, out_dtype):
    """Normalize the streaming accumulator; fully-masked rows output 0."""
    l = jnp.where(l == 0, 1.0, l)
    return (acc / l.transpose(0, 2, 1)[..., None]).astype(out_dtype)


def _check_window(causal: bool, window: Optional[int]) -> None:
    if window is not None:
        if not causal:
            raise ValueError("sliding window requires causal attention")
        if window < 1:
            raise ValueError(f"sliding window must be >= 1, got {window}")


def _ring_steps(n: int, chunk: int, window: Optional[int]) -> int:
    """How many ring rotations a windowed causal attention needs.

    Ring step t delivers the K/V block t hops behind the local queries;
    its nearest key is (t-1)*chunk + 1 positions before the first query,
    so any step with that distance > window - 1 is wholly outside every
    query's (q - window, q] range and is skipped — neither computed nor
    rotated in (the sliding-window point: a 4k-window prefill over a
    128k prompt does ~window/S of the full-causal ring work).
    """
    if window is None:
        return n
    return min(n, (window - 2) // chunk + 2)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False,
                   window: Optional[int] = None) -> jax.Array:
    """Exact attention over a ring-sharded sequence axis.

    Call inside `shard_map` with q/k/v local chunks [B, S/n, H, D] sharded on
    the sequence axis `axis_name`. Returns the local output chunk.

    `window` (static int) applies the sliding-window mask AND shortens the
    ring: only the first ceil-enough steps whose K/V block can intersect
    some query's (q - window, q] range run at all; blocks wholly outside
    every window are skipped — never computed, never rotated in.
    """
    _check_window(causal, window)
    n = jax_compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    chunk = k.shape[1]
    # GQA: k/v may carry fewer heads than q — they rotate unrepeated
    # (repeat happens inside the block update), so the ring traffic is
    # sized by the kv heads, preserving GQA's bandwidth advantage
    perm = [(i, (i + 1) % n) for i in range(n)]

    n_steps = _ring_steps(n, chunk, window)

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, sq, h, d), jnp.float32)
    q_offset = idx * sq

    def attend(t, m, l, acc, k_cur, v_cur):
        # K/V block t originated on ring neighbor (idx - t) mod n
        k_offset = ((idx - t) % n) * chunk
        return _block_attention(q, k_cur, v_cur, m, l, acc, q_offset,
                                k_offset, causal, scale, window)

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = attend(t, m, l, acc, k_cur, v_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt

    # the last block update runs OUTSIDE the loop so the ring does exactly
    # n_steps - 1 rotations: the step after the final attend would only
    # rotate in the first skipped (or already-consumed) block
    m, l, acc, k_last, v_last = jax.lax.fori_loop(
        0, n_steps - 1, step, (m0, l0, acc0, k, v))
    m, l, acc = attend(n_steps - 1, m, l, acc, k_last, v_last)
    return _finish_softmax(acc, l, q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False,
                      window: Optional[int] = None) -> jax.Array:
    """Exact attention via all-to-all head<->sequence resharding.

    Inside `shard_map`: inputs are sequence-sharded [B, S/n, H, D]; an
    all-to-all regroups to head-sharded [B, S, H/n, D], blockwise local
    attention runs per head group (streaming softmax over S/n-sized key
    blocks, so peak score memory is O((H/n) * S * S/n) — the same
    H*(S/n)^2 per chip as ring, NOT the full [S, S]), and the inverse
    all-to-all restores sequence sharding. Requires H % n == 0.

    Unlike ring, a sliding `window` cannot skip key blocks here: every
    chip holds ALL query positions after the first all-to-all, so every
    key block intersects someone's window — the window is mask-only.
    """
    _check_window(causal, window)
    n = jax_compat.axis_size(axis_name)
    b, s_local, h, d = q.shape
    assert h % n == 0, "ulysses requires head count divisible by axis size"
    scale = 1.0 / (d ** 0.5)

    def to_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):    # [B, S, H/n, D] -> [B, S/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    kv = k.shape[2]
    if kv != h and kv % n:
        # GQA group count not divisible by the axis: pre-repeat K/V to
        # lcm(kv, n) — the SMALLEST head count the all-to-all can split
        # evenly (kv and n both divide h, so their lcm does too). The
        # remaining h/lcm repeat still happens locally per block, so only
        # lcm/kv x of GQA's bandwidth advantage is forfeited (the old
        # fallback repeated all the way to h).
        target = math.lcm(kv, n)
        if (kv, n) not in _WARNED_GQA_FALLBACK:
            _WARNED_GQA_FALLBACK.add((kv, n))
            logger.warning(
                "ulysses GQA fallback: kv_heads=%d not divisible by sp=%d; "
                "K/V pre-repeat to lcm=%d heads, so the all-to-all moves "
                "%dx the GQA-ideal K/V bytes. Use an sp degree dividing "
                "kv_heads to keep the full advantage.",
                kv, n, target, target // kv)
        k = jnp.repeat(k, target // kv, axis=2)
        v = jnp.repeat(v, target // kv, axis=2)
    # kv heads ride the all-to-all unrepeated (kv/n per chip); the block
    # update repeats them locally per key block
    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)

    s_total = s_local * n
    hq, kvh = h // n, kh.shape[2]
    m0 = jnp.full((b, hq, s_total), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, hq, s_total), jnp.float32)
    acc0 = jnp.zeros((b, s_total, hq, d), jnp.float32)
    # key blocks of the local chunk size: [n, B, S/n, KV/n, D]
    kb = jnp.moveaxis(kh.reshape(b, n, s_local, kvh, d), 1, 0)
    vb = jnp.moveaxis(vh.reshape(b, n, s_local, kvh, d), 1, 0)
    offsets = jnp.arange(n) * s_local

    def blk(carry, xs):
        m, l, acc = carry
        k_blk, v_blk, k_off = xs
        m, l, acc = _block_attention(qh, k_blk, v_blk, m, l, acc, 0, k_off,
                                     causal, scale, window)
        return (m, l, acc), None

    (m, l, acc), _ = jax.lax.scan(blk, (m0, l0, acc0), (kb, vb, offsets))
    return to_seq(_finish_softmax(acc, l, q.dtype))


def resolve_sp_core(sp_kind: str, num_heads: Optional[int] = None,
                    n: Optional[int] = None,
                    window: Optional[int] = None):
    """THE dispatch point for the sequence-parallel attention core (shared
    by the SPMD pipeline, the decode prefill, and the standalone wrapper):
    'ring' streams K/V chunks via ppermute with a blockwise softmax
    (O((S/n)^2) score memory AND window-skipped ring steps — the
    long-context choice); 'ulysses' all-to-all reshards heads<->sequence
    with blockwise local attention (same per-chip score memory, cheaper
    collectives when heads >= chips). Validates the Ulysses
    head-divisibility requirement when `num_heads`/`n` are supplied
    (ulysses_attention also asserts it at trace time). A `window` binds
    the Mistral-style sliding-window mask into the returned core; callers
    keep the plain `core(q, k, v, axis, causal=True)` signature."""
    if sp_kind == "ring":
        core = ring_attention
    elif sp_kind == "ulysses":
        if num_heads is not None and n and num_heads % n:
            raise ValueError(f"ulysses sp={n} requires head count "
                             f"({num_heads}) divisible by sp")
        core = ulysses_attention
    else:
        raise ValueError(f"unknown sp_kind {sp_kind!r} (ring | ulysses)")
    if window is not None:
        core = partial(core, window=int(window))
    return core


def make_sequence_parallel_attention(mesh: Mesh, axis_name: str = "sp",
                                     kind: str = "ring",
                                     causal: bool = False,
                                     window: Optional[int] = None):
    """Build a jitted `fn(q, k, v) -> out` over globally-shaped [B, S, H, D]
    arrays with the sequence axis sharded over `axis_name`."""
    inner = resolve_sp_core(kind, window=window)
    spec = P(None, axis_name)

    @jax.jit
    def fn(q, k, v):
        return jax_compat.shard_map(
            partial(inner, axis_name=axis_name, causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return lambda q, k, v: fn(place(q), place(k), place(v))
