"""Sequence/context parallelism: ring attention and Ulysses-style all-to-all.

NEW capability beyond the reference: PipeEdge only ever splits the layer axis
and tops out at 512 tokens (SURVEY.md §5.7 — no ring/blockwise/Ulysses
anywhere). For long contexts the sequence axis must shard across chips; this
module provides both standard formulations, built on XLA collectives over a
`shard_map` mesh axis so the communication rides ICI:

- `ring_attention`: each chip holds a query/key/value sequence chunk; K/V
  chunks rotate around the ring via `lax.ppermute` while a streaming
  (log-sum-exp) softmax accumulates partial attention — memory per chip is
  O(S/n * S/n) for scores, O(S/n) for state, so sequence length scales
  linearly with chip count. Compute of block t overlaps the transfer of
  block t+1 (XLA schedules the ppermute asynchronously).
- `ulysses_attention`: all-to-all swaps sequence sharding for head sharding,
  runs exact local attention per head group, and swaps back. Cheaper when
  heads >= chips; two all-to-alls instead of n-1 permutes.

Both are exact (match full attention to float tolerance) and support causal
masking with global position offsets.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_attention(q, k, v, m_prev, l_prev, acc_prev, q_offset, k_offset,
                     causal: bool, scale: float):
    """One streaming-softmax block update.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D] — or [B, Sk, KV, D] with KV < H
    (GQA): the kv heads repeat LOCALLY here, so ring_attention's
    ppermutes carry only the unrepeated rows (H/KV times fewer
    inter-chip bytes). Running (max, sum, acc) over the key axis;
    scores/stats in float32 regardless of input dtype.
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        q_pos = q_offset + jnp.arange(sq)
        k_pos = k_offset + jnp.arange(sk)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    m_blk = jnp.max(scores, axis=-1)                       # [B, H, Sq]
    m_new = jnp.maximum(m_prev, m_blk)
    # guard fully-masked rows (m == -inf) against NaN from exp(-inf - -inf)
    safe_m = jnp.where(jnp.isinf(m_new), 0.0, m_new)
    p = jnp.exp(jnp.where(jnp.isinf(scores), -jnp.inf, scores) -
                safe_m[..., None])
    corr = jnp.exp(jnp.where(jnp.isinf(m_prev), -jnp.inf, m_prev) - safe_m)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    acc_new = acc_prev * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False) -> jax.Array:
    """Exact attention over a ring-sharded sequence axis.

    Call inside `shard_map` with q/k/v local chunks [B, S/n, H, D] sharded on
    the sequence axis `axis_name`. Returns the local output chunk.
    """
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    chunk = k.shape[1]
    # GQA: k/v may carry fewer heads than q — they rotate unrepeated
    # (repeat happens inside the block update), so the ring traffic is
    # sized by the kv heads, preserving GQA's bandwidth advantage
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, sq, h, d), jnp.float32)
    q_offset = idx * sq

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        # K/V block t originated on ring neighbor (idx - t) mod n
        k_offset = ((idx - t) % n) * chunk
        m, l, acc = _block_attention(q, k_cur, v_cur, m, l, acc, q_offset,
                                     k_offset, causal, scale)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    l = jnp.where(l == 0, 1.0, l)  # fully-masked rows output zeros
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False) -> jax.Array:
    """Exact attention via all-to-all head<->sequence resharding.

    Inside `shard_map`: inputs are sequence-sharded [B, S/n, H, D]; an
    all-to-all regroups to head-sharded [B, S, H/n, D], local full attention
    runs per head group, and the inverse all-to-all restores sequence
    sharding. Requires H % n == 0.
    """
    n = jax.lax.axis_size(axis_name)
    b, s_local, h, d = q.shape
    assert h % n == 0, "ulysses requires head count divisible by axis size"
    scale = 1.0 / (d ** 0.5)

    def to_heads(x):  # [B, S/n, H, D] -> [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):    # [B, S, H/n, D] -> [B, S/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    kv = k.shape[2]
    if kv != h and kv % n:
        # GQA group count not divisible by the axis: pre-repeat to the
        # full head count (correct for any kv since h % n == 0 holds) —
        # the all-to-all then moves full-head bytes, like the pre-GQA
        # behavior. The bandwidth-saving path below needs kv % n == 0.
        k = jnp.repeat(k, h // kv, axis=2)
        v = jnp.repeat(v, h // kv, axis=2)
    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    if k.shape[2] != h:               # GQA: repeat AFTER the all-to-all
        kh = jnp.repeat(kh, h // k.shape[2], axis=2)
        vh = jnp.repeat(vh, h // k.shape[2], axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        s_total = s_local * n
        pos = jnp.arange(s_total)
        mask = pos[:, None] >= pos[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(vh.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, vh,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return to_seq(ctx)


def resolve_sp_core(sp_kind: str, num_heads: Optional[int] = None,
                    n: Optional[int] = None):
    """THE dispatch point for the sequence-parallel attention core (shared
    by the SPMD pipeline, the decode prefill, and the standalone wrapper):
    'ring' streams K/V chunks via ppermute with a blockwise softmax
    (O((S/n)^2) score memory — the long-context choice); 'ulysses'
    all-to-all reshards heads<->sequence and materializes full [S, S]
    scores per local head group (cheaper collectives, but score memory
    grows quadratically with S). Validates the Ulysses head-divisibility
    requirement when `num_heads`/`n` are supplied (ulysses_attention also
    asserts it at trace time)."""
    if sp_kind == "ring":
        return ring_attention
    if sp_kind == "ulysses":
        if num_heads is not None and n and num_heads % n:
            raise ValueError(f"ulysses sp={n} requires head count "
                             f"({num_heads}) divisible by sp")
        return ulysses_attention
    raise ValueError(f"unknown sp_kind {sp_kind!r} (ring | ulysses)")


def make_sequence_parallel_attention(mesh: Mesh, axis_name: str = "sp",
                                     kind: str = "ring",
                                     causal: bool = False):
    """Build a jitted `fn(q, k, v) -> out` over globally-shaped [B, S, H, D]
    arrays with the sequence axis sharded over `axis_name`."""
    inner = resolve_sp_core(kind)
    spec = P(None, axis_name)

    @jax.jit
    def fn(q, k, v):
        return jax.shard_map(
            partial(inner, axis_name=axis_name, causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return lambda q, k, v: fn(place(q), place(k), place(v))
