"""Host-driven pipeline driver: per-stage jitted programs, device_put edges.

This is the TPU equivalent of the reference's P2P pipeline
(/root/reference/src/pipeedge/comm/p2p/__init__.py:334-450): one "stage" per
device, microbatches streamed through the stages, results collected in FIFO
order. The reference needs four threads per rank (recv/work/send/command) and
a hand-rolled wire protocol because stages are separate Python processes
exchanging dynamically-shaped CPU tensors over TCP; under a single-controller
JAX program none of that machinery exists:

- A stage is a jit-compiled pure function resident on one device; its
  input/output signatures (shape/dtype/arity) are static per (model,
  partition, microbatch-size), so there is no framing protocol — the
  "wire format" is the compiled program signature (SURVEY.md §5.8).
- Dispatch is asynchronous: the host enqueues stage s for microbatch i and
  the transfer to stage s+1 without blocking, so while stage s computes
  microbatch i, stage s-1 computes microbatch i+1 — the same fill/drain
  overlap the reference builds with threads and maxsize-1 queues
  (p2p:88-93), but scheduled by the XLA runtime instead of Python locks.
- Backpressure (the reference's ConditionQueue semantics) is a bounded
  in-flight window: the host blocks on the oldest outstanding result once
  `max_inflight` microbatches are unfinished.

Quantized edges: each stage optionally decodes its input and encodes its
output (QuantPipe, reference runtime.py:73-119) *inside* the stage's jit, so
the pack/unpack fuses with the stage's first/last matmuls, and only the packed
uint32 payload crosses devices. Per-bitwidth compiled variants are cached —
bitwidth is compile-static (SURVEY.md §7 "hard parts").
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from .. import telemetry
from ..ops import clamp as clamp_ops
from ..ops import fused_quant
from ..ops import quant as quant_ops
from ..utils import tracing

logger = logging.getLogger(__name__)

# Payload tuples use this transform on quantized edges. The reference clamps
# post-GeLU tensors with the gelu variant when the edge carries an MLP-up
# output (runtime.py:73-90); the hidden-state tensor uses the laplace variant.


def _encode_payload(payload, bit: int, clamp: bool):
    """Quantize every tensor in a stage-output payload (1- or 2-tuple)."""
    if bit == 0:
        return payload
    single = not isinstance(payload, tuple)
    tensors = (payload,) if single else payload
    out = []
    for t in tensors:
        if clamp:
            t = clamp_ops.clamp_banner2019_laplace(t, bit)
        # fused Pallas epilogue when enabled (ops/fused_quant.py): the
        # encode rides the stage's last matmul inside this same jit
        out.append(fused_quant.encode_outerdim(t, bit))
    return out[0] if single else tuple(out)


def _decode_payload(payload):
    """Dequantize a payload produced by `_encode_payload` (no-op otherwise);
    the fused-dequant consumer prologue when enabled."""
    if isinstance(payload, quant_ops.QuantizedTensor):
        return fused_quant.decode_outerdim(payload)
    if isinstance(payload, tuple) and any(
            isinstance(t, quant_ops.QuantizedTensor) for t in payload):
        return tuple(fused_quant.decode_outerdim(t) for t in payload)
    return payload


def _tunnel_decode_payload(payload):
    """Tunnel variant of `_decode_payload`: the payload's LEADING tensor
    stays an 8-bit `QuantizedTensor` — the stage's first sublayer leads
    with a dense that consumes the wire bytes directly in the int8 matmul
    (ops/int8_matmul.wire_dense), so the activation crosses the pipeline
    seam MXU-to-MXU without a dequant round-trip. Trailing tensors (the
    residual skip) decode normally; non-8-bit payloads fall back."""
    if isinstance(payload, quant_ops.QuantizedTensor):
        return payload if payload.bit == 8 else _decode_payload(payload)
    if isinstance(payload, tuple) and payload and isinstance(
            payload[0], quant_ops.QuantizedTensor) and payload[0].bit == 8:
        return (payload[0],) + tuple(
            _decode_payload(t) for t in payload[1:])
    return _decode_payload(payload)


@dataclasses.dataclass
class PipelineStage:
    """One pipeline stage: a shard function bound to a device.

    `quant_bit` applies to this stage's *output* edge (the reference registers
    the encode hook on the producing module, runtime.py:464-482). It may be
    changed between microbatches; each bitwidth compiles once and is cached.
    """
    shard_fn: Callable[[Dict, Any], Any]
    params: Dict
    device: jax.Device
    quant_bit: int = 0
    clamp: bool = True
    name: str = ""
    # Donate the (device_put-copied) payload buffers to XLA: the output
    # reuses the input's allocation instead of growing the arena each
    # microbatch. Only safe when the caller does not reuse the payload it
    # passes in — true for interior pipeline edges (each stage's input is
    # the previous stage's otherwise-unreferenced output), NOT for the
    # head stage, whose input is caller-owned (e.g. replayed across
    # --measure-rounds). build_pipeline sets it for stages > 0.
    donate_payload: bool = False
    # int8 stage-seam tunnel: leave the input payload's leading 8-bit
    # wire tensor ENCODED so this stage's first matmul eats it directly
    # (only set when the stage's first sublayer is wire-consuming —
    # FamilySpec.wire_subs — and the producing edge runs at 8 bits)
    tunnel: bool = False

    def __post_init__(self):
        self.params = jax.device_put(self.params, self.device)
        self._compiled: Dict[int, Callable] = {}

    def _fn_for_bit(self, bit: int) -> Callable:
        fn = self._compiled.get(bit)
        if fn is None:
            shard_fn, do_clamp = self.shard_fn, self.clamp
            decode = _tunnel_decode_payload if self.tunnel \
                else _decode_payload

            def step(params, payload):
                data = decode(payload)
                out = shard_fn(params, data)
                return _encode_payload(out, bit, do_clamp)

            fn = jax.jit(step, donate_argnums=(
                (1,) if self.donate_payload else ()))
            self._compiled[bit] = fn
        return fn

    def __call__(self, payload):
        # tiered edge transfer (docs/DCN_WIRE.md): a payload already
        # resident on this stage's device (the single-device pipeline, or
        # consecutive stages sharing a chip) skips the device_put dispatch
        # entirely — the host-hop-free degenerate of the DCN colocated
        # hand-off; cross-device payloads ride device-to-device DMA/ICI.
        if not _payload_on_device(payload, self.device):
            with telemetry.span("wire", f"edge->{self.name or 'stage'}"):
                payload = jax.device_put(payload, self.device)
        return self._fn_for_bit(self.quant_bit)(self.params, payload)


class HostPipeline:
    """Drive microbatches through a chain of `PipelineStage`s.

    FIFO ordering is guaranteed (single dispatch thread + in-order device
    queues), which the reference could only promise for its P2P transport
    (rpc:44, runtime.py:250-254).
    """

    def __init__(self, stages: Sequence[PipelineStage], max_inflight: int = 0,
                 ubatch_callback: Optional[Callable[[int, Any], None]] = None,
                 edge_bytes_callback: Optional[
                     Callable[[int, List[int]], None]] = None):
        if not stages:
            raise ValueError("pipeline needs at least one stage")
        self.stages = list(stages)
        # Default window: 2 microbatches per stage (double buffering), the
        # analog of the reference's buffers_in=2/buffers_out=2 (sched model).
        self.max_inflight = max_inflight or 2 * len(self.stages)
        self.ubatch_callback = ubatch_callback
        # called at each microbatch's retirement with the per-edge wire byte
        # counts [stage0->1, stage1->2, ...] of that microbatch — the
        # single-controller analogue of the reference's per-rank send
        # monitoring hooks (p2p:132-152, runtime.py:219-230)
        self.edge_bytes_callback = edge_bytes_callback

    def enqueue(self, ubatch, edge_bytes: Optional[List[int]] = None,
                mb: Optional[int] = None,
                trace: Optional[telemetry.TraceContext] = None):
        """Dispatch one microbatch through all stages; returns the (device-
        resident, not yet materialized) final payload. When `edge_bytes` is a
        list, it receives the wire byte count of each inter-stage edge.
        `mb` tags the telemetry spans with the microbatch id (flow events
        on the merged trace); `trace` additionally tags them with the
        request id this microbatch serves (trace_report --request)."""
        data = ubatch
        last = len(self.stages) - 1
        rid = trace.rid if trace is not None else None
        for i, stage in enumerate(self.stages):
            # named profiler region: stage dispatch shows up on the trace
            # timeline (see utils/tracing.py; no-op cost when not tracing).
            # The telemetry span measures HOST dispatch time (device work
            # is async); the retire span is where device time surfaces.
            with tracing.annotate(stage.name or f"stage{i}"), \
                    telemetry.span("stage", stage.name or f"stage{i}",
                                   stage=i, mb=mb, rid=rid):
                data = stage(data)
            if edge_bytes is not None and i < last:
                edge_bytes.append(payload_wire_bytes(data))
        return _undequantized_guard(data)

    def run(self, ubatches: Sequence[Any],
            traces: Optional[Sequence[telemetry.TraceContext]] = None
            ) -> Tuple[List[Any], Dict[str, float]]:
        """Stream all microbatches; returns (results, stats). `traces`
        (optional, one per microbatch) request-tags each microbatch's
        dispatch/retire spans.

        Stats mirror the reference's end-of-run measurement: latency =
        t(last result) - t(first enqueue); throughput = total items / latency
        (reference runtime.py:493-505). `steady_state_throughput_items_sec`
        additionally excludes the FIRST microbatch — its latency carries
        the XLA compiles, and decisions fed by these stats (adaptive
        microbatching, benches) must not chase JIT noise.

        Retirement is opportunistic: after each dispatch, any already-
        finished microbatches at the head of the window retire without
        blocking, so a full window (or a slow result callback) only stalls
        dispatch when the oldest result genuinely isn't ready yet — not on
        every oldest microbatch's full host readback.
        """
        ubatches = list(ubatches)  # single pass: generators welcome
        results: List[Any] = []
        inflight: List[Any] = []
        # (items, t_retired) per microbatch, stamped as each result becomes
        # host-visible — the steady-state measurement's raw series
        retired: List[Tuple[int, float]] = []
        # per-mb end-to-end latency (enqueue -> host-visible result): the
        # fill/steady breakdown's raw series
        mb_latency_s: List[float] = []
        track_edges = self.edge_bytes_callback is not None
        tik = time.monotonic()
        dispatch_s: List[float] = []  # per-mb host enqueue cost (t_fixed)
        for i, ubatch in enumerate(ubatches):
            edge_bytes: Optional[List[int]] = [] if track_edges else None
            trace = traces[i] if traces is not None and i < len(traces) \
                else None
            t_d0 = time.monotonic()
            out = self.enqueue(ubatch, edge_bytes, mb=i, trace=trace)
            dispatch_s.append(time.monotonic() - t_d0)
            inflight.append((i, out, edge_bytes, t_d0, trace))
            while inflight and payload_ready(inflight[0][1]):
                self._retire(inflight.pop(0), results, retired, mb_latency_s)
            while len(inflight) >= self.max_inflight:
                self._retire(inflight.pop(0), results, retired, mb_latency_s)
        while inflight:
            self._retire(inflight.pop(0), results, retired, mb_latency_s)
        tok = time.monotonic()
        items = sum(_leading_dim(u) for u in ubatches)
        latency = tok - tik
        stats = {"latency_sec": latency,
                 "throughput_items_sec": items / latency if latency > 0 else 0.0,
                 "microbatches": len(ubatches),
                 # first dispatch carries the XLA compiles: average the rest
                 # when there is a rest (the planner's fixed-cost input)
                 "host_dispatch_s_per_ubatch":
                     (sum(dispatch_s[1:]) / (len(dispatch_s) - 1))
                     if len(dispatch_s) > 1
                     else (dispatch_s[0] if dispatch_s else 0.0)}
        if len(retired) >= 2:
            # window: first retirement -> last retirement, so the first
            # (compile-tainted) microbatch's latency is excluded while the
            # remaining M-1 retirements still measure the warm cadence
            steady_s = retired[-1][1] - retired[0][1]
            steady_items = sum(n for n, _ in retired[1:])
            if steady_s > 0:
                stats["steady_state_throughput_items_sec"] = \
                    steady_items / steady_s
                stats["steady_mb_interval_s"] = steady_s / (len(retired) - 1)
        if mb_latency_s:
            # fill vs steady split (BENCH latency-gap tracking, ROADMAP
            # item 5): the first microbatch's latency carries compile +
            # pipeline fill; the steady percentiles are what an SLO sees
            from pipeedge_tpu.telemetry.report import _percentile
            steady = sorted(mb_latency_s[1:]) or [mb_latency_s[0]]
            stats["latency_breakdown"] = {
                "fill_ms": round(mb_latency_s[0] * 1e3, 3),
                "steady_p50_ms": round(_percentile(steady, 50) * 1e3, 3),
                "steady_p99_ms": round(_percentile(steady, 99) * 1e3, 3),
            }
        return results, stats

    def _retire(self, item, results, retired: Optional[list] = None,
                mb_latency_s: Optional[list] = None):
        i, out, edge_bytes, t_enq, trace = item
        with telemetry.span("results", "retire", mb=i,
                            rid=trace.rid if trace is not None else None):
            out = jax.block_until_ready(out)
            # opt-in NaN/Inf guard (PIPEEDGE_NAN_GUARD=1): the host
            # driver's stage hand-offs stay on-device for overlap, so the
            # boundary check lands here, where the result is already
            # fenced — a poisoned microbatch raises the named error
            # instead of reaching the result callback
            from ..health import guard as nan_guard
            if nan_guard.nan_guard_enabled():
                out = nan_guard.check_finite(
                    out, where="host_pipeline/retire", mb=i,
                    rid=trace.rid if trace is not None else None)
        now = time.monotonic()
        if retired is not None:
            retired.append((_leading_dim(out), now))
        if mb_latency_s is not None and t_enq is not None:
            mb_latency_s.append(now - t_enq)
        if self.edge_bytes_callback is not None:
            self.edge_bytes_callback(i, edge_bytes)
        if self.ubatch_callback is not None:
            self.ubatch_callback(i, out)
        results.append(out)


def _leading_dim(ubatch) -> int:
    t = ubatch[0] if isinstance(ubatch, tuple) else ubatch
    return int(t.shape[0])


def _payload_on_device(payload, device) -> bool:
    """Whether every array in a stage payload is already committed to
    `device` (single-device shardings only). Conservative False for host
    arrays and anything that cannot answer, so callers fall back to the
    explicit device_put."""
    tensors = payload if isinstance(payload, tuple) else (payload,)
    for t in tensors:
        if isinstance(t, quant_ops.QuantizedTensor):
            if not _payload_on_device((t.data, t.scale, t.shift), device):
                return False
            continue
        sharding = getattr(t, "sharding", None)
        try:
            if sharding is None or sharding.device_set != {device}:
                return False
        except Exception:  # noqa: BLE001 - deleted buffer, odd sharding
            return False
    return True


def payload_ready(payload) -> bool:
    """Whether every array in a stage payload has finished computing
    (jax.Array.is_ready — no fence, no transfer). Conservative False for
    anything that cannot answer, so callers fall back to the blocking
    retirement path rather than fencing early."""
    tensors = payload if isinstance(payload, tuple) else (payload,)
    for t in tensors:
        is_ready = getattr(t, "is_ready", None)
        try:
            if is_ready is None or not is_ready():
                return False
        except Exception:  # noqa: BLE001 - deleted/donated buffer etc.
            return False
    return True


def plan_microbatches(n_items: int, n_stages: int, t_item_s: float,
                      t_fixed_s: float,
                      max_ubatch: Optional[int] = None) -> Tuple[int, int, float]:
    """Pick the microbatch size from MEASURED timings instead of a fixed
    `--ubatch`: minimize the modeled round latency

        T(M) = (M + S - 1) * (t_fixed + t_item * ceil(B/M))

    — the classic fill/drain tradeoff. More microbatches shrink the
    pipeline bubble ((S-1)/(M+S-1) of the round), fewer amortize the
    per-microbatch fixed overhead `t_fixed_s` (host dispatch, framing);
    `t_item_s` is the bottleneck stage's measured per-ITEM time. Returns
    `(ubatch_size, n_microbatches, predicted_latency_s)`; exhaustive over
    the distinct sizes (batches are small), deterministic."""
    if n_items < 1 or n_stages < 1:
        raise ValueError(f"need n_items >= 1 and n_stages >= 1, got "
                         f"{n_items}, {n_stages}")
    t_item = max(0.0, float(t_item_s))
    t_fixed = max(0.0, float(t_fixed_s))
    best = None
    seen = set()
    for m in range(1, n_items + 1):
        u = -(-n_items // m)
        if u in seen or (max_ubatch is not None and u > max_ubatch):
            continue
        seen.add(u)
        m_eff = -(-n_items // u)
        t = (m_eff + n_stages - 1) * (t_fixed + t_item * u)
        if best is None or t < best[2]:
            best = (u, m_eff, t)
    if best is None:
        raise ValueError(f"max_ubatch={max_ubatch} admits no microbatch "
                         f"size for {n_items} items")
    return best


def payload_wire_bytes(payload) -> int:
    """Bytes a stage-output payload puts on the inter-stage edge.

    For quantized payloads this counts the packed words plus scale/shift
    metadata (everything that actually travels, `QuantizedTensor.nbytes_wire`
    + per-item scalars); raw payloads count their array bytes. Shapes are
    known without materializing, so this never fences the device."""
    tensors = payload if isinstance(payload, tuple) else (payload,)
    total = 0
    for t in tensors:
        if isinstance(t, quant_ops.QuantizedTensor):
            total += t.nbytes_wire + t.scale.nbytes + t.shift.nbytes
        else:
            total += t.nbytes
    return total


def _undequantized_guard(data):
    """Final stage output must not leave the pipeline quantized."""
    if isinstance(data, quant_ops.QuantizedTensor) or (
            isinstance(data, tuple) and any(
                isinstance(t, quant_ops.QuantizedTensor) for t in data)):
        return _decode_payload(data)
    return data


def build_pipeline(model_name: str, partition: Sequence[Tuple[int, int]],
                   model_file: Optional[str] = None,
                   devices: Optional[Sequence[jax.Device]] = None,
                   quant_bits: Optional[Sequence[int]] = None,
                   dtype=None, max_inflight: int = 0) -> HostPipeline:
    """Build a host-driven pipeline from a model partition.

    `partition` is the reference's stage-layers list [[l0, r0], [l1, r1], ...]
    (runtime.py:291-355); `quant_bits[i]` quantizes the edge leaving stage i
    (reference `-q`, runtime.py:652-656). Stages are placed round-robin on
    `devices` (default: all local devices).

    Int8 tunnel: when the active `QuantizeCompute` config has `tunnel`
    set, a stage whose first sublayer leads with a dense
    (`FamilySpec.wire_subs`) and whose incoming edge runs at 8 bits keeps
    that payload encoded — its first matmul consumes the wire bytes
    directly (ops/int8_matmul.wire_dense).
    """
    import jax.numpy as jnp

    from ..models import registry
    from ..models.layers import quantize_compute

    if devices is None:
        devices = jax.local_devices()
    if dtype is None:
        dtype = jnp.float32
    if quant_bits is None:
        quant_bits = [0] * len(partition)
    wire_subs = getattr(
        registry.get_model_entry(model_name).family.FAMILY, "wire_subs", ())
    qc = quantize_compute()
    stages = []
    for i, (layer_start, layer_end) in enumerate(partition):
        fn, params, _ = registry.module_shard_factory(
            model_name, model_file, layer_start, layer_end, stage=i, dtype=dtype)
        dev = devices[i % len(devices)]
        bit = quant_bits[i] if i < len(quant_bits) else 0
        # final stage's output edge is the result path: never quantized
        if i == len(partition) - 1:
            bit = 0
        in_bit = quant_bits[i - 1] if 0 < i <= len(quant_bits) else 0
        tunnel = (qc.tunnel and i > 0 and in_bit == 8
                  and (layer_start - 1) % 4 in wire_subs)
        stages.append(PipelineStage(shard_fn=fn, params=params, device=dev,
                                    quant_bit=bit, name=f"stage{i}",
                                    donate_payload=i > 0, tunnel=tunnel))
    return HostPipeline(stages, max_inflight=max_inflight)
