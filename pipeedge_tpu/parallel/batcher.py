"""Continuous batching for pipelined decoding: concurrent requests fill the
pipeline bubbles a single autoregressive stream leaves empty.

A single stream decodes one token per FULL pipeline traversal — with K
stages, every stage idles K-1 of every K stage-times (docs/DECODE.md).
Interleaving S concurrent requests as a wave — stage i decoding request r
while stage i+1 decodes request r-1 — keeps every stage busy once S >=
K, multiplying aggregate tokens/sec by ~min(S, K) without touching the
compiled stage programs.

TPU-first constraints drive the design:

- **Static shapes preserved**: each request keeps its OWN per-stage cache
  slots (created at admission, freed at completion), so the compiled
  prefill/decode programs are exactly DecodePipeline's — one program per
  (batch, prompt-shape) signature, shared by every request with that
  signature, and token-for-token identical to a solo `generate()` run.
  There is no cross-request padding or masking to invalidate shapes.
- **Wave scheduling, host-driven**: the scheduler advances one "tick" at a
  time; per tick each stage dispatches at most one request's stage-step.
  Stages are processed back-to-front so a request advances exactly one
  stage per tick (and a token finishing at the last stage re-enters stage
  0 within the same tick — no idle gap). JAX dispatch is asynchronous, so
  with stages placed on distinct devices the per-tick dispatches execute
  concurrently; the host never blocks inside a tick.
- **Ready-queue admission**: requests wait in a FIFO until an active slot
  frees (`max_active` bounds cache memory, default = enough to saturate
  the pipeline); arrivals and completions interleave freely mid-run —
  the "continuous" in continuous batching.
- **Iteration-level scheduling** (opt-in): `step_join=True` joins a
  pending request the moment a step boundary frees its slot (same tick,
  not next wave), and `chunk_tokens=N` splits long prompt passes into
  N-token CHUNKS interleaved with other requests' decode steps under a
  token-budget-per-step policy — a long prompt streams in at a bounded
  rate instead of monopolizing the pipeline (docs/SERVING.md).

The reference has no analogue (its runtime is single-shot batch inference;
the decode subsystem itself is already beyond-reference — docs/DECODE.md).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..telemetry import metrics as prom
from .decode import (DecodePipeline, _repeat_batch, make_token_picker,
                     validate_capacity)

# iteration-level scheduling counters (docs/OBSERVABILITY.md): one family
# per event, labelled by executor so /metrics tells the wave batcher's
# steps from the stage workers' without a second registry
M_STEPS = prom.REGISTRY.counter(
    "pipeedge_decode_steps_total",
    "decode-step boundaries crossed (one per picked token wave), "
    "by executor")
M_CHUNKS = prom.REGISTRY.counter(
    "pipeedge_prefill_chunks_total",
    "prompt chunks dispatched by the chunked-prefill scheduler, "
    "by executor")
for _ex in ("wave", "workers"):
    M_STEPS.declare(executor=_ex)
    M_CHUNKS.declare(executor=_ex)
del _ex


def _sched_mark(name: str, rid) -> None:
    """Instant `sched` span (join/retire/chunk): scheduler decisions are
    point events whose endpoints may straddle threads, so both executors
    record them pre-timed instead of opening a with-span."""
    if telemetry.enabled():
        now = time.monotonic_ns()
        telemetry.record("sched", name, now, now, rid=str(rid))


@dataclass
class _Request:
    rid: object
    ids: jnp.ndarray                 # [B, S] prompt (prompt included in
    new_tokens: int                  # the result; the SUFFIX when a
    pick: object                     # prefix handle seeds the caches)
    rng: jax.Array
    prompt_len: int                  # prefix + suffix
    prefix: Optional[Dict] = None    # precompute_prefix handle
    eos_token: Optional[int] = None  # stop early once every row emitted it
    pad_token: Optional[int] = None  # fills rows past their own eos
    # streaming hook: fires (step, [B] device tokens) as each pick lands
    on_token: Optional[object] = None
    # cooperative cancellation: an is_set()-style flag (threading.Event)
    # checked after each pick — a cancelled request completes with the
    # tokens decoded so far, freeing its cache slots/admission slot early
    # (dead streaming clients must not hold capacity, tools/serve.py)
    cancel: Optional[object] = None
    # absolute monotonic deadline (docs/SERVING.md): checked at every
    # decode-step boundary; expiry FIRES the cancel flag and completes
    # the request early — expired work must stop consuming TPU time
    # mid-flight, not decode uselessly to the cap
    deadline: Optional[float] = None
    expired: bool = False            # the deadline check tripped
    rows_done: Optional[np.ndarray] = None   # [B] eos seen per row
    caches: Optional[List] = None    # per-stage cache slots (admission)
    # paged-KV plane (pipeedge_tpu/kv): page tables + sharing state when
    # a PagedKvBackend drives this request instead of dense cache slots
    kvstate: Optional[Dict] = None
    # a prefill fleet's ship handle (kv/disagg.py): the prompt pass
    # already ran remotely; admission installs the KV rows and decoding
    # starts directly at the first decode step
    shipped: Optional[Dict] = None
    # chunked prefill (docs/SERVING.md): a long prompt pass split into
    # fixed-token chunks interleaved with other requests' decode steps.
    # One chunk is in flight at a time; `chunk_rest` holds the prompt
    # tokens not yet dispatched, `chunk_off` the in-flight chunk's
    # absolute cache offset, `chunk_next` the next chunk's offset, and
    # `chunk_final` whether the in-flight chunk completes the prompt
    # (only then does the last stage pick a token / publish trie pages)
    chunk_rest: Optional[jnp.ndarray] = None
    chunk_off: int = 0
    chunk_next: int = 0
    chunk_final: bool = False
    chunks_done: int = 0
    tokens: List = field(default_factory=list)

    @property
    def pos(self) -> int:
        """Cache position for the NEXT decode wave: the wave that produces
        token len(tokens)+1 attends through position prompt_len +
        len(tokens) - 1 (mirrors DecodePipeline.generate's pos)."""
        return self.prompt_len + len(self.tokens) - 1


def _build_request(pipe: DecodePipeline, rid, ids, new_tokens: int,
                   temperature: float, top_k: int, seed: int,
                   eos_token: Optional[int], pad_token: Optional[int],
                   prefix: Optional[Dict],
                   on_token=None, cancel=None,
                   deadline: Optional[float] = None,
                   shipped: Optional[Dict] = None) -> _Request:
    """Validate one request's arguments against `pipe` and build its
    `_Request` — the shared admission contract of the wave batcher and
    the stage-worker executor (identical errors, identical rng/pick
    discipline, so token streams match across executors)."""
    ids = jnp.asarray(ids, jnp.int32)
    if ids.ndim != 2 or ids.shape[1] == 0:
        raise ValueError("prompt must be [B, S] with S >= 1, got "
                         f"shape {ids.shape}")
    if new_tokens < 1:
        raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
    if pad_token is not None and eos_token is None:
        raise ValueError("pad_token only applies with eos_token (rows "
                         "are padded after their own eos)")
    if prefix is not None:
        # reject handles built by an incompatible pipeline up front
        # (a mismatch would otherwise surface as an opaque jit shape
        # error mid-tick, or corrupt attend windows)
        pipe.check_prefix(prefix)
    if shipped is not None and prefix is not None:
        raise ValueError("shipped KV already covers the whole prompt; "
                         "it does not compose with a prefix handle")
    prompt_len = ids.shape[1] + (prefix["len"] if prefix else 0)
    validate_capacity(pipe.cfg, pipe.max_len, prompt_len, new_tokens)
    return _Request(
        rid=rid, ids=ids, new_tokens=new_tokens,
        pick=make_token_picker(temperature, top_k),
        rng=jax.random.PRNGKey(seed), prompt_len=prompt_len,
        prefix=prefix, eos_token=eos_token,
        pad_token=eos_token if pad_token is None else pad_token,
        on_token=on_token, cancel=cancel,
        deadline=None if deadline is None else float(deadline),
        shipped=shipped)


def _seed_caches(pipe: DecodePipeline, req: _Request) -> str:
    """Create the request's per-stage cache slots and return its prompt
    pass kind: a prefix-seeded request's suffix runs as one SPAN at the
    prefix offset (prompt caching); otherwise a fresh prefill. Shared by
    the wave batcher's admission and the stage workers' submit."""
    if req.prefix is not None:
        req.caches = [_repeat_batch(c, req.ids.shape[0])
                      for c in req.prefix["caches"]]
        return "span"
    req.caches = pipe._fresh_caches(req.ids.shape[0])
    return "prefill"


def _next_chunk(req: _Request, chunk_tokens: int) -> jnp.ndarray:
    """Pop the next prompt chunk off `req.chunk_rest`: advances
    `chunk_off`/`chunk_next`, sets `chunk_final` on the last slice.
    `chunk_tokens` is read per pop, so a brownout chunk clamp
    (`set_chunk_tokens`) takes effect at the next chunk boundary."""
    rest = req.chunk_rest
    take = rest.shape[1] if chunk_tokens < 1 \
        else min(int(chunk_tokens), rest.shape[1])
    req.chunk_off = req.chunk_next
    req.chunk_next += take
    data, rest = rest[:, :take], rest[:, take:]
    req.chunk_rest = rest if rest.shape[1] else None
    req.chunk_final = req.chunk_rest is None
    req.chunks_done += 1
    _sched_mark("chunk", req.rid)
    return data


def _maybe_chunk(req: _Request, kind: str, data,
                 chunk_tokens: int):
    """Convert a long prompt pass into its first CHUNK. A prompt pass
    ("prefill" for a fresh prompt, "span" for a prefix/trie-seeded
    suffix) longer than `chunk_tokens` becomes a sequence of "chunk"
    waves: each runs `chunk_tokens` prompt positions as a span at its
    absolute offset (DecodePipeline.extend's rule — token-identical to
    the single pass for fp caches, where masked positions contribute
    exact softmax zeros), and the scheduler interleaves other requests'
    decode steps between chunks. The base offset is uniform across
    seeding paths: prompt_len - data_len (0 fresh, shared_len trie,
    prefix_len dense prefix)."""
    if chunk_tokens < 1 or kind not in ("prefill", "span") \
            or data.shape[1] <= chunk_tokens:
        return kind, data
    req.chunk_next = req.prompt_len - data.shape[1]
    req.chunk_rest = data
    return "chunk", _next_chunk(req, chunk_tokens)


def _run_stage(pipe: DecodePipeline, i: int, req: _Request, data,
               kind: str):
    """One stage-step dispatch for request `req` at stage `i` — THE
    per-stage semantics (device placement, prefill vs span vs step),
    shared by ContinuousBatcher.tick and StageWorkerExecutor's workers
    so the two executors can never drift apart. Each step records a
    request-tagged `stage`/`exec{i}` span (rid = the request id), so
    trace_report --request attributes a slow request's per-stage compute
    without a fleet trace — free when span recording is off. The mb tag
    stays None: decode-step indices are NOT microbatch ids, and tagging
    them as such would cross-link unrelated concurrent requests through
    every mb-keyed consumer (trace_slice, flow events)."""
    st = pipe.stages[i]
    with telemetry.span("stage", f"exec{i}", stage=i,
                        rid=str(req.rid)):
        if st["device"] is not None:
            data = jax.device_put(data, st["device"])
        if kind == "prefill":
            out, req.caches[i] = st["prefill"](st["params"], data,
                                               req.caches[i])
        elif kind == "span":
            # prefix-seeded prompt pass: the suffix runs as one span at
            # the prefix offset (DecodePipeline.extend's rule)
            out, req.caches[i] = pipe._decode_step(
                st, data, req.caches[i], req.prefix["len"],
                span=data.shape[1])
        elif kind == "chunk":
            # chunked prefill: this slice of the prompt runs as a span
            # at its absolute offset; earlier chunks' KV rows are
            # already in the caches, so attention is exact
            out, req.caches[i] = pipe._decode_step(
                st, data, req.caches[i], req.chunk_off,
                span=data.shape[1])
        else:
            out, req.caches[i] = pipe._decode_step(st, data, req.caches[i],
                                                   req.pos)
    return out


def _expired(req: _Request, now: Optional[float] = None) -> bool:
    """THE deadline check, shared by both executors at their decode-step
    boundaries (and at admission): past-deadline requests fire the
    existing `cancel` flag — one cancellation mechanism, two triggers
    (client disconnect, deadline) — and record `expired` so the serving
    layer can tell a 504 from an ordinary early completion."""
    if req.deadline is None:
        return False
    if (now if now is not None else time.monotonic()) < req.deadline:
        return False
    req.expired = True
    cancel_set = getattr(req.cancel, "set", None)
    if cancel_set is not None:
        cancel_set()
    return True


def _finalize_tokens(req: _Request) -> np.ndarray:
    """[B, S + T] result array: prompt + picked tokens, with everything
    strictly after each row's first eos masked to its pad token (rows
    that hit eos early kept decoding in lockstep; no garbage
    continuation reaches the caller)."""
    if not req.tokens:
        # a request expired/cancelled before its first pick completes
        # with the bare prompt (the serving layer answers it 504)
        return np.asarray(req.ids)
    toks = np.stack([np.asarray(t) for t in req.tokens], axis=1)  # [B, T]
    if req.eos_token is not None:
        seen = np.cumsum(toks == req.eos_token, axis=1) > 0
        after = np.concatenate(
            [np.zeros_like(seen[:, :1]), seen[:, :-1]], axis=1)
        toks = np.where(after, req.pad_token, toks)
    return np.concatenate([np.asarray(req.ids), toks], axis=1)


class ContinuousBatcher:
    """Wave-scheduled multi-request decoding over a `DecodePipeline`.

    >>> batcher = ContinuousBatcher(pipe)
    >>> batcher.submit("a", ids_a, new_tokens=8)
    >>> batcher.submit("b", ids_b, new_tokens=5, temperature=0.7, seed=1)
    >>> results = batcher.run()      # {"a": [B, S_a+8], "b": [B, S_b+5]}

    Results are token-identical to `pipe.generate(ids, new_tokens, ...)`
    run solo with the same sampling settings: the same compiled stage
    programs run on the same per-request data; only the interleaving
    differs. `stats` afterwards reports ticks/stage_steps/tokens — in
    steady state with >= n_stages active requests every stage works every
    tick, i.e. ~1 token per tick vs a solo stream's 1 per n_stages.
    """

    def __init__(self, pipe: DecodePipeline, max_active: Optional[int] = None,
                 kv=None, chunk_tokens: int = 0,
                 prefill_budget: Optional[int] = None,
                 step_join: bool = False, on_step=None):
        if pipe.sp_degree != 1:
            raise ValueError("continuous batching drives per-request decode "
                             "waves; sp prefill is a whole-pipeline pass "
                             "(prefill each request solo instead)")
        self.pipe = pipe
        self.n_stages = len(pipe.stages)
        # paged-KV backend (kv/backend.py): when set, requests hold page
        # tables over the shared pool instead of private dense slots, and
        # admission is bounded by PAGES (max_active defaults to the pool's
        # page count — effectively token-bounded concurrency)
        self.kv = kv
        if max_active is None:
            max_active = (self.n_stages + 1 if kv is None
                          else max(self.n_stages + 1, kv.pool.n_pages))
        self.max_active = max_active
        if self.max_active < 1:
            raise ValueError(f"max_active must be >= 1, got {self.max_active}")
        # chunked prefill (docs/SERVING.md): prompt passes longer than
        # `chunk_tokens` are split into chunk waves; `prefill_budget`
        # bounds the prompt tokens ENTERING stage 0 per tick (default:
        # one chunk's worth), so decode steps keep landing while a long
        # prompt streams in. 0 disables chunking.
        if chunk_tokens < 0:
            raise ValueError(f"chunk_tokens must be >= 0, got {chunk_tokens}")
        self.chunk_tokens = int(chunk_tokens)
        self.prefill_budget = (self.chunk_tokens if prefill_budget is None
                               else int(prefill_budget))
        if self.chunk_tokens and self.prefill_budget < 1:
            raise ValueError("prefill_budget must be >= 1 when chunking")
        self._budget = 0
        # step_join: refill a slot freed at the LAST stage into stage 0
        # within the SAME tick (the reversed drain visits stage 0 after
        # the completion), so admission happens at step boundaries, not
        # wave boundaries. Off by default: strict-wave timing is the
        # contract tests/test_batcher.py pins.
        self.step_join = bool(step_join)
        # on_step(): fired after each decode-step boundary (a pick
        # landed) — tools/serve.py chains admission re-grants to it
        self.on_step = on_step
        self.pending: deque = deque()
        self.active = 0
        self._live_rids = set()      # pending + admitted (not yet completed)
        # stage i's input queue: (request, data, kind) tuples with kind in
        # {"prefill", "span", "chunk", "step"} ("span" = a prefix-seeded
        # request's suffix prompt pass, "chunk" = one slice of a chunked
        # prompt pass); `data` is token ids at stage 0, the previous
        # stage's hidden state after
        self._stage_q: List[deque] = [deque() for _ in range(self.n_stages)]
        self.results: Dict = {}
        self.stats = {"ticks": 0, "stage_steps": 0, "tokens": 0,
                      "prefill_chunks": 0}

    def set_chunk_tokens(self, n: int) -> None:
        """Retarget the chunk size (GIL-atomic int write) — the brownout
        ladder's chunk-clamp rung calls this from the governor thread;
        in-flight requests see it at their next chunk boundary."""
        self.chunk_tokens = max(0, int(n))

    def submit(self, rid, ids, new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0,
               eos_token: Optional[int] = None,
               pad_token: Optional[int] = None,
               prefix: Optional[Dict] = None,
               on_token=None, cancel=None,
               deadline: Optional[float] = None,
               shipped: Optional[Dict] = None) -> None:
        """Queue a request. `ids` [B, S] is a prompt batch decoded in
        lockstep (B=1 for a single sequence); each distinct (B, S) shape
        compiles its own prefill program, shared across requests.

        `shipped` (paged-KV executors only) is a prefill fleet's ship
        handle (kv/disagg.py): the prompt pass already ran remotely, so
        admission installs the KV rows into this request's pages and
        decoding starts at the first decode step.

        `prefix` (from the pipeline's `precompute_prefix`) seeds this
        request's cache slots with a shared prompt prefix; `ids` is then
        the request's SUFFIX, its prompt pass runs as one span at the
        prefix offset, and — matching `generate`'s prefix contract — the
        returned array omits the prefix. Many queued requests can share
        one handle: that is the point (1 prefix prefill for the fleet).

        `eos_token`: finish this request early — freeing its cache slots
        for the ready queue — once EVERY row of its batch has emitted the
        token (`new_tokens` stays the hard cap). Rows that finished first
        keep DECODING until the whole request stops, but their post-eos
        tokens are masked with `pad_token` (default: the eos token, HF
        generate's pad-after-eos convention) in the returned array, so
        callers never consume a finished row's garbage continuation. The
        continuous-batching payoff: short answers release capacity
        immediately instead of padding to the cap.

        `on_token(step, tokens)` fires as each step's pick lands (tokens
        is the [B] device array — the callback decides when to block on
        readback), the streaming hook `tools/serve.py` chains to chunked
        HTTP responses.

        `cancel` (an is_set()-style flag, e.g. threading.Event) requests
        cooperative cancellation: once set, the request completes at its
        next pick with the tokens decoded so far — freeing its cache
        slots for pending requests instead of decoding to the cap for a
        caller that stopped listening.

        `deadline` (absolute `time.monotonic()` seconds) bounds the
        request's USEFUL lifetime: the executor checks it at every
        decode-step boundary, and expiry fires the `cancel` flag and
        completes the request with the tokens decoded so far
        (`docs/SERVING.md` — expired work must not keep consuming the
        pipeline)."""
        if rid in self.results or rid in self._live_rids:
            raise ValueError(f"duplicate request id {rid!r}")
        if shipped is not None and self.kv is None:
            raise ValueError("shipped KV needs a paged-KV backend "
                             "(ContinuousBatcher(kv=...))")
        req = _build_request(self.pipe, rid, ids, new_tokens, temperature,
                             top_k, seed, eos_token, pad_token, prefix,
                             on_token=on_token, cancel=cancel,
                             deadline=deadline, shipped=shipped)
        if self.kv is not None:
            # a reservation bigger than the whole pool would wedge the
            # pending queue forever (can_admit never true): reject it
            # up front like the dense path's capacity check
            self.kv.check_admittable(req)
        self._live_rids.add(rid)
        self.pending.append(req)

    def _admit(self) -> None:
        while self.pending and self.active < self.max_active:
            req = self.pending[0]
            if _expired(req):
                # dead before its first wave: never seed caches or touch
                # the pipeline — the whole point of deadline propagation
                self.pending.popleft()
                self.results[req.rid] = _finalize_tokens(req)
                self._live_rids.discard(req.rid)
                continue
            if self.kv is not None:
                if not self.kv.can_admit(req):
                    break       # head-of-line: wait for page releases
                self.pending.popleft()
                kind, data = self.kv.admit(req)
                if req.tokens:
                    # shipped install picked the first token in admit
                    self.stats["tokens"] += int(req.ids.shape[0])
                if kind == "done":
                    self.kv.release(req)
                    self.results[req.rid] = _finalize_tokens(req)
                    self._live_rids.discard(req.rid)
                    continue
            else:
                self.pending.popleft()
                kind, data = _seed_caches(self.pipe, req), req.ids
            kind, data = _maybe_chunk(req, kind, data, self.chunk_tokens)
            if kind == "chunk":
                self.stats["prefill_chunks"] += 1
                M_CHUNKS.inc(executor="wave")
            self.active += 1
            _sched_mark("join", req.rid)
            self._stage_q[0].append((req, data, kind))

    def _finish_wave(self, req: _Request, out, kind: str,
                     reentries: list, eos_pending: list) -> None:
        """Last stage done: pick the next token, then complete or re-enter
        stage 0 (same split-per-pick rng discipline as generate()).

        Requests with an eos_token defer their stop decision to AFTER the
        tick's dispatch loop (`eos_pending`): the decision needs a host
        readback of the token, and blocking here — the loop's first
        iteration — would serialize every other stage's dispatch behind
        this request's compute.

        An INTERMEDIATE prompt chunk produces no token: its chunk
        boundary is a scheduling point — retire an expired/cancelled
        request right here (its pages/slots free without decoding a
        single token) or queue the next chunk."""
        if kind == "chunk" and not req.chunk_final:
            if _expired(req) or (req.cancel is not None
                                 and req.cancel.is_set()):
                self._complete(req)   # mid-prompt shed: free pages now
                return
            data = _next_chunk(req, self.chunk_tokens)
            self.stats["prefill_chunks"] += 1
            M_CHUNKS.inc(executor="wave")
            reentries.append((req, data, "chunk"))
            return
        del kind  # the last position's logits, for every wave kind:
        logits = out[:, -1]  # prefill [B,S], span [B,S_s], step [B,1]
        req.rng, sub = jax.random.split(req.rng)
        token = req.pick(logits.astype(jnp.float32), sub)
        req.tokens.append(token)
        self.stats["tokens"] += int(token.shape[0])
        M_STEPS.inc(executor="wave")
        if self.on_step is not None:
            self.on_step()
        if req.on_token is not None:
            req.on_token(len(req.tokens) - 1, token)
        done = len(req.tokens) >= req.new_tokens
        if not done and (_expired(req) or (req.cancel is not None
                                           and req.cancel.is_set())):
            self._complete(req)     # expired/caller gone: free the slots
            return
        if req.eos_token is not None:
            eos_pending.append(req)
            return
        if done:
            self._complete(req)
        else:
            reentries.append((req, token[:, None], "step"))

    def _complete(self, req: _Request) -> None:
        self.results[req.rid] = _finalize_tokens(req)
        req.caches = None            # free this request's cache slots
        req.chunk_rest = None
        if self.kv is not None:
            self.kv.release(req)     # ... or its page references
        self.active -= 1
        self._live_rids.discard(req.rid)
        _sched_mark("retire", req.rid)
        if self.step_join:
            # the slot freed at THIS step boundary joins a pending
            # request into stage 0 immediately: the reversed drain has
            # not reached stage 0 yet, so the joiner's first wave
            # dispatches within the same tick (iteration-level
            # scheduling, not wave-level)
            self._admit()

    def _decide_eos(self, req: _Request) -> None:
        """Post-dispatch stop decision for an eos request: read back the
        just-picked token (all of this tick's work is already dispatched,
        so the fence overlaps other requests' device compute)."""
        token = req.tokens[-1]
        done = len(req.tokens) >= req.new_tokens
        if not done:
            hit = np.asarray(token) == req.eos_token
            req.rows_done = hit if req.rows_done is None \
                else req.rows_done | hit
            done = bool(req.rows_done.all())
        if done:
            self._complete(req)
        else:
            self._stage_q[0].append((req, token[:, None], "step"))

    def _pop_stage0(self):
        """Token-budget-per-step policy at stage 0: the budget accrues
        `prefill_budget` tokens per tick (capped so it cannot bank an
        unbounded prompt burst) and prompt-kind dispatches
        (prefill/span/chunk) spend it. A prompt head that outruns the
        accrued budget is deferred behind the first queued decode step —
        decode steps keep landing at a guaranteed rate while a long
        prompt streams in at `prefill_budget` tokens/tick. When no
        decode step is waiting, prompt work passes regardless (budget
        throttles competition, not progress), so starvation is
        impossible. Pure deterministic queue arithmetic: interleaving is
        reproducible under a pinned seed."""
        q = self._stage_q[0]
        if self.chunk_tokens and q[0][2] != "step" \
                and q[0][1].shape[1] > self._budget:
            for k in range(1, len(q)):
                if q[k][2] == "step":
                    q.rotate(-k)
                    item = q.popleft()
                    q.rotate(k)   # restore order minus item k
                    return item
        item = q.popleft()
        if item[2] != "step":
            self._budget -= item[1].shape[1]
        return item

    def tick(self) -> bool:
        """Advance every stage by at most one stage-step; returns whether
        any work remains.

        Strict wave semantics: stages are drained back-to-front and a
        token finishing at the last stage re-enters stage 0 only AFTER the
        tick, so every request advances exactly one stage per tick and all
        of a tick's dispatches belong to DISTINCT requests. That makes a
        tick one parallel stage-time: no intra-tick data dependencies, so
        with stages on distinct devices the asynchronously dispatched
        steps genuinely overlap. (A solo request therefore costs exactly
        n_stages ticks per token — the pipeline-bubble baseline the
        batcher exists to fill.) With `step_join`, completions refill
        stage 0 mid-tick; with `chunk_tokens`, stage 0's pop obeys the
        per-tick prefill token budget."""
        cap = max(self.prefill_budget, self.chunk_tokens)
        self._budget = min(self._budget + self.prefill_budget, cap)
        self._admit()
        worked = False
        reentries: list = []
        eos_pending: list = []
        for i in reversed(range(self.n_stages)):
            if not self._stage_q[i]:
                continue
            req, data, kind = (self._pop_stage0() if i == 0
                               else self._stage_q[i].popleft())
            out = (self.kv.run_stage(i, req, data, kind)
                   if self.kv is not None
                   else _run_stage(self.pipe, i, req, data, kind))
            self.stats["stage_steps"] += 1
            worked = True
            if i + 1 < self.n_stages:
                self._stage_q[i + 1].append((req, out, kind))
            else:
                self._finish_wave(req, out, kind, reentries, eos_pending)
        self._stage_q[0].extend(reentries)
        for req in eos_pending:
            self._decide_eos(req)
        self.stats["ticks"] += worked
        self._admit()                # a completion may free a slot mid-tick
        return worked or self.active > 0 or bool(self.pending)

    def run(self) -> Dict:
        """Drive ticks until every submitted request completes; returns
        {rid: [B, prompt+new_tokens] ids} (prompt included)."""
        while self.tick():
            pass
        return self.results


class StageWorkerExecutor:
    """Stage-pinned multi-worker executor: one thread per pipeline stage.

    Where `ContinuousBatcher.tick` serializes the HOST side of every
    stage's dispatch through one Python loop (the device work is async,
    but tracing/argument handling/dispatch are not), this executor pins a
    worker thread to each stage: worker `i` blocks on stage `i`'s input
    queue, dispatches exactly its own stage's compiled programs, and
    hands the wave to stage `i+1`'s queue. Host-side dispatch of
    different stages genuinely overlaps, and the last stage's token
    picks (plus eos readbacks) never stall the other stages' dispatch.

    The per-request computation is exactly the wave batcher's — the same
    `_build_request` admission contract, the same stage programs, the
    same pick/rng discipline — so token streams are identical to solo
    `DecodePipeline.generate` runs and to `ContinuousBatcher` results
    (tests/test_batcher.py). Request lifecycle:

    >>> ex = StageWorkerExecutor(pipe)
    >>> ex.submit("a", ids, new_tokens=8)       # returns immediately
    >>> out = ex.wait("a")                      # [B, S+8]
    >>> ex.stop()

    `max_active` bounds concurrently admitted requests (KV-cache memory)
    with a semaphore: `submit` blocks while the pipeline is full —
    callers ARE the queue (one HTTP handler thread per request in
    tools/serve.py), so admission backpressure lands on them directly.
    A worker that raises marks the executor dead; every current and
    future waiter raises instead of hanging (the serve.py healthz
    contract)."""

    _DONE = object()

    def __init__(self, pipe: DecodePipeline,
                 max_active: Optional[int] = None, kv=None,
                 chunk_tokens: int = 0, step_join: bool = False,
                 on_step=None):
        import queue as queue_mod
        import threading

        from ..utils.threads import make_condition

        if pipe.sp_degree != 1:
            raise ValueError("stage workers drive per-request decode "
                             "waves; sp prefill is a whole-pipeline pass")
        self.pipe = pipe
        self.n_stages = len(pipe.stages)
        # paged-KV backend: page-table caches + token-bounded admission
        # (submit blocks on PAGE availability, not just the slot count)
        self.kv = kv
        if max_active is None:
            max_active = (self.n_stages + 1 if kv is None
                          else max(self.n_stages + 1, kv.pool.n_pages))
        self.max_active = max_active
        if self.max_active < 1:
            raise ValueError(f"max_active must be >= 1, got {self.max_active}")
        # chunked prefill: the stage queues are FIFO, so bounding every
        # item's token cost at `chunk_tokens` IS the latency policy here
        # — a decode step queued behind a chunk waits one chunk-time,
        # not one whole-prompt-time (no explicit budget needed: workers
        # interleave whatever order the queues hold)
        if chunk_tokens < 0:
            raise ValueError(f"chunk_tokens must be >= 0, got {chunk_tokens}")
        self.chunk_tokens = int(chunk_tokens)
        # stage workers join/retire at step boundaries BY CONSTRUCTION
        # (submit feeds stage 0 whenever a slot frees, mid-wave);
        # `step_join` is accepted for signature parity with the wave
        # batcher so tools/serve.py configures both identically
        self.step_join = bool(step_join)
        # on_step(): fired after each decode-step pick (last stage's
        # worker thread) — tools/serve.py chains admission re-grants
        self.on_step = on_step
        self._q = [queue_mod.Queue() for _ in range(self.n_stages)]
        # plain (not Bounded) semaphore: _die() over-releases on purpose
        # so submitters blocked on admission wake up and see the failure
        self._slots = threading.Semaphore(self.max_active)
        self._lock = make_condition("batcher.results")
        self.results: Dict = {}
        self._live = set()
        self._dead: Optional[BaseException] = None
        self.active = 0
        self.stats = {"stage_steps": [0] * self.n_stages,
                      "busy": [False] * self.n_stages, "tokens": 0,
                      "prefill_chunks": 0}
        self._workers = [
            threading.Thread(target=self._stage_loop, args=(i,),
                             daemon=True, name=f"stage-worker-{i}")
            for i in range(self.n_stages)]
        for w in self._workers:
            w.start()

    # -- client side ------------------------------------------------------

    def submit(self, rid, ids, new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0,
               eos_token: Optional[int] = None,
               pad_token: Optional[int] = None,
               prefix: Optional[Dict] = None,
               on_token=None, cancel=None,
               deadline: Optional[float] = None,
               shipped: Optional[Dict] = None) -> None:
        """Admit one request (same argument contract as
        `ContinuousBatcher.submit`, including prefix-handle validation,
        the `on_token` streaming hook, the `cancel` flag, the `deadline`
        and — on a paged-KV executor — a prefill fleet's `shipped`
        handle). BLOCKS while `max_active` requests are in flight —
        admission backpressure is the caller's thread, not an internal
        queue; a paged executor additionally blocks on PAGE
        availability."""
        if shipped is not None and self.kv is None:
            raise ValueError("shipped KV needs a paged-KV backend "
                             "(StageWorkerExecutor(kv=...))")
        req = _build_request(self.pipe, rid, ids, new_tokens, temperature,
                             top_k, seed, eos_token, pad_token, prefix,
                             on_token=on_token, cancel=cancel,
                             deadline=deadline, shipped=shipped)
        if self.kv is not None:
            # reject a bigger-than-the-pool reservation BEFORE taking a
            # slot (alloc would raise PoolExhausted anyway; this makes
            # it the same up-front ValueError the wave batcher gives)
            self.kv.check_admittable(req)
        with self._lock:
            self._check_dead()
            if rid in self.results or rid in self._live:
                raise ValueError(f"duplicate request id {rid!r}")
            self._live.add(rid)
        self._slots.acquire()
        try:
            with self._lock:
                if self._dead is not None:   # woken by _die's over-release
                    self._check_dead()
                self.active += 1
            if _expired(req):
                # the admission wait outlived the deadline: complete with
                # the bare prompt without ever touching the pipeline
                with self._lock:
                    self.results[rid] = _finalize_tokens(req)
                    self._live.discard(rid)
                    self.active -= 1
                    self._lock.notify_all()
                self._slots.release()
                return
            try:
                if self.kv is not None:
                    # page admission blocks like the slot semaphore does:
                    # completions release pages, so waiting here is the
                    # same caller-thread backpressure contract
                    kind, data = self.kv.admit(req, block=True)
                    if req.tokens and kind != "done":
                        # a shipped install's first token was picked in
                        # admit — count it like the wave batcher does
                        with self._lock:
                            self.stats["tokens"] += int(req.ids.shape[0])
                else:
                    kind, data = _seed_caches(self.pipe, req), req.ids
                if kind == "done":
                    # a shipped install whose first token already
                    # completed the request: never touches the pipeline
                    arr = _finalize_tokens(req)
                    self.kv.release(req)
                    with self._lock:
                        self.stats["tokens"] += int(req.ids.shape[0])
                        self.results[rid] = arr
                        self._live.discard(rid)
                        self.active -= 1
                        self._lock.notify_all()
                    self._slots.release()
                    return
                kind, data = _maybe_chunk(req, kind, data,
                                          self.chunk_tokens)
                if kind == "chunk":
                    with self._lock:
                        self.stats["prefill_chunks"] += 1
                    M_CHUNKS.inc(executor="workers")
                _sched_mark("join", rid)
                self._q[0].put((req, data, kind))
            except BaseException:
                # roll the admission back (e.g. cache allocation OOM /
                # page-pool exhaustion): leaking the slot would
                # eventually wedge every submit while healthz reports ok
                with self._lock:
                    self.active -= 1
                raise
        except BaseException:
            with self._lock:
                self._live.discard(rid)
            self._slots.release()
            raise

    def wait(self, rid, timeout: Optional[float] = None) -> np.ndarray:
        """Block until request `rid` completes; returns its [B, S + T]
        ids (the same array `ContinuousBatcher.run` would record)."""
        with self._lock:
            while rid not in self.results:
                self._check_dead()
                if not self._lock.wait(timeout):
                    raise TimeoutError(f"request {rid!r} not done after "
                                       f"{timeout}s")
            return self.results.pop(rid)

    def snapshot(self) -> Dict:
        """Point-in-time per-worker stats for health reporting: stage
        steps and busy flag per worker, queue depths, tokens, active."""
        with self._lock:
            return {"stage_steps": list(self.stats["stage_steps"]),
                    "busy": list(self.stats["busy"]),
                    "queued": [q.qsize() for q in self._q],
                    "tokens": self.stats["tokens"],
                    "prefill_chunks": self.stats["prefill_chunks"],
                    "active": self.active}

    def set_chunk_tokens(self, n: int) -> None:
        """Retarget the chunk size (GIL-atomic int write) — the brownout
        ladder's chunk-clamp rung calls this from the governor thread;
        in-flight requests see it at their next chunk boundary."""
        self.chunk_tokens = max(0, int(n))

    def stop(self) -> None:
        """Shut the workers down. Queued work ahead of the sentinels is
        processed, but a multi-step request cannot finish once worker 0
        exits (its re-entering waves have no one to run them) — after
        the join, every still-live request's waiter is FAILED rather
        than left hanging. Drain with `wait` before stopping if results
        matter."""
        if self.kv is not None:
            # wake submitters parked on PAGE availability too (the
            # semaphore over-release below only reaches slot waiters);
            # in-flight completions still release their pages
            self.kv.pool.close()
        for q in self._q:
            q.put(self._DONE)
        for w in self._workers:
            w.join()
        with self._lock:
            if self._live and self._dead is None:
                self._dead = RuntimeError(
                    f"executor stopped with {len(self._live)} request(s) "
                    "in flight")
            self._lock.notify_all()
            dead = self._dead is not None
        if dead:
            # mirror _die(): in-flight requests will never release their
            # admission slots now, so over-release the semaphore to wake
            # submitters blocked in acquire — they re-check _dead and
            # raise instead of hanging forever (ADVICE.md r5)
            for _ in range(self.max_active):
                self._slots.release()

    def _check_dead(self) -> None:
        if self._dead is not None:
            raise RuntimeError(f"stage worker died: {self._dead!r}")

    # -- worker side ------------------------------------------------------

    def _stage_loop(self, i: int) -> None:
        while True:
            item = self._q[i].get()
            if item is self._DONE:
                return
            req, data, kind = item
            self.stats["busy"][i] = True
            try:
                out = (self.kv.run_stage(i, req, data, kind)
                       if self.kv is not None
                       else _run_stage(self.pipe, i, req, data, kind))
                self.stats["stage_steps"][i] += 1
                if i + 1 < self.n_stages:
                    self._q[i + 1].put((req, out, kind))
                else:
                    self._finish(req, out, kind)
            except BaseException as exc:   # noqa: BLE001 — a dead worker
                self._die(exc)             # must fail waiters, not hang them
                raise
            finally:
                self.stats["busy"][i] = False

    def _finish(self, req: _Request, out, kind: str) -> None:
        """Last stage done (runs in the last stage's worker): pick the
        next token, stream it, then complete or re-enter stage 0. The
        eos readback blocks only THIS worker; earlier stages keep
        dispatching other requests. An INTERMEDIATE prompt chunk picks
        nothing: its boundary retires an expired/cancelled request (the
        mid-prompt shed frees pages before a single token decodes) or
        queues the next chunk."""
        if kind == "chunk" and not req.chunk_final:
            if _expired(req) or (req.cancel is not None
                                 and req.cancel.is_set()):
                arr = _finalize_tokens(req)   # the bare prompt
                req.caches = None
                req.chunk_rest = None
                if self.kv is not None:
                    self.kv.release(req)
                _sched_mark("retire", req.rid)
                with self._lock:
                    self.results[req.rid] = arr
                    self._live.discard(req.rid)
                    self.active -= 1
                    self._lock.notify_all()
                self._slots.release()
                return
            data = _next_chunk(req, self.chunk_tokens)
            with self._lock:
                self.stats["prefill_chunks"] += 1
            M_CHUNKS.inc(executor="workers")
            self._q[0].put((req, data, "chunk"))
            return
        logits = out[:, -1]
        req.rng, sub = jax.random.split(req.rng)
        token = req.pick(logits.astype(jnp.float32), sub)
        req.tokens.append(token)
        with self._lock:
            self.stats["tokens"] += int(token.shape[0])
        M_STEPS.inc(executor="workers")
        if self.on_step is not None:
            self.on_step()
        if req.on_token is not None:
            req.on_token(len(req.tokens) - 1, token)
        done = len(req.tokens) >= req.new_tokens
        if not done and _expired(req):
            done = True             # deadline passed: cancel mid-flight
        if not done and req.cancel is not None and req.cancel.is_set():
            done = True             # caller gone: free the slot early
        if not done and req.eos_token is not None:
            hit = np.asarray(token) == req.eos_token
            req.rows_done = hit if req.rows_done is None \
                else req.rows_done | hit
            done = bool(req.rows_done.all())
        if done:
            arr = _finalize_tokens(req)
            req.caches = None        # free this request's cache slots
            if self.kv is not None:
                self.kv.release(req)  # ... or its page references
            _sched_mark("retire", req.rid)
            with self._lock:
                self.results[req.rid] = arr
                self._live.discard(req.rid)
                self.active -= 1
                self._lock.notify_all()
            self._slots.release()
        else:
            self._q[0].put((req, token[:, None], "step"))

    def _die(self, exc: BaseException) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = exc
            self._lock.notify_all()
        # wake submitters blocked on admission so they observe the death
        # — both the slot semaphore and (paged) the page-pool wait
        if self.kv is not None:
            self.kv.pool.close()
        for _ in range(self.max_active):
            self._slots.release()
