"""Continuous batching for pipelined decoding: concurrent requests fill the
pipeline bubbles a single autoregressive stream leaves empty.

A single stream decodes one token per FULL pipeline traversal — with K
stages, every stage idles K-1 of every K stage-times (docs/DECODE.md).
Interleaving S concurrent requests as a wave — stage i decoding request r
while stage i+1 decodes request r-1 — keeps every stage busy once S >=
K, multiplying aggregate tokens/sec by ~min(S, K) without touching the
compiled stage programs.

TPU-first constraints drive the design:

- **Static shapes preserved**: each request keeps its OWN per-stage cache
  slots (created at admission, freed at completion), so the compiled
  prefill/decode programs are exactly DecodePipeline's — one program per
  (batch, prompt-shape) signature, shared by every request with that
  signature, and token-for-token identical to a solo `generate()` run.
  There is no cross-request padding or masking to invalidate shapes.
- **Wave scheduling, host-driven**: the scheduler advances one "tick" at a
  time; per tick each stage dispatches at most one request's stage-step.
  Stages are processed back-to-front so a request advances exactly one
  stage per tick (and a token finishing at the last stage re-enters stage
  0 within the same tick — no idle gap). JAX dispatch is asynchronous, so
  with stages placed on distinct devices the per-tick dispatches execute
  concurrently; the host never blocks inside a tick.
- **Ready-queue admission**: requests wait in a FIFO until an active slot
  frees (`max_active` bounds cache memory, default = enough to saturate
  the pipeline); arrivals and completions interleave freely mid-run —
  the "continuous" in continuous batching.

The reference has no analogue (its runtime is single-shot batch inference;
the decode subsystem itself is already beyond-reference — docs/DECODE.md).
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from .decode import (DecodePipeline, _repeat_batch, make_token_picker,
                     validate_capacity)


@dataclass
class _Request:
    rid: object
    ids: jnp.ndarray                 # [B, S] prompt (prompt included in
    new_tokens: int                  # the result; the SUFFIX when a
    pick: object                     # prefix handle seeds the caches)
    rng: jax.Array
    prompt_len: int                  # prefix + suffix
    prefix: Optional[Dict] = None    # precompute_prefix handle
    eos_token: Optional[int] = None  # stop early once every row emitted it
    pad_token: Optional[int] = None  # fills rows past their own eos
    # streaming hook: fires (step, [B] device tokens) as each pick lands
    on_token: Optional[object] = None
    # cooperative cancellation: an is_set()-style flag (threading.Event)
    # checked after each pick — a cancelled request completes with the
    # tokens decoded so far, freeing its cache slots/admission slot early
    # (dead streaming clients must not hold capacity, tools/serve.py)
    cancel: Optional[object] = None
    # absolute monotonic deadline (docs/SERVING.md): checked at every
    # decode-step boundary; expiry FIRES the cancel flag and completes
    # the request early — expired work must stop consuming TPU time
    # mid-flight, not decode uselessly to the cap
    deadline: Optional[float] = None
    expired: bool = False            # the deadline check tripped
    rows_done: Optional[np.ndarray] = None   # [B] eos seen per row
    caches: Optional[List] = None    # per-stage cache slots (admission)
    # paged-KV plane (pipeedge_tpu/kv): page tables + sharing state when
    # a PagedKvBackend drives this request instead of dense cache slots
    kvstate: Optional[Dict] = None
    # a prefill fleet's ship handle (kv/disagg.py): the prompt pass
    # already ran remotely; admission installs the KV rows and decoding
    # starts directly at the first decode step
    shipped: Optional[Dict] = None
    tokens: List = field(default_factory=list)

    @property
    def pos(self) -> int:
        """Cache position for the NEXT decode wave: the wave that produces
        token len(tokens)+1 attends through position prompt_len +
        len(tokens) - 1 (mirrors DecodePipeline.generate's pos)."""
        return self.prompt_len + len(self.tokens) - 1


def _build_request(pipe: DecodePipeline, rid, ids, new_tokens: int,
                   temperature: float, top_k: int, seed: int,
                   eos_token: Optional[int], pad_token: Optional[int],
                   prefix: Optional[Dict],
                   on_token=None, cancel=None,
                   deadline: Optional[float] = None,
                   shipped: Optional[Dict] = None) -> _Request:
    """Validate one request's arguments against `pipe` and build its
    `_Request` — the shared admission contract of the wave batcher and
    the stage-worker executor (identical errors, identical rng/pick
    discipline, so token streams match across executors)."""
    ids = jnp.asarray(ids, jnp.int32)
    if ids.ndim != 2 or ids.shape[1] == 0:
        raise ValueError("prompt must be [B, S] with S >= 1, got "
                         f"shape {ids.shape}")
    if new_tokens < 1:
        raise ValueError(f"new_tokens must be >= 1, got {new_tokens}")
    if pad_token is not None and eos_token is None:
        raise ValueError("pad_token only applies with eos_token (rows "
                         "are padded after their own eos)")
    if prefix is not None:
        # reject handles built by an incompatible pipeline up front
        # (a mismatch would otherwise surface as an opaque jit shape
        # error mid-tick, or corrupt attend windows)
        pipe.check_prefix(prefix)
    if shipped is not None and prefix is not None:
        raise ValueError("shipped KV already covers the whole prompt; "
                         "it does not compose with a prefix handle")
    prompt_len = ids.shape[1] + (prefix["len"] if prefix else 0)
    validate_capacity(pipe.cfg, pipe.max_len, prompt_len, new_tokens)
    return _Request(
        rid=rid, ids=ids, new_tokens=new_tokens,
        pick=make_token_picker(temperature, top_k),
        rng=jax.random.PRNGKey(seed), prompt_len=prompt_len,
        prefix=prefix, eos_token=eos_token,
        pad_token=eos_token if pad_token is None else pad_token,
        on_token=on_token, cancel=cancel,
        deadline=None if deadline is None else float(deadline),
        shipped=shipped)


def _seed_caches(pipe: DecodePipeline, req: _Request) -> str:
    """Create the request's per-stage cache slots and return its prompt
    pass kind: a prefix-seeded request's suffix runs as one SPAN at the
    prefix offset (prompt caching); otherwise a fresh prefill. Shared by
    the wave batcher's admission and the stage workers' submit."""
    if req.prefix is not None:
        req.caches = [_repeat_batch(c, req.ids.shape[0])
                      for c in req.prefix["caches"]]
        return "span"
    req.caches = pipe._fresh_caches(req.ids.shape[0])
    return "prefill"


def _run_stage(pipe: DecodePipeline, i: int, req: _Request, data,
               kind: str):
    """One stage-step dispatch for request `req` at stage `i` — THE
    per-stage semantics (device placement, prefill vs span vs step),
    shared by ContinuousBatcher.tick and StageWorkerExecutor's workers
    so the two executors can never drift apart. Each step records a
    request-tagged `stage`/`exec{i}` span (rid = the request id), so
    trace_report --request attributes a slow request's per-stage compute
    without a fleet trace — free when span recording is off. The mb tag
    stays None: decode-step indices are NOT microbatch ids, and tagging
    them as such would cross-link unrelated concurrent requests through
    every mb-keyed consumer (trace_slice, flow events)."""
    st = pipe.stages[i]
    with telemetry.span("stage", f"exec{i}", stage=i,
                        rid=str(req.rid)):
        if st["device"] is not None:
            data = jax.device_put(data, st["device"])
        if kind == "prefill":
            out, req.caches[i] = st["prefill"](st["params"], data,
                                               req.caches[i])
        elif kind == "span":
            # prefix-seeded prompt pass: the suffix runs as one span at
            # the prefix offset (DecodePipeline.extend's rule)
            out, req.caches[i] = pipe._decode_step(
                st, data, req.caches[i], req.prefix["len"],
                span=data.shape[1])
        else:
            out, req.caches[i] = pipe._decode_step(st, data, req.caches[i],
                                                   req.pos)
    return out


def _expired(req: _Request, now: Optional[float] = None) -> bool:
    """THE deadline check, shared by both executors at their decode-step
    boundaries (and at admission): past-deadline requests fire the
    existing `cancel` flag — one cancellation mechanism, two triggers
    (client disconnect, deadline) — and record `expired` so the serving
    layer can tell a 504 from an ordinary early completion."""
    if req.deadline is None:
        return False
    if (now if now is not None else time.monotonic()) < req.deadline:
        return False
    req.expired = True
    cancel_set = getattr(req.cancel, "set", None)
    if cancel_set is not None:
        cancel_set()
    return True


def _finalize_tokens(req: _Request) -> np.ndarray:
    """[B, S + T] result array: prompt + picked tokens, with everything
    strictly after each row's first eos masked to its pad token (rows
    that hit eos early kept decoding in lockstep; no garbage
    continuation reaches the caller)."""
    if not req.tokens:
        # a request expired/cancelled before its first pick completes
        # with the bare prompt (the serving layer answers it 504)
        return np.asarray(req.ids)
    toks = np.stack([np.asarray(t) for t in req.tokens], axis=1)  # [B, T]
    if req.eos_token is not None:
        seen = np.cumsum(toks == req.eos_token, axis=1) > 0
        after = np.concatenate(
            [np.zeros_like(seen[:, :1]), seen[:, :-1]], axis=1)
        toks = np.where(after, req.pad_token, toks)
    return np.concatenate([np.asarray(req.ids), toks], axis=1)


class ContinuousBatcher:
    """Wave-scheduled multi-request decoding over a `DecodePipeline`.

    >>> batcher = ContinuousBatcher(pipe)
    >>> batcher.submit("a", ids_a, new_tokens=8)
    >>> batcher.submit("b", ids_b, new_tokens=5, temperature=0.7, seed=1)
    >>> results = batcher.run()      # {"a": [B, S_a+8], "b": [B, S_b+5]}

    Results are token-identical to `pipe.generate(ids, new_tokens, ...)`
    run solo with the same sampling settings: the same compiled stage
    programs run on the same per-request data; only the interleaving
    differs. `stats` afterwards reports ticks/stage_steps/tokens — in
    steady state with >= n_stages active requests every stage works every
    tick, i.e. ~1 token per tick vs a solo stream's 1 per n_stages.
    """

    def __init__(self, pipe: DecodePipeline, max_active: Optional[int] = None,
                 kv=None):
        if pipe.sp_degree != 1:
            raise ValueError("continuous batching drives per-request decode "
                             "waves; sp prefill is a whole-pipeline pass "
                             "(prefill each request solo instead)")
        self.pipe = pipe
        self.n_stages = len(pipe.stages)
        # paged-KV backend (kv/backend.py): when set, requests hold page
        # tables over the shared pool instead of private dense slots, and
        # admission is bounded by PAGES (max_active defaults to the pool's
        # page count — effectively token-bounded concurrency)
        self.kv = kv
        if max_active is None:
            max_active = (self.n_stages + 1 if kv is None
                          else max(self.n_stages + 1, kv.pool.n_pages))
        self.max_active = max_active
        if self.max_active < 1:
            raise ValueError(f"max_active must be >= 1, got {self.max_active}")
        self.pending: deque = deque()
        self.active = 0
        self._live_rids = set()      # pending + admitted (not yet completed)
        # stage i's input queue: (request, data, kind) tuples with kind in
        # {"prefill", "span", "step"} ("span" = a prefix-seeded request's
        # suffix prompt pass); `data` is token ids at stage 0, the
        # previous stage's hidden state after
        self._stage_q: List[deque] = [deque() for _ in range(self.n_stages)]
        self.results: Dict = {}
        self.stats = {"ticks": 0, "stage_steps": 0, "tokens": 0}

    def submit(self, rid, ids, new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0,
               eos_token: Optional[int] = None,
               pad_token: Optional[int] = None,
               prefix: Optional[Dict] = None,
               on_token=None, cancel=None,
               deadline: Optional[float] = None,
               shipped: Optional[Dict] = None) -> None:
        """Queue a request. `ids` [B, S] is a prompt batch decoded in
        lockstep (B=1 for a single sequence); each distinct (B, S) shape
        compiles its own prefill program, shared across requests.

        `shipped` (paged-KV executors only) is a prefill fleet's ship
        handle (kv/disagg.py): the prompt pass already ran remotely, so
        admission installs the KV rows into this request's pages and
        decoding starts at the first decode step.

        `prefix` (from the pipeline's `precompute_prefix`) seeds this
        request's cache slots with a shared prompt prefix; `ids` is then
        the request's SUFFIX, its prompt pass runs as one span at the
        prefix offset, and — matching `generate`'s prefix contract — the
        returned array omits the prefix. Many queued requests can share
        one handle: that is the point (1 prefix prefill for the fleet).

        `eos_token`: finish this request early — freeing its cache slots
        for the ready queue — once EVERY row of its batch has emitted the
        token (`new_tokens` stays the hard cap). Rows that finished first
        keep DECODING until the whole request stops, but their post-eos
        tokens are masked with `pad_token` (default: the eos token, HF
        generate's pad-after-eos convention) in the returned array, so
        callers never consume a finished row's garbage continuation. The
        continuous-batching payoff: short answers release capacity
        immediately instead of padding to the cap.

        `on_token(step, tokens)` fires as each step's pick lands (tokens
        is the [B] device array — the callback decides when to block on
        readback), the streaming hook `tools/serve.py` chains to chunked
        HTTP responses.

        `cancel` (an is_set()-style flag, e.g. threading.Event) requests
        cooperative cancellation: once set, the request completes at its
        next pick with the tokens decoded so far — freeing its cache
        slots for pending requests instead of decoding to the cap for a
        caller that stopped listening.

        `deadline` (absolute `time.monotonic()` seconds) bounds the
        request's USEFUL lifetime: the executor checks it at every
        decode-step boundary, and expiry fires the `cancel` flag and
        completes the request with the tokens decoded so far
        (`docs/SERVING.md` — expired work must not keep consuming the
        pipeline)."""
        if rid in self.results or rid in self._live_rids:
            raise ValueError(f"duplicate request id {rid!r}")
        if shipped is not None and self.kv is None:
            raise ValueError("shipped KV needs a paged-KV backend "
                             "(ContinuousBatcher(kv=...))")
        req = _build_request(self.pipe, rid, ids, new_tokens, temperature,
                             top_k, seed, eos_token, pad_token, prefix,
                             on_token=on_token, cancel=cancel,
                             deadline=deadline, shipped=shipped)
        if self.kv is not None:
            # a reservation bigger than the whole pool would wedge the
            # pending queue forever (can_admit never true): reject it
            # up front like the dense path's capacity check
            self.kv.check_admittable(req)
        self._live_rids.add(rid)
        self.pending.append(req)

    def _admit(self) -> None:
        while self.pending and self.active < self.max_active:
            req = self.pending[0]
            if _expired(req):
                # dead before its first wave: never seed caches or touch
                # the pipeline — the whole point of deadline propagation
                self.pending.popleft()
                self.results[req.rid] = _finalize_tokens(req)
                self._live_rids.discard(req.rid)
                continue
            if self.kv is not None:
                if not self.kv.can_admit(req):
                    break       # head-of-line: wait for page releases
                self.pending.popleft()
                kind, data = self.kv.admit(req)
                if req.tokens:
                    # shipped install picked the first token in admit
                    self.stats["tokens"] += int(req.ids.shape[0])
                if kind == "done":
                    self.kv.release(req)
                    self.results[req.rid] = _finalize_tokens(req)
                    self._live_rids.discard(req.rid)
                    continue
            else:
                self.pending.popleft()
                kind, data = _seed_caches(self.pipe, req), req.ids
            self.active += 1
            self._stage_q[0].append((req, data, kind))

    def _finish_wave(self, req: _Request, out, kind: str,
                     reentries: list, eos_pending: list) -> None:
        """Last stage done: pick the next token, then complete or re-enter
        stage 0 (same split-per-pick rng discipline as generate()).

        Requests with an eos_token defer their stop decision to AFTER the
        tick's dispatch loop (`eos_pending`): the decision needs a host
        readback of the token, and blocking here — the loop's first
        iteration — would serialize every other stage's dispatch behind
        this request's compute."""
        del kind  # the last position's logits, for every wave kind:
        logits = out[:, -1]  # prefill [B,S], span [B,S_s], step [B,1]
        req.rng, sub = jax.random.split(req.rng)
        token = req.pick(logits.astype(jnp.float32), sub)
        req.tokens.append(token)
        self.stats["tokens"] += int(token.shape[0])
        if req.on_token is not None:
            req.on_token(len(req.tokens) - 1, token)
        done = len(req.tokens) >= req.new_tokens
        if not done and (_expired(req) or (req.cancel is not None
                                           and req.cancel.is_set())):
            self._complete(req)     # expired/caller gone: free the slots
            return
        if req.eos_token is not None:
            eos_pending.append(req)
            return
        if done:
            self._complete(req)
        else:
            reentries.append((req, token[:, None], "step"))

    def _complete(self, req: _Request) -> None:
        self.results[req.rid] = _finalize_tokens(req)
        req.caches = None            # free this request's cache slots
        if self.kv is not None:
            self.kv.release(req)     # ... or its page references
        self.active -= 1
        self._live_rids.discard(req.rid)

    def _decide_eos(self, req: _Request) -> None:
        """Post-dispatch stop decision for an eos request: read back the
        just-picked token (all of this tick's work is already dispatched,
        so the fence overlaps other requests' device compute)."""
        token = req.tokens[-1]
        done = len(req.tokens) >= req.new_tokens
        if not done:
            hit = np.asarray(token) == req.eos_token
            req.rows_done = hit if req.rows_done is None \
                else req.rows_done | hit
            done = bool(req.rows_done.all())
        if done:
            self._complete(req)
        else:
            self._stage_q[0].append((req, token[:, None], "step"))

    def tick(self) -> bool:
        """Advance every stage by at most one stage-step; returns whether
        any work remains.

        Strict wave semantics: stages are drained back-to-front and a
        token finishing at the last stage re-enters stage 0 only AFTER the
        tick, so every request advances exactly one stage per tick and all
        of a tick's dispatches belong to DISTINCT requests. That makes a
        tick one parallel stage-time: no intra-tick data dependencies, so
        with stages on distinct devices the asynchronously dispatched
        steps genuinely overlap. (A solo request therefore costs exactly
        n_stages ticks per token — the pipeline-bubble baseline the
        batcher exists to fill.)"""
        self._admit()
        worked = False
        reentries: list = []
        eos_pending: list = []
        for i in reversed(range(self.n_stages)):
            if not self._stage_q[i]:
                continue
            req, data, kind = self._stage_q[i].popleft()
            out = (self.kv.run_stage(i, req, data, kind)
                   if self.kv is not None
                   else _run_stage(self.pipe, i, req, data, kind))
            self.stats["stage_steps"] += 1
            worked = True
            if i + 1 < self.n_stages:
                self._stage_q[i + 1].append((req, out, kind))
            else:
                self._finish_wave(req, out, kind, reentries, eos_pending)
        self._stage_q[0].extend(reentries)
        for req in eos_pending:
            self._decide_eos(req)
        self.stats["ticks"] += worked
        self._admit()                # a completion may free a slot mid-tick
        return worked or self.active > 0 or bool(self.pending)

    def run(self) -> Dict:
        """Drive ticks until every submitted request completes; returns
        {rid: [B, prompt+new_tokens] ids} (prompt included)."""
        while self.tick():
            pass
        return self.results


class StageWorkerExecutor:
    """Stage-pinned multi-worker executor: one thread per pipeline stage.

    Where `ContinuousBatcher.tick` serializes the HOST side of every
    stage's dispatch through one Python loop (the device work is async,
    but tracing/argument handling/dispatch are not), this executor pins a
    worker thread to each stage: worker `i` blocks on stage `i`'s input
    queue, dispatches exactly its own stage's compiled programs, and
    hands the wave to stage `i+1`'s queue. Host-side dispatch of
    different stages genuinely overlaps, and the last stage's token
    picks (plus eos readbacks) never stall the other stages' dispatch.

    The per-request computation is exactly the wave batcher's — the same
    `_build_request` admission contract, the same stage programs, the
    same pick/rng discipline — so token streams are identical to solo
    `DecodePipeline.generate` runs and to `ContinuousBatcher` results
    (tests/test_batcher.py). Request lifecycle:

    >>> ex = StageWorkerExecutor(pipe)
    >>> ex.submit("a", ids, new_tokens=8)       # returns immediately
    >>> out = ex.wait("a")                      # [B, S+8]
    >>> ex.stop()

    `max_active` bounds concurrently admitted requests (KV-cache memory)
    with a semaphore: `submit` blocks while the pipeline is full —
    callers ARE the queue (one HTTP handler thread per request in
    tools/serve.py), so admission backpressure lands on them directly.
    A worker that raises marks the executor dead; every current and
    future waiter raises instead of hanging (the serve.py healthz
    contract)."""

    _DONE = object()

    def __init__(self, pipe: DecodePipeline,
                 max_active: Optional[int] = None, kv=None):
        import queue as queue_mod
        import threading

        from ..utils.threads import make_condition

        if pipe.sp_degree != 1:
            raise ValueError("stage workers drive per-request decode "
                             "waves; sp prefill is a whole-pipeline pass")
        self.pipe = pipe
        self.n_stages = len(pipe.stages)
        # paged-KV backend: page-table caches + token-bounded admission
        # (submit blocks on PAGE availability, not just the slot count)
        self.kv = kv
        if max_active is None:
            max_active = (self.n_stages + 1 if kv is None
                          else max(self.n_stages + 1, kv.pool.n_pages))
        self.max_active = max_active
        if self.max_active < 1:
            raise ValueError(f"max_active must be >= 1, got {self.max_active}")
        self._q = [queue_mod.Queue() for _ in range(self.n_stages)]
        # plain (not Bounded) semaphore: _die() over-releases on purpose
        # so submitters blocked on admission wake up and see the failure
        self._slots = threading.Semaphore(self.max_active)
        self._lock = make_condition("batcher.results")
        self.results: Dict = {}
        self._live = set()
        self._dead: Optional[BaseException] = None
        self.active = 0
        self.stats = {"stage_steps": [0] * self.n_stages,
                      "busy": [False] * self.n_stages, "tokens": 0}
        self._workers = [
            threading.Thread(target=self._stage_loop, args=(i,),
                             daemon=True, name=f"stage-worker-{i}")
            for i in range(self.n_stages)]
        for w in self._workers:
            w.start()

    # -- client side ------------------------------------------------------

    def submit(self, rid, ids, new_tokens: int, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0,
               eos_token: Optional[int] = None,
               pad_token: Optional[int] = None,
               prefix: Optional[Dict] = None,
               on_token=None, cancel=None,
               deadline: Optional[float] = None,
               shipped: Optional[Dict] = None) -> None:
        """Admit one request (same argument contract as
        `ContinuousBatcher.submit`, including prefix-handle validation,
        the `on_token` streaming hook, the `cancel` flag, the `deadline`
        and — on a paged-KV executor — a prefill fleet's `shipped`
        handle). BLOCKS while `max_active` requests are in flight —
        admission backpressure is the caller's thread, not an internal
        queue; a paged executor additionally blocks on PAGE
        availability."""
        if shipped is not None and self.kv is None:
            raise ValueError("shipped KV needs a paged-KV backend "
                             "(StageWorkerExecutor(kv=...))")
        req = _build_request(self.pipe, rid, ids, new_tokens, temperature,
                             top_k, seed, eos_token, pad_token, prefix,
                             on_token=on_token, cancel=cancel,
                             deadline=deadline, shipped=shipped)
        if self.kv is not None:
            # reject a bigger-than-the-pool reservation BEFORE taking a
            # slot (alloc would raise PoolExhausted anyway; this makes
            # it the same up-front ValueError the wave batcher gives)
            self.kv.check_admittable(req)
        with self._lock:
            self._check_dead()
            if rid in self.results or rid in self._live:
                raise ValueError(f"duplicate request id {rid!r}")
            self._live.add(rid)
        self._slots.acquire()
        try:
            with self._lock:
                if self._dead is not None:   # woken by _die's over-release
                    self._check_dead()
                self.active += 1
            if _expired(req):
                # the admission wait outlived the deadline: complete with
                # the bare prompt without ever touching the pipeline
                with self._lock:
                    self.results[rid] = _finalize_tokens(req)
                    self._live.discard(rid)
                    self.active -= 1
                    self._lock.notify_all()
                self._slots.release()
                return
            try:
                if self.kv is not None:
                    # page admission blocks like the slot semaphore does:
                    # completions release pages, so waiting here is the
                    # same caller-thread backpressure contract
                    kind, data = self.kv.admit(req, block=True)
                    if req.tokens and kind != "done":
                        # a shipped install's first token was picked in
                        # admit — count it like the wave batcher does
                        with self._lock:
                            self.stats["tokens"] += int(req.ids.shape[0])
                else:
                    kind, data = _seed_caches(self.pipe, req), req.ids
                if kind == "done":
                    # a shipped install whose first token already
                    # completed the request: never touches the pipeline
                    arr = _finalize_tokens(req)
                    self.kv.release(req)
                    with self._lock:
                        self.stats["tokens"] += int(req.ids.shape[0])
                        self.results[rid] = arr
                        self._live.discard(rid)
                        self.active -= 1
                        self._lock.notify_all()
                    self._slots.release()
                    return
                self._q[0].put((req, data, kind))
            except BaseException:
                # roll the admission back (e.g. cache allocation OOM /
                # page-pool exhaustion): leaking the slot would
                # eventually wedge every submit while healthz reports ok
                with self._lock:
                    self.active -= 1
                raise
        except BaseException:
            with self._lock:
                self._live.discard(rid)
            self._slots.release()
            raise

    def wait(self, rid, timeout: Optional[float] = None) -> np.ndarray:
        """Block until request `rid` completes; returns its [B, S + T]
        ids (the same array `ContinuousBatcher.run` would record)."""
        with self._lock:
            while rid not in self.results:
                self._check_dead()
                if not self._lock.wait(timeout):
                    raise TimeoutError(f"request {rid!r} not done after "
                                       f"{timeout}s")
            return self.results.pop(rid)

    def snapshot(self) -> Dict:
        """Point-in-time per-worker stats for health reporting: stage
        steps and busy flag per worker, queue depths, tokens, active."""
        with self._lock:
            return {"stage_steps": list(self.stats["stage_steps"]),
                    "busy": list(self.stats["busy"]),
                    "queued": [q.qsize() for q in self._q],
                    "tokens": self.stats["tokens"],
                    "active": self.active}

    def stop(self) -> None:
        """Shut the workers down. Queued work ahead of the sentinels is
        processed, but a multi-step request cannot finish once worker 0
        exits (its re-entering waves have no one to run them) — after
        the join, every still-live request's waiter is FAILED rather
        than left hanging. Drain with `wait` before stopping if results
        matter."""
        if self.kv is not None:
            # wake submitters parked on PAGE availability too (the
            # semaphore over-release below only reaches slot waiters);
            # in-flight completions still release their pages
            self.kv.pool.close()
        for q in self._q:
            q.put(self._DONE)
        for w in self._workers:
            w.join()
        with self._lock:
            if self._live and self._dead is None:
                self._dead = RuntimeError(
                    f"executor stopped with {len(self._live)} request(s) "
                    "in flight")
            self._lock.notify_all()
            dead = self._dead is not None
        if dead:
            # mirror _die(): in-flight requests will never release their
            # admission slots now, so over-release the semaphore to wake
            # submitters blocked in acquire — they re-check _dead and
            # raise instead of hanging forever (ADVICE.md r5)
            for _ in range(self.max_active):
                self._slots.release()

    def _check_dead(self) -> None:
        if self._dead is not None:
            raise RuntimeError(f"stage worker died: {self._dead!r}")

    # -- worker side ------------------------------------------------------

    def _stage_loop(self, i: int) -> None:
        while True:
            item = self._q[i].get()
            if item is self._DONE:
                return
            req, data, kind = item
            self.stats["busy"][i] = True
            try:
                out = (self.kv.run_stage(i, req, data, kind)
                       if self.kv is not None
                       else _run_stage(self.pipe, i, req, data, kind))
                self.stats["stage_steps"][i] += 1
                if i + 1 < self.n_stages:
                    self._q[i + 1].put((req, out, kind))
                else:
                    self._finish(req, out)
            except BaseException as exc:   # noqa: BLE001 — a dead worker
                self._die(exc)             # must fail waiters, not hang them
                raise
            finally:
                self.stats["busy"][i] = False

    def _finish(self, req: _Request, out) -> None:
        """Last stage done (runs in the last stage's worker): pick the
        next token, stream it, then complete or re-enter stage 0. The
        eos readback blocks only THIS worker; earlier stages keep
        dispatching other requests."""
        logits = out[:, -1]
        req.rng, sub = jax.random.split(req.rng)
        token = req.pick(logits.astype(jnp.float32), sub)
        req.tokens.append(token)
        with self._lock:
            self.stats["tokens"] += int(token.shape[0])
        if req.on_token is not None:
            req.on_token(len(req.tokens) - 1, token)
        done = len(req.tokens) >= req.new_tokens
        if not done and _expired(req):
            done = True             # deadline passed: cancel mid-flight
        if not done and req.cancel is not None and req.cancel.is_set():
            done = True             # caller gone: free the slot early
        if not done and req.eos_token is not None:
            hit = np.asarray(token) == req.eos_token
            req.rows_done = hit if req.rows_done is None \
                else req.rows_done | hit
            done = bool(req.rows_done.all())
        if done:
            arr = _finalize_tokens(req)
            req.caches = None        # free this request's cache slots
            if self.kv is not None:
                self.kv.release(req)  # ... or its page references
            with self._lock:
                self.results[req.rid] = arr
                self._live.discard(req.rid)
                self.active -= 1
                self._lock.notify_all()
            self._slots.release()
        else:
            self._q[0].put((req, token[:, None], "step"))

    def _die(self, exc: BaseException) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = exc
            self._lock.notify_all()
        # wake submitters blocked on admission so they observe the death
        # — both the slot semaphore and (paged) the page-pool wait
        if self.kv is not None:
            self.kv.pool.close()
        for _ in range(self.max_active):
            self._slots.release()
