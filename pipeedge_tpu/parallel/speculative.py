"""Speculative decoding: a draft pipeline proposes, the target verifies.

NEW capability beyond the reference (whose model list is encoder-only;
SURVEY.md §2.4 — no decode subsystem at all). TPU-first design:

- **Greedy-exact**: output is token-identical to `target.generate(...,
  temperature=0)` for fp caches — verification accepts exactly the draft
  tokens the target itself would have produced, and the first mismatch is
  replaced by the target's own argmax. Acceptance only changes HOW MANY
  target dispatches the sequence costs, never the tokens.
- **Static shapes**: every round runs ONE target `extend()` over a fixed
  (gamma+1)-token span — a single compiled program per attend bucket —
  plus gamma-1 draft single steps and a 1-or-2-token draft catch-up
  span. No data-dependent shapes; acceptance is host-side control flow
  between dispatches, exactly like the pipeline's other host drivers.
- **Batch-safe**: drafts are per-row; a round accepts the MINIMUM
  accepted prefix across rows. Rows that matched deeper simply re-derive
  those tokens next round — greedy is deterministic, so exactness is
  unaffected (this trades a little wasted compute for scalar `pos`
  bookkeeping and static shapes, the TPU-friendly end of the trade).
- **Cache discipline**: rejected proposals leave K/V rows beyond the
  committed position; every such row is overwritten by the next round's
  span write before any query can attend it (the span mask keeps
  k_pos <= q_pos), so rollback is free — the committed position IS the
  rollback state.

The draft can be any pipeline over the same vocabulary (typically a much
smaller model). Speedup = (accepted+1 tokens per verify) vs (1 token per
target step); acceptance depends on draft/target agreement, so the
measured `acceptance_rate` is reported alongside tokens.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .decode import DecodePipeline, validate_capacity

__all__ = ["SpeculativeDecoder"]


class SpeculativeDecoder:
    """Greedy speculative decoding over two `DecodePipeline`s.

    `gamma` is the draft lookahead per round: the draft proposes gamma
    tokens, one target `extend()` scores all of them plus a bonus
    position. gamma is fixed for the whole generation so the verify span
    compiles once per attend bucket.
    """

    def __init__(self, target: DecodePipeline, draft: DecodePipeline,
                 gamma: int = 4):
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                "draft and target must share a vocabulary: "
                f"{draft.cfg.vocab_size} vs {target.cfg.vocab_size}")
        for name, pipe in (("target", target), ("draft", draft)):
            cfg = pipe.cfg
            if cfg.n_experts and cfg.capacity_factor < cfg.n_experts:
                # capacity routing is not per-token: a verify span routes
                # its tokens jointly, which serial decode steps cannot
                # reproduce — the greedy-exact guarantee would not hold
                raise ValueError(
                    f"capacity-bounded MoE {name} breaks the greedy-exact "
                    "guarantee (span routing != per-step routing); use a "
                    "dropless config (capacity_factor >= n_experts)")
        self.target = target
        self.draft = draft
        self.gamma = gamma
        self.last_acceptance_rate: Optional[float] = None

    def precompute_prefix(self, prefix_ids) -> dict:
        """Prompt caching for speculative decoding: prefill the shared
        prefix through BOTH pipelines (each model needs its own K/V) and
        return one handle for `generate(..., prefix=)`."""
        return {"target": self.target.precompute_prefix(prefix_ids),
                "draft": self.draft.precompute_prefix(prefix_ids)}

    def generate(self, ids, new_tokens: int, prefix: Optional[dict] = None):
        """Greedy-decode `new_tokens` continuations of prompt `ids`
        [B, S]; returns [B, S + new_tokens] (prompt included), token-
        identical to `target.generate(ids, new_tokens)` for fp caches.
        Sets `last_acceptance_rate` (accepted drafts / proposed drafts).

        `prefix` (from this decoder's `precompute_prefix`) seeds both
        pipelines with a shared prompt prefix; `ids` is then each
        request's SUFFIX (non-empty), and the returned array omits the
        prefix — matching `DecodePipeline.generate`'s prefix contract."""
        ids = jnp.asarray(ids, jnp.int32)
        batch, suffix_len = ids.shape
        base = prefix["target"]["len"] if prefix else 0
        prompt_len = suffix_len + base
        if prefix is not None:
            # each sub-handle must match ITS pipeline's cache layout
            # (round-4 advice: reject foreign handles before jit)
            self.target.check_prefix(prefix["target"])
            self.draft.check_prefix(prefix["draft"])
            if prefix["draft"]["len"] != base:
                raise ValueError("target/draft prefix lengths differ: "
                                 f"{base} vs {prefix['draft']['len']}")
            if suffix_len == 0:
                raise ValueError("prefix reuse needs a non-empty suffix")
        if new_tokens <= 0:
            return ids
        g = self.gamma
        # worst case writes a full span past the last emitted token
        validate_capacity(self.target.cfg, self.target.max_len,
                          prompt_len, new_tokens + g)
        validate_capacity(self.draft.cfg, self.draft.max_len,
                          prompt_len, new_tokens + g)

        if prefix is None:
            t_out, t_caches = self.target._prefill(ids)
            _, d_caches = self.draft._prefill(ids)
            # the draft has seen the whole prompt; catch-up tokens are
            # all emitted ones
            known = []
        else:
            from .decode import _repeat_batch
            t_caches = [_repeat_batch(c, batch)
                        for c in prefix["target"]["caches"]]
            t_out, t_caches = self.target.extend(ids, t_caches, base)
            d_caches = [_repeat_batch(c, batch)
                        for c in prefix["draft"]["caches"]]
            # the draft has seen only the prefix: its first catch-up
            # span covers the whole suffix too (one transfer, [B] rows)
            known = list(np.asarray(ids, np.int32).T)
        pending = np.asarray(
            jnp.argmax(t_out[:, -1].astype(jnp.float32), -1),
            np.int32)                       # [B] first continuation token
        n_suffix = len(known)    # known = suffix tokens ++ emissions,
        known.append(pending)    # sitting at positions [d_floor, ...)
        d_floor = base if prefix else prompt_len
        n_emitted = 1
        t_pos = prompt_len   # target cache rows [0, t_pos) are committed
        d_pos = d_floor      # draft cache rows [0, d_pos) are committed
        proposed = accepted = 0

        while n_emitted < new_tokens:
            # --- draft: catch up on committed tokens it hasn't seen
            # (suffix+pending on the first prefix-seeded round; then 1
            # token normally, 2 after a fully-accepted round), then
            # propose gamma tokens autoregressively
            catch = np.stack(known[d_pos - d_floor:], axis=1)
            d_logits, d_caches = self.draft.extend(catch, d_caches, d_pos)
            d_pos += catch.shape[1]
            props = [np.asarray(
                jnp.argmax(d_logits[:, -1].astype(jnp.float32), -1),
                np.int32)]
            for _ in range(g - 1):
                d_logits, d_caches = self.draft.extend(
                    props[-1][:, None], d_caches, d_pos)
                props.append(np.asarray(
                    jnp.argmax(d_logits[:, -1].astype(jnp.float32), -1),
                    np.int32))
                d_pos += 1

            # --- target: one span forward scores pending + all proposals
            span = np.stack([pending] + props, axis=1)      # [B, g+1]
            t_logits, t_caches = self.target.extend(span, t_caches, t_pos)
            targets = np.asarray(
                jnp.argmax(t_logits.astype(jnp.float32), -1), np.int32)

            # --- accept the minimum matching prefix across rows
            a = 0
            while a < g and bool(np.all(props[a] == targets[:, a])):
                a += 1
            proposed += g
            accepted += a
            known.extend(props[:a] + [targets[:, a]])  # drafts + correction
            n_emitted += a + 1
            pending = targets[:, a]
            t_pos += a + 1
            # draft rows hold [pending, p1..p_{g-1}] from this round's
            # catch-up+proposals; committed among them: pending..p_a
            d_pos = t_pos - 1 if a == g else t_pos

        self.last_acceptance_rate = accepted / proposed if proposed else None
        gen = jnp.asarray(np.stack(known[n_suffix:n_suffix + new_tokens],
                                   axis=1))
        return jnp.concatenate([ids, gen], axis=1)
