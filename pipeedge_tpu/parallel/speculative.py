"""Speculative decoding: a draft pipeline proposes, the target verifies.

NEW capability beyond the reference (whose model list is encoder-only;
SURVEY.md §2.4 — no decode subsystem at all). TPU-first design:

- **Greedy-exact**: output is token-identical to `target.generate(...,
  temperature=0)` for fp caches — verification accepts exactly the draft
  tokens the target itself would have produced, and the first mismatch is
  replaced by the target's own argmax. Acceptance only changes HOW MANY
  target dispatches the sequence costs, never the tokens.
- **Static shapes**: every round runs ONE target `extend()` over a fixed
  (gamma+1)-token span — a single compiled program per attend bucket —
  plus gamma-1 draft single steps and a 1-or-2-token draft catch-up
  span. No data-dependent shapes; acceptance is host-side control flow
  between dispatches, exactly like the pipeline's other host drivers.
- **Batch-safe**: drafts are per-row; a round accepts the MINIMUM
  accepted prefix across rows. Rows that matched deeper simply re-derive
  those tokens next round — greedy is deterministic, so exactness is
  unaffected (this trades a little wasted compute for scalar `pos`
  bookkeeping and static shapes, the TPU-friendly end of the trade).
- **Cache discipline**: rejected proposals leave K/V rows beyond the
  committed position; every such row is overwritten by the next round's
  span write before any query can attend it (the span mask keeps
  k_pos <= q_pos), so rollback is free — the committed position IS the
  rollback state.

The draft can be any pipeline over the same vocabulary (typically a much
smaller model). Speedup = (accepted+1 tokens per verify) vs (1 token per
target step); acceptance depends on draft/target agreement, so the
measured `acceptance_rate` is reported alongside tokens.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from .decode import DecodePipeline, validate_capacity

__all__ = ["SpeculativeDecoder"]


class SpeculativeDecoder:
    """Greedy speculative decoding over two `DecodePipeline`s.

    `gamma` is the draft lookahead per round: the draft proposes gamma
    tokens, one target `extend()` scores all of them plus a bonus
    position. gamma is fixed for the whole generation so the verify span
    compiles once per attend bucket.
    """

    def __init__(self, target: DecodePipeline, draft: DecodePipeline,
                 gamma: int = 4):
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                "draft and target must share a vocabulary: "
                f"{draft.cfg.vocab_size} vs {target.cfg.vocab_size}")
        for name, pipe in (("target", target), ("draft", draft)):
            cfg = pipe.cfg
            if cfg.n_experts and cfg.capacity_factor < cfg.n_experts:
                # capacity routing is not per-token: a verify span routes
                # its tokens jointly, which serial decode steps cannot
                # reproduce — the greedy-exact guarantee would not hold
                raise ValueError(
                    f"capacity-bounded MoE {name} breaks the greedy-exact "
                    "guarantee (span routing != per-step routing); use a "
                    "dropless config (capacity_factor >= n_experts)")
        self.target = target
        self.draft = draft
        self.gamma = gamma
        self.last_acceptance_rate: Optional[float] = None

    def generate(self, ids, new_tokens: int):
        """Greedy-decode `new_tokens` continuations of prompt `ids`
        [B, S]; returns [B, S + new_tokens] (prompt included), token-
        identical to `target.generate(ids, new_tokens)` for fp caches.
        Sets `last_acceptance_rate` (accepted drafts / proposed drafts)."""
        ids = jnp.asarray(ids, jnp.int32)
        batch, prompt_len = ids.shape
        if new_tokens <= 0:
            return ids
        g = self.gamma
        # worst case writes a full span past the last emitted token
        validate_capacity(self.target.cfg, self.target.max_len,
                          prompt_len, new_tokens + g)
        validate_capacity(self.draft.cfg, self.draft.max_len,
                          prompt_len, new_tokens + g)

        t_out, t_caches = self.target._prefill(ids)
        _, d_caches = self.draft._prefill(ids)
        pending = np.asarray(
            jnp.argmax(t_out[:, prompt_len - 1].astype(jnp.float32), -1),
            np.int32)                       # [B] first continuation token
        out = [pending]                     # committed tokens == ids ++ out
        t_pos = prompt_len   # target cache rows [0, t_pos) are committed
        d_pos = prompt_len   # ditto for the draft
        proposed = accepted = 0

        while len(out) < new_tokens:
            # --- draft: catch up on committed tokens it hasn't seen
            # (1 token normally, 2 after a fully-accepted round; d_pos
            # never falls below prompt_len so the slice stays in `out`),
            # then propose gamma tokens autoregressively
            catch = np.stack(out[d_pos - prompt_len:], axis=1)  # [B, 1|2]
            d_logits, d_caches = self.draft.extend(catch, d_caches, d_pos)
            d_pos += catch.shape[1]
            props = [np.asarray(
                jnp.argmax(d_logits[:, -1].astype(jnp.float32), -1),
                np.int32)]
            for _ in range(g - 1):
                d_logits, d_caches = self.draft.extend(
                    props[-1][:, None], d_caches, d_pos)
                props.append(np.asarray(
                    jnp.argmax(d_logits[:, -1].astype(jnp.float32), -1),
                    np.int32))
                d_pos += 1

            # --- target: one span forward scores pending + all proposals
            span = np.stack([pending] + props, axis=1)      # [B, g+1]
            t_logits, t_caches = self.target.extend(span, t_caches, t_pos)
            targets = np.asarray(
                jnp.argmax(t_logits.astype(jnp.float32), -1), np.int32)

            # --- accept the minimum matching prefix across rows
            a = 0
            while a < g and bool(np.all(props[a] == targets[:, a])):
                a += 1
            proposed += g
            accepted += a
            out.extend(props[:a] + [targets[:, a]])  # drafts + correction
            pending = targets[:, a]
            t_pos += a + 1
            # draft rows hold [pending, p1..p_{g-1}] from this round's
            # catch-up+proposals; committed among them: pending..p_a
            d_pos = t_pos - 1 if a == g else t_pos

        self.last_acceptance_rate = accepted / proposed if proposed else None
        gen = jnp.asarray(np.stack(out[:new_tokens], axis=1))
        return jnp.concatenate([ids, gen], axis=1)
