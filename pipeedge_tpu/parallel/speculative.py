"""Speculative decoding: a draft pipeline proposes, the target verifies.

NEW capability beyond the reference (whose model list is encoder-only;
SURVEY.md §2.4 — no decode subsystem at all). TPU-first design:

- **Greedy-exact**: output is token-identical to `target.generate(...,
  temperature=0)` for fp caches — verification accepts exactly the draft
  tokens the target itself would have produced, and the first mismatch is
  replaced by the target's own argmax. Acceptance only changes HOW MANY
  target dispatches the sequence costs, never the tokens.
- **Static shapes**: every round runs ONE target `extend()` over a fixed
  (gamma+1)-token span — a single compiled program per attend bucket —
  plus gamma-1 draft single steps and a 1-or-2-token draft catch-up
  span. No data-dependent shapes; acceptance is host-side control flow
  between dispatches, exactly like the pipeline's other host drivers.
- **Batch-safe**: drafts are per-row; a round accepts the MINIMUM
  accepted prefix across rows. Rows that matched deeper simply re-derive
  those tokens next round — greedy is deterministic, so exactness is
  unaffected (this trades a little wasted compute for scalar `pos`
  bookkeeping and static shapes, the TPU-friendly end of the trade).
- **Cache discipline**: rejected proposals leave K/V rows beyond the
  committed position; every such row is overwritten by the next round's
  span write before any query can attend it (the span mask keeps
  k_pos <= q_pos), so rollback is free — the committed position IS the
  rollback state.

The draft can be any pipeline over the same vocabulary (typically a much
smaller model). Speedup = (accepted+1 tokens per verify) vs (1 token per
target step); acceptance depends on draft/target agreement, so the
measured `acceptance_rate` is reported alongside tokens.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .decode import DecodePipeline, validate_capacity

__all__ = ["SpeculativeDecoder"]


def _device_rounds_eligible(pipe: DecodePipeline) -> Optional[str]:
    """None if `pipe`'s stage programs can be inlined into ONE jitted
    round program, else the reason they cannot: explicit per-stage device
    placement inserts host-driven transfers between stages (a single XLA
    program is single-(mesh-)device), and tp / tp x ep meshes place
    params+caches with shardings the fused program would have to
    re-specify."""
    if any(st["device"] is not None for st in pipe.stages):
        return "per-stage device placement"
    if pipe.mesh is not None:
        return "tensor-parallel mesh"
    if pipe.ep_mesh is not None:
        return "expert-parallel mesh"
    if pipe.tp_ep_mesh is not None:
        return "tp x ep mesh"
    return None


class SpeculativeDecoder:
    """Greedy speculative decoding over two `DecodePipeline`s.

    `gamma` is the draft lookahead per round: the draft proposes gamma
    tokens, one target `extend()` scores all of them plus a bonus
    position. gamma is fixed for the whole generation so the verify span
    compiles once per attend bucket.

    `sync` picks how many host round trips a round costs:

    - ``"host"``: every draft argmax reads back to the host — g+1
      device round trips per round. On a remote/tunneled chip each
      readback costs a full RTT, which can eat the verify-span win.
    - ``"device"``: the DRAFT side of the round — catch-up span plus
      gamma-1 draft steps, argmax feeding argmax on device — is one
      compiled program returning one packed [B, gamma] proposal array
      (ONE readback); the target verify then runs through the SAME
      compiled stage programs the host mode uses (one more readback for
      its argmax row). TWO syncs per round vs g+1. Token-identical to
      "host": committed tokens are always the target program's own
      greedy continuations (the standard speculative exactness
      argument), and the target program is literally the same compiled
      object in both modes. (A fully-fused round — verify + acceptance
      in the same program, ONE sync — was built and measured on chip:
      inlining the target stages changes XLA fusion, and at bf16 the
      fused verify's argmax flips on near-ties, 16% token divergence on
      random-init logits. Reverted to the draft-only fusion, which is
      numerics-robust by construction; docs/DECODE.md records the
      negative.)
    - ``"auto"`` (default): "device" when the draft pipeline's stage
      programs can legally inline into one jitted program (no per-stage
      device placement, no tp/ep/tp x ep mesh —
      `_device_rounds_eligible`), else "host".

    `last_sync_count` records the host round trips of the latest
    generate() (the chip A/B's measured quantity: docs/DECODE.md).
    """

    def __init__(self, target: DecodePipeline, draft: DecodePipeline,
                 gamma: int = 4, sync: str = "auto",
                 target_kv=None, draft_pool=None):
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        if (target_kv is None) != (draft_pool is None):
            raise ValueError(
                "paged speculative decoding needs BOTH pools: target_kv "
                "(the decode plane's PagedKvBackend) and draft_pool (a "
                "KvPagePool over the draft pipeline)")
        if target.cfg.vocab_size != draft.cfg.vocab_size:
            raise ValueError(
                "draft and target must share a vocabulary: "
                f"{draft.cfg.vocab_size} vs {target.cfg.vocab_size}")
        for name, pipe in (("target", target), ("draft", draft)):
            cfg = pipe.cfg
            if cfg.n_experts and cfg.capacity_factor < cfg.n_experts:
                # capacity routing is not per-token: a verify span routes
                # its tokens jointly, which serial decode steps cannot
                # reproduce — the greedy-exact guarantee would not hold
                raise ValueError(
                    f"capacity-bounded MoE {name} breaks the greedy-exact "
                    "guarantee (span routing != per-step routing); use a "
                    "dropless config (capacity_factor >= n_experts)")
        if sync not in ("auto", "host", "device"):
            raise ValueError(f"sync must be auto/host/device, got {sync!r}")
        # only the DRAFT is fused into one program; the target verify
        # rides its normal stage programs in both modes
        blockers = {name: why for name, pipe in (("draft", draft),)
                    if (why := _device_rounds_eligible(pipe)) is not None}
        if sync == "device" and blockers:
            raise ValueError(
                f"sync='device' unavailable: {blockers} (the draft round "
                "must compile into one program); use sync='auto' or "
                "'host'")
        self.target = target
        self.draft = draft
        self.gamma = gamma
        self.sync = "host" if sync == "auto" and blockers else \
            ("device" if sync == "auto" else sync)
        # paged mode (docs/SERVING.md): draft/verify caches live as
        # page-shaped views over KvPagePools instead of dense max_len
        # slots — speculation's cache residency is charged against the
        # SAME capacity plane as the decode executor's requests (and a
        # separate draft-layout pool), so admission tokens, brownout
        # eviction pressure and the orphan sweep all see it
        self.kv = target_kv
        self.draft_pool = draft_pool
        self._live: set = set()   # owners mid-generate (sweep liveness)
        import itertools
        self._seq = itertools.count()
        self.last_acceptance_rate: Optional[float] = None
        self.last_sync_count: Optional[int] = None
        self._round_cache: dict = {}

    def _draft_round_fn(self, batch: int, catch_len: int, d_read):
        """The compiled device-side DRAFT round (sync='device'): catch-up
        span + gamma-1 proposal steps with argmax feeding argmax on
        device, returning one packed [B, gamma] proposal array. Cached
        per (batch, catch span length, attend bucket) — a handful of
        variants per generation, the same compile-per-discrete-value
        pattern as the attend buckets themselves."""
        key = (batch, catch_len, d_read)
        fn = self._round_cache.get(key)
        if fn is not None:
            return fn
        g = self.gamma
        draft_fns = [st["decode"] for st in self.draft.stages]

        def run_stages(params_list, data, caches, pos):
            out = []
            for fn, p, c in zip(draft_fns, params_list, caches):
                if d_read is None:
                    data, c = fn(p, data, c, pos)
                else:
                    data, c = fn(p, data, c, pos, read_len=d_read)
                out.append(c)
            return data, out

        def greedy(logits):     # [B, V] -> [B] int32, the host rule
            return jnp.argmax(logits.astype(jnp.float32), -1) \
                .astype(jnp.int32)

        # params enter as ARGUMENTS, never closures: a closed-over param
        # pytree would bake the full model weights into the program as
        # constants — the serialized HLO then carries them to the
        # compiler (hundreds of MB; the tunneled compile endpoint
        # rejects it outright)
        @jax.jit
        def draft_round(d_params, d_caches, catch, d_pos):
            # catch-up span over committed-but-unseen tokens ...
            x, d_caches = run_stages(d_params, catch, d_caches, d_pos)
            props = [greedy(x[:, -1])]
            # ... then gamma-1 proposals, argmax feeding argmax ON DEVICE
            for k in range(g - 1):
                x, d_caches = run_stages(d_params, props[-1][:, None],
                                         d_caches,
                                         d_pos + catch_len + k)
                props.append(greedy(x[:, -1]))
            return jnp.stack(props, axis=1), d_caches      # [B, g]

        self._round_cache[key] = draft_round
        return draft_round

    def precompute_prefix(self, prefix_ids) -> dict:
        """Prompt caching for speculative decoding: prefill the shared
        prefix through BOTH pipelines (each model needs its own K/V) and
        return one handle for `generate(..., prefix=)`."""
        return {"target": self.target.precompute_prefix(prefix_ids),
                "draft": self.draft.precompute_prefix(prefix_ids)}

    # -- paged caches (kv/pool.py) ----------------------------------------

    def attach_paged(self, target_kv, draft_pool) -> None:
        """Arm paged mode after construction. The serving layer builds
        the decoder BEFORE the decode plane's PagedKvBackend exists
        (tools/serve.py constructs the backend inside `_Service`), so
        the pools are attached here rather than via `__init__`."""
        if target_kv is None or draft_pool is None:
            raise ValueError("attach_paged needs BOTH target_kv and "
                             "draft_pool (see __init__)")
        self.kv = target_kv
        self.draft_pool = draft_pool

    def live_rids(self) -> set:
        """Owners currently mid-generate. The serving governor unions
        this into the pool sweeps' live set, so a speculative request's
        pages are never taken for orphans while its thread runs."""
        return set(self._live)

    def sweep_orphans(self) -> int:
        """Reclaim DRAFT-pool pages whose generation died between page
        charge and release (the target pool's pages ride the decode
        plane's sweep — tools/serve.py passes `live_rids` into it)."""
        if self.draft_pool is None:
            return 0
        return self.draft_pool.sweep_leaked(lambda: self.live_rids())

    def _alloc_paged(self, owner, batch: int, prompt_len: int,
                     new_tokens: int):
        """Charge pages for one paged generation — target pages from the
        decode plane's pool (speculation competes for the SAME capacity
        as executor requests), draft pages from the draft-layout pool —
        and return the gathered page-shaped working caches. The views
        are `[L, B, pages * page_size, ...]`, shorter than dense
        `max_len` slots: positions past the window are masked to exact
        softmax zeros, so tokens are identical to the dense path
        (kv/backend.py's numerics argument; tests pin it). Speculative
        caches are never shared cross-request, so the pages are held as
        the capacity reservation and the rounds run on the views —
        scatters back to the arena would be dead stores."""
        from ..kv.pool import pages_for
        g = self.gamma
        t_per = self.kv.pages_needed(prompt_len, new_tokens + g)
        dpool = self.draft_pool
        # the draft pool buckets like PagedKvBackend.pages_needed: page
        # spans round up to a power of two so the draft programs compile
        # per bucket, not per exact prompt length
        d_per = pages_for(prompt_len + new_tokens + g, dpool.page_size)
        cap = pages_for(self.draft.max_len, dpool.page_size)
        p2 = 1
        while p2 < d_per:
            p2 *= 2
        d_per = min(p2, cap)
        t_rows: list = []
        d_rows: list = []
        try:
            for _ in range(batch):
                t_rows.append(self.kv.pool.alloc(t_per))
            for _ in range(batch):
                d_rows.append(dpool.alloc(d_per))
        except BaseException:
            for row in t_rows:
                self.kv.pool.release(row)
            for row in d_rows:
                dpool.release(row)
            raise
        # ledger adoption: a thread that dies past this point is
        # reclaimable by the orphan sweeps (owner is in _live already,
        # so a concurrent sweep cannot take the pages for dead)
        self.kv.pool.adopt(owner, [p for row in t_rows for p in row])
        dpool.adopt(owner, [p for row in d_rows for p in row])
        t_table = np.asarray(t_rows, np.int32)
        d_table = np.asarray(d_rows, np.int32)
        with self.kv._arena_lock:
            t_caches = [self.kv.pool.gather(i, t_table)
                        for i in range(len(self.target.stages))]
        d_caches = [dpool.gather(i, d_table)
                    for i in range(len(self.draft.stages))]
        return t_caches, d_caches

    def _release_paged(self, owner) -> None:
        """Drop both pools' page references (claim-then-release through
        the owner ledgers, so the release path and the orphan sweeps
        race benignly) and delist the owner."""
        pids = self.kv.pool.disown(owner)
        if pids is not None:
            self.kv.pool.release(pids)
        pids = self.draft_pool.disown(owner)
        if pids is not None:
            self.draft_pool.release(pids)
        self._live.discard(owner)

    def generate(self, ids, new_tokens: int, prefix: Optional[dict] = None,
                 rid=None):
        """Greedy-decode `new_tokens` continuations of prompt `ids`
        [B, S]; returns [B, S + new_tokens] (prompt included), token-
        identical to `target.generate(ids, new_tokens)` for fp caches.
        Sets `last_acceptance_rate` (accepted drafts / proposed drafts).

        `prefix` (from this decoder's `precompute_prefix`) seeds both
        pipelines with a shared prompt prefix; `ids` is then each
        request's SUFFIX (non-empty), and the returned array omits the
        prefix — matching `DecodePipeline.generate`'s prefix contract.

        In paged mode (`target_kv`/`draft_pool` set) the caches are
        page-shaped views over the pools instead of dense slots —
        token-identical — and `rid` names the page owner in the pools'
        ledgers (defaults to a fresh unique id)."""
        ids = jnp.asarray(ids, jnp.int32)
        batch, suffix_len = ids.shape
        base = prefix["target"]["len"] if prefix else 0
        prompt_len = suffix_len + base
        if prefix is not None:
            # each sub-handle must match ITS pipeline's cache layout
            # (round-4 advice: reject foreign handles before jit)
            self.target.check_prefix(prefix["target"])
            self.draft.check_prefix(prefix["draft"])
            if prefix["draft"]["len"] != base:
                raise ValueError("target/draft prefix lengths differ: "
                                 f"{base} vs {prefix['draft']['len']}")
            if suffix_len == 0:
                raise ValueError("prefix reuse needs a non-empty suffix")
        if new_tokens <= 0:
            return ids
        if self.kv is not None and prefix is not None:
            raise ValueError(
                "paged speculative decoding replaces dense prefix "
                "handles (the serving layer expands prefixes into "
                "prompt tokens); submit the full prompt instead")
        g = self.gamma
        # worst case writes a full span past the last emitted token
        validate_capacity(self.target.cfg, self.target.max_len,
                          prompt_len, new_tokens + g)
        validate_capacity(self.draft.cfg, self.draft.max_len,
                          prompt_len, new_tokens + g)

        owner = None
        try:
            if self.kv is not None:
                owner = str(rid) if rid is not None \
                    else f"spec{next(self._seq)}"
                self._live.add(owner)
                t_caches, d_caches = self._alloc_paged(
                    owner, batch, prompt_len, new_tokens)
                # the prompt pass runs as a span at offset 0 over the
                # page-shaped views — token-identical to _prefill (the
                # same masking rule chunked prefill relies on)
                t_out, t_caches = self.target.extend(ids, t_caches, 0)
                _, d_caches = self.draft.extend(ids, d_caches, 0)
                known = []
            elif prefix is None:
                t_out, t_caches = self.target._prefill(ids)
                _, d_caches = self.draft._prefill(ids)
                # the draft has seen the whole prompt; catch-up tokens
                # are all emitted ones
                known = []
            else:
                from .decode import _repeat_batch
                t_caches = [_repeat_batch(c, batch)
                            for c in prefix["target"]["caches"]]
                t_out, t_caches = self.target.extend(ids, t_caches, base)
                d_caches = [_repeat_batch(c, batch)
                            for c in prefix["draft"]["caches"]]
                # the draft has seen only the prefix: its first catch-up
                # span covers the whole suffix too (one transfer, [B]
                # rows)
                known = list(np.asarray(ids, np.int32).T)
            return self._rounds(ids, new_tokens, t_out, t_caches,
                                d_caches, known, base, prompt_len,
                                bool(prefix))
        finally:
            if owner is not None:
                self._release_paged(owner)

    def _rounds(self, ids, new_tokens: int, t_out, t_caches, d_caches,
                known: list, base: int, prompt_len: int,
                prefixed: bool):
        """The draft-propose / target-verify loop (seeding done): shared
        verbatim by the dense, prefix-seeded and paged cache paths."""
        g = self.gamma
        batch = ids.shape[0]
        pending = np.asarray(
            jnp.argmax(t_out[:, -1].astype(jnp.float32), -1),
            np.int32)                       # [B] first continuation token
        syncs = 1                           # the first-token readback
        n_suffix = len(known)    # known = suffix tokens ++ emissions,
        known.append(pending)    # sitting at positions [d_floor, ...)
        d_floor = base if prefixed else prompt_len
        n_emitted = 1
        t_pos = prompt_len   # target cache rows [0, t_pos) are committed
        d_pos = d_floor      # draft cache rows [0, d_pos) are committed
        proposed = accepted = 0
        device_rounds = self.sync == "device"

        while n_emitted < new_tokens:
            # --- draft: catch up on committed tokens it hasn't seen
            # (suffix+pending on the first prefix-seeded round; then 1
            # token normally, 2 after a fully-accepted round), then
            # propose gamma tokens autoregressively
            catch = np.stack(known[d_pos - d_floor:], axis=1)
            if device_rounds:
                # the draft side in ONE program, one packed readback:
                # the attend bucket for the round's deepest draft
                # position is chosen host-side (positions are host
                # bookkeeping, never read back) and bound statically;
                # earlier in-round steps attending through the wider
                # bucket is numerically identical (extra positions are
                # masked). The target verify below uses the SAME
                # compiled stage programs as sync='host', so tokens
                # cannot diverge between modes.
                c_len = catch.shape[1]
                draft_round = self._draft_round_fn(
                    batch, c_len,
                    self.draft._read_len(d_pos, c_len + g - 1))
                props_arr, d_caches = draft_round(
                    [st["params"] for st in self.draft.stages],
                    d_caches, jnp.asarray(catch), d_pos)
                props_arr = np.asarray(props_arr, np.int32)    # sync 1
                syncs += 1
                props = [props_arr[:, k] for k in range(g)]
                # (d_pos is reconciled from `a` at the end of the loop)
            else:
                d_logits, d_caches = self.draft.extend(catch, d_caches,
                                                       d_pos)
                d_pos += catch.shape[1]
                props = [np.asarray(
                    jnp.argmax(d_logits[:, -1].astype(jnp.float32), -1),
                    np.int32)]
                syncs += 1
                for _ in range(g - 1):
                    d_logits, d_caches = self.draft.extend(
                        props[-1][:, None], d_caches, d_pos)
                    props.append(np.asarray(
                        jnp.argmax(d_logits[:, -1].astype(jnp.float32), -1),
                        np.int32))
                    syncs += 1
                    d_pos += 1

            # --- target: one span forward scores pending + proposals —
            # THE SAME compiled stage programs in both sync modes, the
            # token-identity anchor
            span = np.stack([pending] + props, axis=1)        # [B, g+1]
            t_logits, t_caches = self.target.extend(span, t_caches,
                                                    t_pos)
            targets = np.asarray(
                jnp.argmax(t_logits.astype(jnp.float32), -1), np.int32)
            syncs += 1

            # --- accept the minimum matching prefix across rows
            a = 0
            while a < g and bool(np.all(props[a] == targets[:, a])):
                a += 1
            proposed += g
            accepted += a
            known.extend(props[:a] + [targets[:, a]])  # drafts + correction
            n_emitted += a + 1
            pending = targets[:, a]
            t_pos += a + 1
            # draft rows hold [pending, p1..p_{g-1}] from this round's
            # catch-up+proposals; committed among them: pending..p_a
            d_pos = t_pos - 1 if a == g else t_pos

        self.last_acceptance_rate = accepted / proposed if proposed else None
        self.last_sync_count = syncs
        gen = jnp.asarray(np.stack(known[n_suffix:n_suffix + new_tokens],
                                   axis=1))
        return jnp.concatenate([ids, gen], axis=1)
