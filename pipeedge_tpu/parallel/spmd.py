"""SPMD pipeline: the whole stage graph as ONE jitted program over a mesh.

The performance path (SURVEY.md §5.8, §7 step 3b). Where the host-driven
driver dispatches per-stage programs with device_put edges, this compiles the
*entire* pipeline — all stages, all microbatches — into a single XLA program
under `shard_map` over a `jax.sharding.Mesh`:

- mesh axes ('dp', 'stage'): 'stage' is the pipeline axis (the reference's
  rank, comm/p2p), 'dp' optionally shards the microbatch dimension (data
  parallelism within a stage — absent in the reference, SURVEY.md §2.4).
- Each device holds only its own stage's transformer blocks (parameters are
  stage-sharded; stages with fewer blocks are zero-padded and masked).
- One `lax.scan` over T = n_microbatches + n_stages - 1 "ticks" runs the
  fill/steady/drain schedule; the inter-stage edge is `lax.ppermute` over ICI
  — the collective-permute equivalent of the reference's gloo send/recv
  threads (p2p:155-258), with zero host involvement in steady state.
- Quantized edges: the payload is encoded to packed uint32 before the
  ppermute and decoded after, so only 32/bit of the activation bytes cross
  the interconnect (QuantPipe on the wire, reference runtime.py:73-119).

Constraints vs the host-driven path: partitions must be block-aligned (each
stage = whole transformer blocks). Mid-block (sublayer) cuts stream a 2-tuple
payload with shapes that differ per cut point, which would break the single
SPMD program; the host-driven driver handles those (SURVEY.md §7 hard parts).
"""
from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..utils import jax_compat
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import block_slices
from ..models.layers import TransformerConfig
from ..models.shard import FamilySpec, stack_blocks
from ..ops import fused_quant
from ..ops import quant as quant_ops

logger = logging.getLogger(__name__)

BlockRange = Tuple[int, int]


def partition_to_blocks(partition: Sequence[Tuple[int, int]]) -> List[BlockRange]:
    """Convert a sublayer partition to 0-based block ranges; reject mid-block cuts."""
    out = []
    for layer_start, layer_end in partition:
        slices = block_slices(layer_start, layer_end)
        if not all(s.is_full for s in slices):
            raise ValueError(
                f"SPMD pipeline requires block-aligned partitions; "
                f"[{layer_start}, {layer_end}] cuts mid-block (use the "
                f"host-driven pipeline for sublayer cuts)")
        out.append((slices[0].block_id, slices[-1].block_id))
    return out


def _pad_stack(stage_blocks: List[Any], max_b: int):
    """Stack per-stage block pytrees [n_i, ...] into [n_stages, max_b, ...]."""
    def pad(leaf):
        pad_width = [(0, max_b - leaf.shape[0])] + [(0, 0)] * (leaf.ndim - 1)
        return jnp.pad(leaf, pad_width)

    padded = [jax.tree_util.tree_map(pad, b) for b in stage_blocks]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *padded)


def _raw_words(n_values: int, itemsize: int) -> int:
    """uint32 words to carry `n_values` raw elements of `itemsize` bytes."""
    return -(-n_values * itemsize // 4)


def _bitcast_to_words(h: jax.Array) -> jax.Array:
    """[B, ...] -> [B, words] uint32 view of the raw payload (bit=0 edges in
    a mixed-bitwidth wire format)."""
    b = h.shape[0]
    flat = h.reshape(b, -1)
    if h.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(flat, jnp.uint32)
    if h.dtype == jnp.bfloat16:
        u16 = jax.lax.bitcast_convert_type(flat, jnp.uint16)
        return jax.lax.bitcast_convert_type(u16.reshape(b, -1, 2), jnp.uint32)
    raise TypeError(f"unsupported raw edge dtype {h.dtype}")


def _bitcast_from_words(words: jax.Array, shape, dtype) -> jax.Array:
    """Inverse of `_bitcast_to_words` for the leading [B, words] block."""
    b = shape[0]
    n = int(np.prod(shape[1:]))
    if dtype == jnp.float32:
        flat = jax.lax.bitcast_convert_type(words[:, :n], jnp.float32)
    elif dtype == jnp.bfloat16:
        u16 = jax.lax.bitcast_convert_type(words[:, :n // 2], jnp.uint16)
        flat = jax.lax.bitcast_convert_type(u16, jnp.bfloat16).reshape(b, -1)
    else:
        raise TypeError(f"unsupported raw edge dtype {dtype}")
    return flat.reshape(shape)


def _stacked_block_specs(cfg, blocks_tree, tp: int):
    """Partition specs for the stacked block params [n_stages, max_b, ...]:
    stage-sharded on the leading axis, and — when the mesh has a 'tp' axis —
    Megatron column/row sharded on the kernel dims per the SAME family spec
    tables the TP block bodies compile against (parallel/tensor.py)."""
    if tp <= 1:
        return jax.tree_util.tree_map(lambda _: P("stage"), blocks_tree)
    from .tensor import family_tp_plan
    table, _ = family_tp_plan(cfg)
    return jax.tree_util.tree_map(
        lambda _, s: P(*(("stage", None) + tuple(s))), blocks_tree, table)


@dataclasses.dataclass
class SpmdPipeline:
    """Compiled SPMD pipeline over a ('dp', 'stage') mesh.

    Build with `build_spmd_pipeline`. Call `run(inputs)` with a stacked
    microbatch array [M, B, ...raw input dims...]; returns [M, B, ...out...].

    `stage_bits[i]` quantizes the edge leaving stage i (reference `-q`
    per-stage semantics, runtime.py:652-656). Uniform bits compile to the
    direct QuantizedTensor edge; mixed bits compile to a `lax.switch` over
    per-bitwidth encoders writing one uniform padded uint32 wire buffer —
    shapes must be identical across devices in an SPMD program, so the
    buffer is sized for the widest edge and each stage's branch zero-pads.
    """
    family: FamilySpec
    cfg: TransformerConfig
    mesh: Mesh
    n_stages: int
    max_blocks: int
    params: Dict            # {'embed', 'final', 'blocks', 'n_blocks'}
    stage_bits: Tuple[int, ...] = (0,)
    sp_kind: str = "ring"   # sp attention core: 'ring' | 'ulysses'
    remat: bool = False     # checkpoint each block (training memory)
    _compiled: Dict[Tuple, Any] = dataclasses.field(default_factory=dict)

    @property
    def quant_bit(self) -> int:
        """Uniform edge bitwidth (0 when edges are mixed) — legacy accessor."""
        bits = set(self.stage_bits[:-1] or (0,))
        return next(iter(bits)) if len(bits) == 1 else 0

    def compiled_for(self, inputs: jax.Array):
        """The param-explicit compiled program `fn(params, inputs)` for
        this input shape (cached per shape/dtype/edge-bits) — the public
        handle `run()`, the training step, and tests share."""
        from .tensor import get_tp_quant_bits
        # the intra-stage collective bitwidth is a trace-time flag
        # (tensor.set_tp_quant_bits): keying the cache on it makes a
        # flag flip rebuild instead of silently reusing the stale trace
        key = (inputs.shape, str(inputs.dtype), self.stage_bits,
               get_tp_quant_bits())
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(inputs)
            self._compiled[key] = fn
        return fn

    def run(self, inputs: jax.Array) -> jax.Array:
        fn = self.compiled_for(inputs)
        dp_spec = "dp" if self.mesh.shape.get("dp", 1) > 1 else None
        inputs = jax.device_put(inputs, NamedSharding(self.mesh, P(None, dp_spec)))
        return fn(self.params, inputs)

    # -- program construction ------------------------------------------

    def _build(self, inputs: jax.Array):
        family, cfg = self.family, self.cfg
        n_stages, max_b = self.n_stages, self.max_blocks
        mesh = self.mesh
        n_ubatch = inputs.shape[0]
        n_ticks = n_ubatch + n_stages - 1
        dp = mesh.shape.get("dp", 1)

        sp = mesh.shape.get("sp", 1)
        # intra-stage collective bitwidth, pinned for THIS trace (the
        # compile cache key carries it, so a later flag flip retraces)
        from .tensor import get_tp_quant_bits
        collective_bits = get_tp_quant_bits()

        # trace shapes: embedded hidden + final output
        embed_shape = jax.eval_shape(
            partial(family.embed, cfg=cfg), self.params["embed"], inputs[0])
        b_local = embed_shape.shape[0] // dp
        seq_total = embed_shape.shape[1]
        if seq_total % sp:
            raise ValueError(f"sequence length {seq_total} must divide by "
                             f"the sp mesh axis ({sp})")
        s_local = seq_total // sp
        # per-device hidden: sequence-sharded over 'sp' (stage edges then
        # carry only the local chunk — sequence-parallel pipeline comm)
        hidden_local = jax.ShapeDtypeStruct(
            (b_local, s_local) + embed_shape.shape[2:], embed_shape.dtype)
        # finalize consumes the FULL sequence (CLS token / pooler): under sp
        # the last stage all-gathers the chunks first
        out_shape = jax.eval_shape(
            partial(family.finalize, cfg=cfg), self.params["final"],
            jnp.zeros((b_local, seq_total) + embed_shape.shape[2:],
                      embed_shape.dtype))

        tp = mesh.shape.get("tp", 1)
        if tp > 1 and sp > 1:
            raise ValueError("tp and sp mesh axes are mutually exclusive "
                             "(Megatron TP assumes a full local sequence)")
        if tp > 1:
            # Megatron block body: kernels arrive as local column/row slices
            # (see the placement specs in build_spmd_pipeline), two psums
            # over 'tp' per block — pp x dp x tp in ONE compiled program
            from .tensor import family_tp_plan
            _, tp_local = family_tp_plan(cfg)

            def block_apply(bp, x):
                return tp_local(bp, x, cfg, "tp")
        elif sp > 1:
            # sequence-parallel block body: activations stay sequence-
            # sharded [b, S/sp, D]; every sublayer is token-local except
            # the attention core, which runs as the exact sp core selected
            # by sp_kind (ring ppermute streaming or Ulysses all-to-all —
            # parallel/sequence.py::resolve_sp_core)
            from ..models.layers import self_attention
            from .sequence import resolve_sp_core
            core = partial(resolve_sp_core(self.sp_kind,
                                           cfg.num_attention_heads, sp),
                           axis_name="sp")

            def sp_attention(qkv, x, num_heads, causal=False):
                # reuse the family projection code; only the core changes
                # (ring/Ulysses cores handle causal masking themselves)
                c = partial(core, causal=True) if causal else core
                return self_attention(qkv, x, num_heads, core_fn=c)

            def block_apply(bp, x):
                for sub in range(4):
                    x = family.sublayer(bp, sub, x, cfg,
                                        attention_fn=sp_attention)
                return x
        else:
            def block_apply(bp, x):
                for sub in range(4):
                    x = family.sublayer(bp, sub, x, cfg)
                return x

        if self.remat:
            # rematerialize per BLOCK under jax.grad: the backward saves
            # only block-boundary activations and recomputes the sublayer
            # intermediates — without this, training ViT-L on one chip
            # needs ~40 GB of tick activations vs ~16 GB HBM (measured);
            # a no-op for inference (no grad, nothing to save)
            block_apply = jax.checkpoint(block_apply)

        def run_blocks(blocks, n_valid, x):
            def step(carry, xs):
                bp, j = xs
                out = jax.lax.cond(j < n_valid, lambda c: block_apply(bp, c),
                                   lambda c: c, carry)
                return out, None

            x, _ = jax.lax.scan(step, x, (blocks, jnp.arange(max_b)))
            return x

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        # -- edge codec: uniform bitwidth (direct) or mixed (lax.switch over
        #    a uniform padded uint32 wire buffer; SPMD shapes must match
        #    across devices, so the buffer is sized for the widest edge) ----
        edge_bits = tuple(self.stage_bits[i] for i in range(n_stages - 1))
        uniform = len(set(edge_bits)) <= 1
        if uniform:
            quant_bit = edge_bits[0] if edge_bits else 0

            def encode(h, stage):
                if quant_bit == 0:
                    return h
                # fused Pallas epilogue when enabled (ops/fused_quant.py):
                # the encode rides the stage's last matmul instead of a
                # separate XLA fusion — bit-identical either way
                return fused_quant.encode_outerdim(h, quant_bit)

            def decode(e, stage):
                if quant_bit == 0:
                    return e
                return fused_quant.decode_outerdim(e)

            def zero_carry(dt=None):
                return encode(jnp.zeros(hidden_local.shape,
                                        dt or hidden_local.dtype), 0)
        else:
            n_vals = int(np.prod(hidden_local.shape[1:]))
            itemsize = jnp.dtype(hidden_local.dtype).itemsize
            distinct = sorted(set(edge_bits))
            words_for = {
                wb: (quant_ops.packed_words(n_vals, wb) if wb > 0
                     else _raw_words(n_vals, itemsize)) for wb in distinct}
            max_words = max(words_for.values())

            def make_enc(wb):
                def enc(h):
                    if wb == 0:
                        data = _bitcast_to_words(h)
                        scale = jnp.ones((b_local,), jnp.float32)
                        shift = jnp.zeros((b_local,), jnp.float32)
                    else:
                        q = fused_quant.encode_outerdim(h, wb)
                        data, scale, shift = q.data, q.scale, q.shift
                    pad = max_words - data.shape[1]
                    if pad:
                        data = jnp.pad(data, ((0, 0), (0, pad)))
                    return data, scale, shift
                return enc

            def make_dec(wb):
                def dec(payload):
                    data, scale, shift = payload
                    if wb == 0:
                        return _bitcast_from_words(
                            data, hidden_local.shape, hidden_local.dtype)
                    q = quant_ops.QuantizedTensor(
                        data=data[:, :words_for[wb]], scale=scale, shift=shift,
                        shape=hidden_local.shape, bit=wb)
                    return fused_quant.decode_outerdim(q).astype(
                        hidden_local.dtype)
                return dec

            enc_branches = [make_enc(wb) for wb in distinct]
            dec_branches = [make_dec(wb) for wb in distinct]
            # stage i's OUT edge uses edge_bits[i]; its IN edge uses
            # edge_bits[i-1] (clamped: stage 0's in-edge / the last stage's
            # out-edge values are never consumed)
            out_branch = jnp.asarray(
                [distinct.index(edge_bits[min(i, n_stages - 2)])
                 for i in range(n_stages)], jnp.int32)
            in_branch = jnp.asarray(
                [distinct.index(edge_bits[max(i - 1, 0)])
                 for i in range(n_stages)], jnp.int32)

            def encode(h, stage):
                return jax.lax.switch(out_branch[stage], enc_branches, h)

            def decode(payload, stage):
                return jax.lax.switch(in_branch[stage], dec_branches, payload)

            def zero_carry(dt=None):
                del dt   # the mixed-bits wire buffer is dtype-fixed
                return (jnp.zeros((b_local, max_words), jnp.uint32),
                        jnp.zeros((b_local,), jnp.float32),
                        jnp.zeros((b_local,), jnp.float32))

        def permute_payload(payload):
            if n_stages == 1:
                return payload
            return jax.tree_util.tree_map(
                lambda t: jax.lax.ppermute(t, "stage", fwd_perm), payload)

        def spmd_body(params, stacked_inputs):
            # local views: blocks [1, max_b, ...] (stage-sharded), inputs
            # [M, B/dp, ...] (dp-sharded), embed/final replicated
            blocks = jax.tree_util.tree_map(lambda x: x[0], params["blocks"])
            n_valid = params["n_blocks"][0]
            stage = jax.lax.axis_index("stage")
            is_first = stage == 0
            is_last = stage == n_stages - 1

            # activation dtype follows THIS call's params/inputs, not the
            # build-time pipeline params: the training step's mixed-
            # precision mode runs this same program on a bfloat16 cast of
            # the float32 masters, so the zeros branches and the scan
            # carry must match the cast, not the masters
            act_dtype = jax.eval_shape(
                partial(family.embed, cfg=cfg), params["embed"],
                stacked_inputs[0]).dtype

            # Embeddings for all microbatches — computed only on the first
            # stage (runtime branch on the device-local stage index); other
            # stages carry zeros of the same shape.
            def do_embed(si):
                return jax.vmap(
                    lambda u: family.embed(params["embed"], u, cfg))(si)

            if sp > 1:
                # Long-context memory: pre-embedding all M microbatches at
                # FULL sequence would give stage 0 an [M, b, S, D] buffer —
                # exactly the scaling sp sheds. Instead embed one microbatch
                # per tick (inside `tick` below, stage 0 only) and keep the
                # local chunk. Trade: embed joins stage 0's tick latency
                # (small vs a stage of blocks); the full-seq [b, S, D]
                # intermediate is transient.
                sp_idx = jax.lax.axis_index("sp")

                def embed_chunk(si_u):
                    full = family.embed(params["embed"], si_u, cfg)
                    return jax.lax.dynamic_slice_in_dim(
                        full, sp_idx * s_local, s_local, axis=1)

                def embed_at(t):
                    return jax.lax.cond(
                        is_first,
                        lambda u: embed_chunk(u),
                        lambda u: jnp.zeros(hidden_local.shape,
                                            act_dtype),
                        stacked_inputs[t])
            else:
                embedded = jax.lax.cond(
                    is_first, do_embed,
                    lambda si: jnp.zeros(
                        (n_ubatch, b_local, seq_total)
                        + embed_shape.shape[2:],
                        act_dtype), stacked_inputs)

                def embed_at(t):
                    return embedded[t]

            outputs0 = jnp.zeros((n_ubatch,) + out_shape.shape, out_shape.dtype)

            def tick(carry, t):
                prev_enc, outputs = carry
                recv = decode(permute_payload(prev_enc), stage)
                in_idx = jnp.clip(t, 0, n_ubatch - 1)
                x = jnp.where(is_first, embed_at(in_idx), recv)
                # Every stage runs its blocks every tick, including fill
                # ticks (garbage in-flight) and drain ticks (stage 0 on a
                # clamped stale input). This is deliberate: ticks are
                # lockstep across the stage axis and some stage does valid
                # work in every tick, so gating invalid stages (lax.cond)
                # cannot shorten any tick — it would only spend the saved
                # FLOPs on idle waiting at the same wall-clock.
                h = run_blocks(blocks, n_valid, x)
                out_idx = t - (n_stages - 1)

                def fin(hh):
                    if sp > 1:
                        # pooler/classifier reads the full sequence (CLS at
                        # position 0 lives on sp rank 0): gather the chunks
                        # — quantized over ICI when --tp-quant-bits is set
                        # (ops/qcollectives.py), exact otherwise
                        if collective_bits:
                            from ..ops import qcollectives
                            hh = qcollectives.qall_gather(
                                hh, "sp", collective_bits, axis=1, tiled=True)
                        else:
                            hh = jax.lax.all_gather(hh, "sp", axis=1,
                                                    tiled=True)
                    return family.finalize(params["final"], hh, cfg).astype(
                        out_shape.dtype)

                # classifier head/pooler only on the last stage — for
                # ViT-Huge's 21843-way head that is a real matmul per tick
                logits = jax.lax.cond(
                    is_last, fin,
                    lambda hh: jnp.zeros(out_shape.shape, out_shape.dtype), h)
                updated = jax.lax.dynamic_update_slice(
                    outputs, logits[None].astype(outputs.dtype),
                    (jnp.clip(out_idx, 0, n_ubatch - 1),)
                    + (0,) * len(out_shape.shape))
                valid = jnp.logical_and(out_idx >= 0, is_last)
                outputs = jnp.where(valid, updated, outputs)
                return (encode(h, stage), outputs), None

            (_, outputs), _ = jax.lax.scan(
                tick, (zero_carry(act_dtype), outputs0), jnp.arange(n_ticks))
            # only the last stage wrote real outputs; fan them back out
            return jax.lax.psum(outputs, "stage")

        dp_spec = "dp" if dp > 1 else None
        in_specs = (
            {
                "embed": P(),
                "final": P(),
                "blocks": _stacked_block_specs(cfg, self.params["blocks"],
                                               tp),
                "n_blocks": P("stage"),
            },
            P(None, dp_spec),
        )
        out_spec = P(None, dp_spec)
        fn = jax.jit(jax_compat.shard_map(spmd_body, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_spec))
        return fn


def build_spmd_pipeline(family: FamilySpec, cfg: TransformerConfig,
                        partition: Sequence[Tuple[int, int]],
                        stage_params: Sequence[Dict], mesh: Mesh,
                        quant_bit=0, sp_kind: str = "ring",
                        remat: bool = False) -> SpmdPipeline:
    """Assemble an `SpmdPipeline` from per-stage shard parameter pytrees.

    `stage_params[i]` is the pytree built by a family loader for stage i's
    `ShardConfig` (block-aligned). Stage 0 must carry 'embeddings', the last
    stage 'final'; per-stage 'blocks' stacks are zero-padded to the deepest
    stage and masked at run time.

    `quant_bit`: an int applied to every inter-stage edge, or a per-stage
    sequence where entry i quantizes the edge leaving stage i (reference
    `-q` list semantics, runtime.py:652-656; the final entry is the result
    edge and is forced to 0).
    """
    n_stages = len(partition)
    if isinstance(quant_bit, (list, tuple)):
        if len(quant_bit) != n_stages:
            raise ValueError(f"quant_bit list length {len(quant_bit)} != "
                             f"{n_stages} stages")
        stage_bits = tuple(int(b) for b in quant_bit[:-1]) + (0,)
    else:
        stage_bits = (int(quant_bit),) * max(n_stages - 1, 0) + (0,)
    if mesh.shape["stage"] != n_stages:
        raise ValueError(f"mesh 'stage' axis {mesh.shape['stage']} != "
                         f"{n_stages} pipeline stages")
    partition_to_blocks(partition)  # validates block alignment

    blocks_list = []
    n_blocks = []
    for i, p in enumerate(stage_params):
        if "blocks" not in p:
            raise ValueError(f"stage {i} has no full blocks; SPMD pipeline "
                             f"requires block-aligned partitions")
        if isinstance(p["blocks"], (tuple, list)):
            raise ValueError(
                f"stage {i} params use the unrolled (tuple) block layout; "
                "the SPMD pipeline stacks blocks across the stage axis — "
                "build stage params with module_shard_factory(..., "
                "unroll=False) or family loaders directly")
        blocks_list.append(p["blocks"])
        n_blocks.append(jax.tree_util.tree_leaves(p["blocks"])[0].shape[0])
    max_b = max(n_blocks)
    nonzero = [b for b in stage_bits[:-1] if b > 0]
    if nonzero and any(b == 0 for b in stage_bits[:-1]):
        logger.warning(
            "SPMD per-stage quant bits %s mix raw (0) and quantized edges: "
            "the uniform SPMD wire buffer is padded to the raw edge's size, "
            "so quantized edges save no interconnect bandwidth in this "
            "configuration (quantization error still applies)", stage_bits)

    tp = mesh.shape.get("tp", 1)
    if tp > 1:
        if cfg.num_attention_heads % tp or cfg.intermediate_size % tp \
                or cfg.kv_heads % tp:
            raise ValueError(
                f"mesh tp={tp} must divide attention heads "
                f"({cfg.num_attention_heads}), kv heads ({cfg.kv_heads}), "
                f"and intermediate size ({cfg.intermediate_size})")
    if cfg.n_experts and (tp > 1 or mesh.shape.get("sp", 1) > 1):
        # tp: expert kernels shard over 'ep', not the Megatron table;
        # sp: routing over a local sequence chunk changes the capacity
        # semantics (per-chunk instead of global top-C) — refuse rather
        # than silently compute something different from the oracle
        raise NotImplementedError(
            "MoE blocks do not compose with the 'tp'/'sp' mesh axes")
    params = {
        "embed": stage_params[0]["embeddings"],
        "final": stage_params[-1]["final"],
        "blocks": _pad_stack(blocks_list, max_b),
        "n_blocks": jnp.asarray(n_blocks, jnp.int32),
    }
    # place parameters: blocks stage-sharded (and Megatron tp-sharded when
    # the mesh has a tp axis), embed/final replicated
    block_specs = _stacked_block_specs(cfg, params["blocks"], tp)
    params = {
        "embed": jax.device_put(params["embed"],
                                NamedSharding(mesh, P())),
        "final": jax.device_put(params["final"], NamedSharding(mesh, P())),
        "blocks": jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params["blocks"], block_specs),
        "n_blocks": jax.device_put(params["n_blocks"],
                                   NamedSharding(mesh, P("stage"))),
    }
    return SpmdPipeline(family=family, cfg=cfg, mesh=mesh, n_stages=n_stages,
                        max_blocks=max_b, params=params,
                        stage_bits=stage_bits, sp_kind=sp_kind,
                        remat=remat)


def make_pipeline_mesh(n_stages: int, dp: int = 1, tp: int = 1, sp: int = 1,
                       devices: Optional[Sequence[jax.Device]] = None,
                       stage_ranks: Optional[Sequence[int]] = None) -> Mesh:
    """Build a ('dp', 'stage'[, 'tp'|'sp']) mesh: the within-stage axis (tp
    Megatron sharding or sp ring attention) innermost — its per-block
    collectives ride adjacent ICI links — stage next (ppermute edges ride
    neighboring links). tp and sp are mutually exclusive.

    `stage_ranks[i]` places stage i on `devices[stage_ranks[i]]` (reference
    `-r` rank-order semantics, runtime.py:657-687); requires dp=tp=sp=1 and
    distinct ranks.
    """
    if tp > 1 and sp > 1:
        raise ValueError("tp and sp mesh axes are mutually exclusive")
    if devices is None:
        devices = jax.devices()
    if stage_ranks is not None:
        if dp != 1 or tp != 1 or sp != 1:
            raise ValueError("stage_ranks requires dp=1, tp=1 and sp=1")
        if len(stage_ranks) != n_stages:
            raise ValueError(f"stage_ranks length {len(stage_ranks)} != "
                             f"{n_stages} stages")
        if len(set(stage_ranks)) != n_stages:
            raise ValueError(f"stage_ranks must be distinct: {stage_ranks}")
        if max(stage_ranks) >= len(devices):
            raise ValueError(f"stage rank {max(stage_ranks)} out of range "
                             f"({len(devices)} devices)")
        arr = np.asarray([devices[r] for r in stage_ranks]).reshape(1, n_stages)
        return Mesh(arr, ("dp", "stage"))
    inner, inner_name = (tp, "tp") if tp > 1 else (sp, "sp")
    need = n_stages * dp * inner
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    if inner > 1:
        arr = np.asarray(devices[:need]).reshape(dp, n_stages, inner)
        return Mesh(arr, ("dp", "stage", inner_name))
    arr = np.asarray(devices[:need]).reshape(dp, n_stages)
    return Mesh(arr, ("dp", "stage"))
