"""SPMD pipeline: the whole stage graph as ONE jitted program over a mesh.

The performance path (SURVEY.md §5.8, §7 step 3b). Where the host-driven
driver dispatches per-stage programs with device_put edges, this compiles the
*entire* pipeline — all stages, all microbatches — into a single XLA program
under `shard_map` over a `jax.sharding.Mesh`:

- mesh axes ('dp', 'stage'): 'stage' is the pipeline axis (the reference's
  rank, comm/p2p), 'dp' optionally shards the microbatch dimension (data
  parallelism within a stage — absent in the reference, SURVEY.md §2.4).
- Each device holds only its own stage's transformer blocks (parameters are
  stage-sharded; stages with fewer blocks are zero-padded and masked).
- One `lax.scan` over T = n_microbatches + n_stages - 1 "ticks" runs the
  fill/steady/drain schedule; the inter-stage edge is `lax.ppermute` over ICI
  — the collective-permute equivalent of the reference's gloo send/recv
  threads (p2p:155-258), with zero host involvement in steady state.
- Quantized edges: the payload is encoded to packed uint32 before the
  ppermute and decoded after, so only 32/bit of the activation bytes cross
  the interconnect (QuantPipe on the wire, reference runtime.py:73-119).

Constraints vs the host-driven path: partitions must be block-aligned (each
stage = whole transformer blocks). Mid-block (sublayer) cuts stream a 2-tuple
payload with shapes that differ per cut point, which would break the single
SPMD program; the host-driven driver handles those (SURVEY.md §7 hard parts).
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import ShardConfig, block_slices
from ..models.layers import TransformerConfig
from ..models.shard import FamilySpec, stack_blocks
from ..ops import quant as quant_ops

BlockRange = Tuple[int, int]


def partition_to_blocks(partition: Sequence[Tuple[int, int]]) -> List[BlockRange]:
    """Convert a sublayer partition to 0-based block ranges; reject mid-block cuts."""
    out = []
    for layer_start, layer_end in partition:
        slices = block_slices(layer_start, layer_end)
        if not all(s.is_full for s in slices):
            raise ValueError(
                f"SPMD pipeline requires block-aligned partitions; "
                f"[{layer_start}, {layer_end}] cuts mid-block (use the "
                f"host-driven pipeline for sublayer cuts)")
        out.append((slices[0].block_id, slices[-1].block_id))
    return out


def _pad_stack(stage_blocks: List[Any], max_b: int):
    """Stack per-stage block pytrees [n_i, ...] into [n_stages, max_b, ...]."""
    def pad(leaf):
        pad_width = [(0, max_b - leaf.shape[0])] + [(0, 0)] * (leaf.ndim - 1)
        return jnp.pad(leaf, pad_width)

    padded = [jax.tree_util.tree_map(pad, b) for b in stage_blocks]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *padded)


@dataclasses.dataclass
class SpmdPipeline:
    """Compiled SPMD pipeline over a ('dp', 'stage') mesh.

    Build with `build_spmd_pipeline`. Call `run(inputs)` with a stacked
    microbatch array [M, B, ...raw input dims...]; returns [M, B, ...out...].
    """
    family: FamilySpec
    cfg: TransformerConfig
    mesh: Mesh
    n_stages: int
    max_blocks: int
    params: Dict            # {'embed', 'final', 'blocks', 'n_blocks'}
    quant_bit: int = 0
    _compiled: Dict[Tuple, Any] = dataclasses.field(default_factory=dict)

    def run(self, inputs: jax.Array) -> jax.Array:
        key = (inputs.shape, str(inputs.dtype), self.quant_bit)
        fn = self._compiled.get(key)
        if fn is None:
            fn = self._build(inputs)
            self._compiled[key] = fn
        dp_spec = "dp" if self.mesh.shape.get("dp", 1) > 1 else None
        inputs = jax.device_put(inputs, NamedSharding(self.mesh, P(None, dp_spec)))
        return fn(self.params, inputs)

    # -- program construction ------------------------------------------

    def _build(self, inputs: jax.Array):
        family, cfg = self.family, self.cfg
        n_stages, max_b = self.n_stages, self.max_blocks
        quant_bit = self.quant_bit
        mesh = self.mesh
        n_ubatch = inputs.shape[0]
        n_ticks = n_ubatch + n_stages - 1
        dp = mesh.shape.get("dp", 1)

        # trace shapes: embedded hidden + final output
        embed_shape = jax.eval_shape(
            partial(family.embed, cfg=cfg), self.params["embed"], inputs[0])
        b_local = embed_shape.shape[0] // dp
        hidden_local = jax.ShapeDtypeStruct(
            (b_local,) + embed_shape.shape[1:], embed_shape.dtype)
        out_shape = jax.eval_shape(
            partial(family.finalize, cfg=cfg), self.params["final"],
            jnp.zeros(hidden_local.shape, hidden_local.dtype))

        def block_apply(bp, x):
            for sub in range(4):
                x = family.sublayer(bp, sub, x, cfg)
            return x

        def run_blocks(blocks, n_valid, x):
            def step(carry, xs):
                bp, j = xs
                out = jax.lax.cond(j < n_valid, lambda c: block_apply(bp, c),
                                   lambda c: c, carry)
                return out, None

            x, _ = jax.lax.scan(step, x, (blocks, jnp.arange(max_b)))
            return x

        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def encode(h):
            if quant_bit == 0:
                return h
            return quant_ops.tensor_encode_outerdim(h, quant_bit)

        def decode(e):
            if quant_bit == 0:
                return e
            return quant_ops.tensor_decode_outerdim(e)

        def permute_payload(payload):
            if n_stages == 1:
                return payload
            return jax.tree_util.tree_map(
                lambda t: jax.lax.ppermute(t, "stage", fwd_perm), payload)

        def spmd_body(params, stacked_inputs):
            # local views: blocks [1, max_b, ...] (stage-sharded), inputs
            # [M, B/dp, ...] (dp-sharded), embed/final replicated
            blocks = jax.tree_util.tree_map(lambda x: x[0], params["blocks"])
            n_valid = params["n_blocks"][0]
            stage = jax.lax.axis_index("stage")
            is_first = stage == 0
            is_last = stage == n_stages - 1

            # Embeddings for all microbatches, computed once per device.
            # Patch/word embedding is <2% of total FLOPs; doing it everywhere
            # avoids a second program region gated on stage index.
            embedded = jax.vmap(
                lambda u: family.embed(params["embed"], u, cfg))(stacked_inputs)

            zero_h = jnp.zeros(hidden_local.shape, hidden_local.dtype)
            outputs0 = jnp.zeros((n_ubatch,) + out_shape.shape, out_shape.dtype)

            def tick(carry, t):
                prev_enc, outputs = carry
                recv = decode(permute_payload(prev_enc))
                in_idx = jnp.clip(t, 0, n_ubatch - 1)
                x = jnp.where(is_first, embedded[in_idx], recv)
                h = run_blocks(blocks, n_valid, x)
                logits = family.finalize(params["final"], h, cfg)
                out_idx = t - (n_stages - 1)
                updated = jax.lax.dynamic_update_slice(
                    outputs, logits[None].astype(outputs.dtype),
                    (jnp.clip(out_idx, 0, n_ubatch - 1),)
                    + (0,) * len(out_shape.shape))
                valid = jnp.logical_and(out_idx >= 0, is_last)
                outputs = jnp.where(valid, updated, outputs)
                return (encode(h), outputs), None

            (_, outputs), _ = jax.lax.scan(
                tick, (encode(zero_h), outputs0), jnp.arange(n_ticks))
            # only the last stage wrote real outputs; fan them back out
            return jax.lax.psum(outputs, "stage")

        dp_spec = "dp" if dp > 1 else None
        in_specs = (
            {
                "embed": P(),
                "final": P(),
                "blocks": jax.tree_util.tree_map(
                    lambda _: P("stage"), self.params["blocks"]),
                "n_blocks": P("stage"),
            },
            P(None, dp_spec),
        )
        out_spec = P(None, dp_spec)
        fn = jax.jit(jax.shard_map(spmd_body, mesh=mesh, in_specs=in_specs,
                                   out_specs=out_spec, check_vma=False))
        return fn


def build_spmd_pipeline(family: FamilySpec, cfg: TransformerConfig,
                        partition: Sequence[Tuple[int, int]],
                        stage_params: Sequence[Dict], mesh: Mesh,
                        quant_bit: int = 0) -> SpmdPipeline:
    """Assemble an `SpmdPipeline` from per-stage shard parameter pytrees.

    `stage_params[i]` is the pytree built by a family loader for stage i's
    `ShardConfig` (block-aligned). Stage 0 must carry 'embeddings', the last
    stage 'final'; per-stage 'blocks' stacks are zero-padded to the deepest
    stage and masked at run time.
    """
    n_stages = len(partition)
    if mesh.shape["stage"] != n_stages:
        raise ValueError(f"mesh 'stage' axis {mesh.shape['stage']} != "
                         f"{n_stages} pipeline stages")
    partition_to_blocks(partition)  # validates block alignment

    blocks_list = []
    n_blocks = []
    for i, p in enumerate(stage_params):
        if "blocks" not in p:
            raise ValueError(f"stage {i} has no full blocks; SPMD pipeline "
                             f"requires block-aligned partitions")
        blocks_list.append(p["blocks"])
        n_blocks.append(jax.tree_util.tree_leaves(p["blocks"])[0].shape[0])
    max_b = max(n_blocks)

    params = {
        "embed": stage_params[0]["embeddings"],
        "final": stage_params[-1]["final"],
        "blocks": _pad_stack(blocks_list, max_b),
        "n_blocks": jnp.asarray(n_blocks, jnp.int32),
    }
    # place parameters: blocks stage-sharded, embed/final replicated
    params = {
        "embed": jax.device_put(params["embed"],
                                NamedSharding(mesh, P())),
        "final": jax.device_put(params["final"], NamedSharding(mesh, P())),
        "blocks": jax.device_put(params["blocks"],
                                 NamedSharding(mesh, P("stage"))),
        "n_blocks": jax.device_put(params["n_blocks"],
                                   NamedSharding(mesh, P("stage"))),
    }
    return SpmdPipeline(family=family, cfg=cfg, mesh=mesh, n_stages=n_stages,
                        max_blocks=max_b, params=params)


def make_pipeline_mesh(n_stages: int, dp: int = 1,
                       devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ('dp', 'stage') mesh: stage axis contiguous so ppermute edges
    ride neighboring ICI links."""
    if devices is None:
        devices = jax.devices()
    need = n_stages * dp
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(dp, n_stages)
    return Mesh(arr, ("dp", "stage"))
