"""Autoregressive KV-cache decoding through the pipeline (GPT-2 family).

NEW capability beyond the reference (whose model list is encoder-only and
whose runtime is single-shot batch inference). TPU-first design:

- **Static shapes everywhere**: the KV cache is a fixed [n_blocks, B,
  max_len, H, Dh] buffer per stage; the current length rides as a traced
  scalar `pos`, future positions are masked. One compiled prefill program +
  one compiled decode-step program per stage serve the whole generation —
  no per-step recompilation (the reference's dynamic-shape wire protocol
  has no answer to this; SURVEY.md §7 'hard parts').
- **Block-aligned pipeline stages**: each stage holds its blocks' cache,
  consumes the previous stage's hidden state for the current token, and
  returns its own — the same stage-edge discipline as the forward
  pipeline (quantizable, device-placeable). Autoregression serializes
  decode steps, so parallelism comes from the batch dimension; stages
  still split the model across devices for memory capacity.
- Attention over the cache streams as one [B, H, 1, T_max] masked matmul —
  MXU-shaped, no gather.

Greedy decoding matches HF `GPT2LMHeadModel.generate(do_sample=False)`
token-for-token (tests/test_decode.py).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..models import ShardConfig, plan_shard
from ..models.layers import (TransformerConfig, dense, gelu_new, layer_norm)

Cache = Dict[str, jax.Array]   # {'k': [L, B, T, H, Dh], 'v': [L, B, T, H, Dh]}


def init_cache(cfg: TransformerConfig, n_blocks: int, batch: int,
               max_len: int, dtype=jnp.float32) -> Cache:
    """Zeroed stacked KV cache for `n_blocks` blocks."""
    shape = (n_blocks, batch, max_len, cfg.num_attention_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def _qkv(p: Dict, normed: jax.Array, cfg: TransformerConfig):
    b, s, _ = normed.shape
    h, hd = cfg.num_attention_heads, cfg.head_dim
    return (dense(p["q"], normed).reshape(b, s, h, hd),
            dense(p["k"], normed).reshape(b, s, h, hd),
            dense(p["v"], normed).reshape(b, s, h, hd))


def _attend(q: jax.Array, k: jax.Array, v: jax.Array, keep: jax.Array,
            cfg: TransformerConfig) -> jax.Array:
    """Masked attention of q [B,S,H,Dh] over k/v [B,T,H,Dh]; `keep`
    [S, T] marks key positions each query may attend to."""
    b, s, h, hd = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(hd))
    scores = jnp.where(keep[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32).astype(q.dtype)
    return ctx.reshape(b, s, h * hd)


def _block_step(p: Dict, x: jax.Array, k_cache: jax.Array,
                v_cache: jax.Array, pos, cfg: TransformerConfig,
                prefill: bool) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One GPT-2 block over current token(s) with cache read/update.

    Prefill: x is the full prompt [B, S, D] written at positions [0, S);
    decode: x is one token [B, 1, D] written at position `pos`."""
    t_max = k_cache.shape[1]
    normed = layer_norm(p["ln_before"], x, cfg.layer_norm_eps)
    q, k_new, v_new = _qkv(p, normed, cfg)
    if prefill:
        s = x.shape[1]
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k_new.astype(k_cache.dtype), (0, 0, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v_new.astype(v_cache.dtype), (0, 0, 0, 0))
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (s, t_max), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, t_max), 1)
        keep = k_pos <= q_pos          # causal within the prompt
    else:
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k_new.astype(k_cache.dtype), (0, pos, 0, 0))
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v_new.astype(v_cache.dtype), (0, pos, 0, 0))
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (1, t_max), 1)
        keep = k_pos <= pos            # attend to [0, pos]
    ctx = _attend(q, k_cache.astype(q.dtype), v_cache.astype(q.dtype),
                  keep, cfg)
    x = dense(p["attn_out"], ctx) + x
    normed = layer_norm(p["ln_after"], x, cfg.layer_norm_eps)
    x = dense(p["mlp_down"], gelu_new(dense(p["mlp_up"], normed))) + x
    return x, k_cache, v_cache


def _stage_blocks(params: Dict) -> jax.Array:
    """The stacked blocks pytree of a decode stage (block-aligned shard)."""
    blocks = params.get("blocks")
    if blocks is None:
        raise ValueError("decode stages must contain full blocks "
                         "(block-aligned partition)")
    if isinstance(blocks, (tuple, list)):  # unrolled layout -> restack
        blocks = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *blocks)
    return blocks


def _run_blocks(blocks, x, cache: Cache, pos, cfg: TransformerConfig,
                prefill: bool) -> Tuple[jax.Array, Cache]:
    def body(carry, xs):
        bp, kc, vc = xs
        y, kc, vc = _block_step(bp, carry, kc, vc, pos, cfg, prefill)
        return y, (kc, vc)

    x, (ks, vs) = jax.lax.scan(body, x, (blocks, cache["k"], cache["v"]))
    return x, {"k": ks, "v": vs}


def make_stage_fns(family, cfg: TransformerConfig, shard_config: ShardConfig):
    """(prefill_fn, decode_fn) for one block-aligned pipeline stage.

    prefill_fn(params, data, cache)        -> (out, cache)   data: ids|hidden
    decode_fn(params, data, cache, pos)    -> (out, cache)   data: ids|hidden

    First stage embeds token ids (decode positions offset by `pos`); last
    stage applies the final LN + LM head and returns per-token logits.
    """
    plan = plan_shard(shard_config)
    if plan.head is not None or plan.tail is not None:
        raise ValueError("decode requires a block-aligned partition "
                         f"(layers [{shard_config.layer_start}, "
                         f"{shard_config.layer_end}] cut mid-block)")

    def run(params, data, cache, pos, prefill):
        if shard_config.is_first:
            if prefill:
                data = family.embed(params["embeddings"], data, cfg)
            else:
                wpe = jax.lax.dynamic_slice_in_dim(
                    params["embeddings"]["wpe"], pos, 1)
                data = jnp.take(params["embeddings"]["wte"], data,
                                axis=0) + wpe[None]
        data, cache = _run_blocks(_stage_blocks(params), data, cache, pos,
                                  cfg, prefill)
        if shard_config.is_last:
            data = family.finalize(params["final"], data, cfg)
        return data, cache

    prefill_fn = jax.jit(partial(run, pos=0, prefill=True))
    decode_fn = jax.jit(partial(run, prefill=False))
    return prefill_fn, decode_fn


class DecodePipeline:
    """Host-driven pipelined greedy decoding over block-aligned stages.

    `stage_params[i]` are forward-pipeline shard params (the same pytrees
    `module_shard_factory` builds); caches are per-stage. Decode steps are
    serial (autoregression), so batch is the throughput axis; stages
    partition the model across devices for capacity, exactly like the
    forward pipeline. `devices` optionally places each stage (device_put,
    mirroring the host pipeline driver).
    """

    def __init__(self, family, cfg: TransformerConfig,
                 partition: Sequence[Tuple[int, int]],
                 stage_params: Sequence[Dict], max_len: int,
                 devices: Optional[Sequence] = None, dtype=jnp.float32):
        total = 4 * cfg.num_hidden_layers
        expect = 1
        for l, r in partition:
            if l != expect:
                raise ValueError(f"partition {list(partition)} does not "
                                 f"contiguously cover [1, {total}]")
            expect = r + 1
        if expect != total + 1:
            raise ValueError(f"partition {list(partition)} does not "
                             f"contiguously cover [1, {total}]")
        if cfg.max_position_embeddings and max_len > cfg.max_position_embeddings:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"{cfg.max_position_embeddings} positions")
        self.cfg = cfg
        self.max_len = max_len
        self.stages = []
        for i, (l, r) in enumerate(partition):
            sc = ShardConfig(l, r, is_first=l == 1, is_last=r == total)
            pre, dec = make_stage_fns(family, cfg, sc)
            params = dict(stage_params[i])
            # restack an unrolled block layout ONCE here, not per traced call
            params["blocks"] = _stage_blocks(params)
            if devices is not None:
                params = jax.device_put(params, devices[i])
            n_blocks = (r - l + 1) // 4
            self.stages.append({"prefill": pre, "decode": dec,
                                "params": params, "n_blocks": n_blocks,
                                "device": None if devices is None
                                else devices[i]})
        self.dtype = dtype

    def _fresh_caches(self, batch: int) -> List[Cache]:
        caches = []
        for st in self.stages:
            c = init_cache(self.cfg, st["n_blocks"], batch, self.max_len,
                           self.dtype)
            if st["device"] is not None:
                c = jax.device_put(c, st["device"])
            caches.append(c)
        return caches

    def generate(self, ids, new_tokens: int):
        """Greedy-decode `new_tokens` continuations of prompt `ids` [B, S].

        Returns [B, S + new_tokens] token ids (prompt included)."""
        ids = jnp.asarray(ids, jnp.int32)
        batch, prompt_len = ids.shape
        if new_tokens <= 0:
            return ids
        if prompt_len + new_tokens > self.max_len:
            raise ValueError(f"prompt {prompt_len} + {new_tokens} new tokens "
                             f"exceeds max_len {self.max_len}")
        caches = self._fresh_caches(batch)
        data = ids
        for i, st in enumerate(self.stages):
            if st["device"] is not None:
                data = jax.device_put(data, st["device"])
            data, caches[i] = st["prefill"](st["params"], data, caches[i])
        tokens = [jnp.argmax(data[:, prompt_len - 1], axis=-1)]
        for step in range(1, new_tokens):
            pos = prompt_len + step - 1
            data = tokens[-1][:, None]
            for i, st in enumerate(self.stages):
                if st["device"] is not None:
                    data = jax.device_put(data, st["device"])
                data, caches[i] = st["decode"](st["params"], data, caches[i],
                                               pos)
            tokens.append(jnp.argmax(data[:, 0], axis=-1))
        return jnp.concatenate([ids, jnp.stack(tokens, axis=1)], axis=1)
